"""Rebuild missing EC shards from surviving ones.

Reference: weed/storage/erasure_coding/ec_encoder.go generateMissingEcFiles
(:147-379). The correctness envelope preserved here (the reference's
accumulated bug-fix scar tissue, SURVEY.md hard part (c)):

- bitrot sidecar verify-and-exclude: present-but-corrupt shards are
  reclassified as missing and regenerated, never fed to Reed-Solomon;
- fail-closed rules: malformed sidecar refuses; >parity mismatches means
  the *sidecar* is suspect (wholesale-mismatch guard) and refuses;
  fewer than k verified-good shards refuses;
- regenerated shards are verified against the sidecar before publish;
- temp file + fsync + atomic rename (+ dir fsync) publication; corrupt
  originals replaced in place only after their replacement verifies.

Performance (PR 2): the rebuild runs the shared 3-stage pipeline
(ec/pipeline.py) — surviving-shard reads / Reed-Solomon apply / fused
native write+CRC — and the k SOURCE shards are sidecar-verified INLINE
by the read stage (CRC rolled while the batch is cache-hot), deleting
the separate whole-shard verification read pass the serial
implementation paid up front. Only the non-source remainder still gets
a dedicated verify, in parallel. A source whose inline CRC mismatches
is re-checked from disk: confirmed rot is reclassified corrupt and the
rebuild restarts without it (the verify-and-exclude envelope); a clean
disk copy means transient read corruption, which fails closed.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .. import faults
from ..ops import gf256
from ..utils import trace
from ..utils.crc import crc32c
from .backend import RSBackend, _decode_coeffs, get_backend
from .bitrot import BitrotError, BitrotProtection
from .context import BITROT_BLOCK_SIZE, DEFAULT_EC_CONTEXT, ECContext, ECError
from .decoder import _fsync_dir
from .encoder import DEFAULT_BATCH, WIDE_STREAM_BYTES
from .pipeline import PyShardSink, make_shard_sink, run_pipeline, run_staged_apply
from .volume_info import VolumeInfo


class _SourceReadError(Exception):
    """A source shard failed mid-pipeline (unreadable/short read)."""

    def __init__(self, shards: list[int]):
        super().__init__(f"source shards {shards} unreadable")
        self.shards = shards


class _BlockCrcRoller:
    """Rolling per-block CRC32C over numpy rows, zero-copy (the inline
    source-verification half of the fused read stage)."""

    def __init__(self, block_size: int):
        self.block_size = block_size
        self.crcs: list[int] = []
        self._crc = 0
        self._filled = 0

    def update(self, arr: np.ndarray) -> None:
        pos, n = 0, len(arr)
        while pos < n:
            take = min(self.block_size - self._filled, n - pos)
            self._crc = crc32c(arr[pos : pos + take], self._crc)
            self._filled += take
            pos += take
            if self._filled == self.block_size:
                self.crcs.append(self._crc)
                self._crc = 0
                self._filled = 0

    def finish(self) -> list[int]:
        if self._filled:
            self.crcs.append(self._crc)
            self._crc = 0
            self._filled = 0
        return self.crcs


def _pread_exact(fd: int, buf: np.ndarray, offset: int) -> None:
    """Fill `buf` from fd at `offset` IN PLACE; short read raises."""
    mv = memoryview(buf)
    filled = 0
    want = len(buf)
    while filled < want:
        got = os.preadv(fd, [mv[filled:]], offset + filled)
        if got == 0:
            raise OSError(f"short shard read at offset {offset + filled}")
        filled += got


def rebuild_ec_files(
    base: str,
    ctx: ECContext | None = None,
    backend: RSBackend | None = None,
    unsafe_ignore_sidecar: bool = False,
    batch_size: int = DEFAULT_BATCH,
    only_shards: list[int] | None = None,
    staged: bool = True,
    priority: str = "recovery",
    scheduler=None,
) -> list[int]:
    """Regenerate missing/corrupt shard files; returns regenerated ids.

    `only_shards` restricts which ABSENT shards are regenerated (a
    subset-holding server must not mint local copies of shards placed on
    peers); present-but-corrupt shards are always replaced regardless.

    `staged` (default) dispatches each batch through the backend's
    staged apply (async H2D + device compute, D2H forced in the writer
    thread) so a device rebuild overlaps transfer with compute like
    `encode_staged`; False keeps the synchronous per-batch `apply` —
    bit-identical by construction, kept for the bench's staged-vs-sync
    comparison.

    `priority` tags the staged stream's class on the shared per-chip
    scheduler (ec/device_queue.py): "recovery" by default (rebuild and
    decode self-heal restore redundancy behind serving traffic); the
    scrub daemon passes "scrub" so background hygiene yields to both.

    `scheduler` is the QueueScope whose placement/admission config the
    staged stream runs under (None = the process-wide default scope);
    on a multi-chip backend the rebuild stream is placed whole onto the
    least-loaded chip (ec/chip_pool.py) instead of column-slicing
    across the pod.
    """
    # Sidecar first: it records the shard ratio too, which backs up the
    # .vif for config resolution and cross-checks it.
    prot: BitrotProtection | None = None
    ecsum = base + ".ecsum"
    if os.path.exists(ecsum):
        try:
            prot = BitrotProtection.load(ecsum)
        except BitrotError as e:
            if not unsafe_ignore_sidecar:
                raise ECError(
                    f"bitrot sidecar for {base} is malformed ({e}); refusing "
                    f"to rebuild (pass unsafe_ignore_sidecar to override)"
                ) from e
            prot = None

    if ctx is None:
        vif_path = base + ".vif"
        if os.path.exists(vif_path):
            # .vif present but unreadable fails closed: silently falling
            # back to 10+4 would rebuild a custom-ratio volume with the
            # wrong layout (reference RebuildEcFiles).
            vi = VolumeInfo.load(vif_path)
            ctx = vi.ec_ctx
        if ctx is None and prot is not None:
            ctx = prot.ctx
        if ctx is None:
            ctx = DEFAULT_EC_CONTEXT
    if prot is not None and prot.ctx != ctx:
        if not unsafe_ignore_sidecar:
            raise ECError(
                f"bitrot sidecar for {base} records ratio {prot.ctx} but the "
                f"volume config says {ctx}; refusing to rebuild"
            )
        prot = None
    # Backend resolution is DEFERRED until a reconstruction target
    # exists: the common no-op case (scrub of a healthy volume, decode's
    # verify pass with all shards present) is pure CPU CRC work, and
    # get_backend("auto") on a TPU host may initialize the device stack
    # — which on a dead relay hangs (see get_backend's warning).

    total, k = ctx.total, ctx.data_shards
    present = [i for i in range(total) if os.path.exists(base + ctx.to_ext(i))]
    missing = [i for i in range(total) if i not in present]
    if only_shards is not None:
        missing = [i for i in missing if i in only_shards]

    # Flight-recorder root for the whole rebuild op (a child when a
    # decode/peer-rebuild/RPC span is active in this thread).
    sp = trace.start(
        "ec.rebuild", name=os.path.basename(base), base=base,
        present=len(present), missing=sorted(missing), priority=priority,
    )
    try:
        return _rebuild_ec_files_traced(
            base, ctx, backend, unsafe_ignore_sidecar, batch_size,
            prot, present, missing, staged, priority, scheduler, sp,
        )
    finally:
        trace.finish(sp)


def _rebuild_ec_files_traced(
    base, ctx, backend, unsafe_ignore_sidecar, batch_size,
    prot, present, missing, staged, priority, scheduler, sp,
) -> list[int]:
    total, k = ctx.total, ctx.data_shards

    # An armed fault registry routes through the PR1-faithful byte path:
    # mutating faults need materialized bytes at the read/write seams,
    # and the chaos contract (upfront verify of every present shard,
    # fail-closed on mid-rebuild read corruption) is asserted against
    # that shape. Disarmed — i.e. production — takes the fused path.
    chaos = faults.active()
    present0 = len(present)
    all_corrupt: list[int] = []
    verified_ok: set[int] = set()

    def _verify_full(ids: list[int]) -> list[int]:
        """Whole-shard sidecar verification (parallel across shards —
        each is an independent read+CRC stream, so N shards drain N
        queues instead of serializing)."""
        if prot is None or not ids:
            return []

        def check(i: int) -> bool:
            try:
                return bool(
                    prot.verify_shard_file(
                        base + ctx.to_ext(i), i, stop_early=True
                    )
                )
            except OSError:
                return True  # unreadable = untrustworthy RS input

        with trace.stage(sp, "verify"):
            if len(ids) == 1:
                flags = [check(ids[0])]
            else:
                with ThreadPoolExecutor(max_workers=min(len(ids), 8)) as ex:
                    flags = list(ex.map(check, ids))
        bad = [i for i, f in zip(ids, flags) if f]
        verified_ok.update(i for i in ids if i not in bad)
        return bad

    def _reclassify(new_bad: list[int]) -> None:
        """Corrupt bookkeeping + the PR1 fail-closed guards."""
        for i in new_bad:
            if i not in all_corrupt:
                all_corrupt.append(i)
        if unsafe_ignore_sidecar:
            return  # tolerate corrupt inputs, as the flag promises
        if len(all_corrupt) > ctx.parity_shards:
            raise ECError(
                f"bitrot sidecar suspect for {base}: {len(all_corrupt)}/"
                f"{present0} present shards mismatch (> parity "
                f"{ctx.parity_shards}); refusing to rebuild"
            )
        if present0 - len(all_corrupt) < k:
            raise ECError(
                f"bitrot: only {present0 - len(all_corrupt)} verified-good "
                f"shards for {base}, need {k} data shards"
            )
        for i in new_bad:
            if i in present:
                present.remove(i)
                missing.append(i)

    if prot is not None and chaos:
        # PR1 path: verify-and-exclude every present shard before any
        # reconstruction input is chosen.
        _reclassify(_verify_full(list(present)))

    while True:
        if len(present) < k:
            raise ECError(
                f"not enough shards to rebuild {base}: found {len(present)}, "
                f"need {k}, missing {sorted(missing)}"
            )
        if not missing:
            # Nothing absent — but a present shard may still be rotten
            # on disk (the verify-and-exclude contract repairs it in
            # place). With no reconstruction stream to fold the check
            # into, every still-unverified shard gets the dedicated
            # parallel verify.
            if prot is not None and not chaos and not unsafe_ignore_sidecar:
                bad = _verify_full(
                    [i for i in present if i not in verified_ok]
                )
                if bad:
                    _reclassify(bad)
                    continue
            return []

        sizes = {i: os.path.getsize(base + ctx.to_ext(i)) for i in present}
        if prot is not None and not chaos and not unsafe_ignore_sidecar:
            # size-vs-sidecar is the cheap half of verification
            # (truncation/growth is corruption) — catch it before the
            # stream even starts.
            size_bad = [
                i for i in present if sizes[i] != prot.shard_sizes[i]
            ]
            if size_bad:
                _reclassify(size_bad)
                continue
        shard_size = max(sizes.values())
        if [i for i, s in sizes.items() if s != shard_size]:
            raise ECError(f"present shards have unequal sizes: {sizes}")

        src = sorted(present)[:k]
        if prot is not None and not chaos and not unsafe_ignore_sidecar:
            # Non-source shards don't flow through the pipelined read,
            # so they get the dedicated (parallel) verify; sources are
            # verified inline below.
            bad = _verify_full(
                [i for i in present if i not in src and i not in verified_ok]
            )
            if bad:
                _reclassify(bad)
                continue

        targets = sorted(missing)
        if backend is None:
            backend = get_backend("auto", ctx.data_shards, ctx.parity_shards)
        bad_src = _attempt_rebuild(
            base, ctx, backend, prot, src, targets, shard_size,
            batch_size, chaos,
            inline_verify=(
                prot is not None and not chaos and not unsafe_ignore_sidecar
            ),
            verified_ok=verified_ok,
            staged=staged,
            priority=priority,
            scheduler=scheduler,
            span=sp,
        )
        if bad_src:
            # Confirmed on-disk rot in a source: verify-and-exclude says
            # reclassify it as missing and rebuild without it.
            _reclassify(bad_src)
            continue
        return targets


def _attempt_rebuild(
    base: str,
    ctx: ECContext,
    backend: RSBackend,
    prot: BitrotProtection | None,
    src: list[int],
    targets: list[int],
    shard_size: int,
    batch_size: int,
    chaos: bool,
    inline_verify: bool,
    verified_ok: set[int] | None = None,
    staged: bool = True,
    priority: str = "recovery",
    scheduler=None,
    span=None,
) -> list[int]:
    """One pipelined reconstruction attempt. Publishes and returns []
    on success; returns confirmed-corrupt source ids for the caller to
    exclude and retry (inline-clean sources are recorded in
    `verified_ok` so a retry never re-reads them); raises fail-closed
    otherwise."""
    k = ctx.data_shards
    fds = {i: os.open(base + ctx.to_ext(i), os.O_RDONLY) for i in src}
    tmp_paths = {i: base + ctx.to_ext(i) + ".rebuilding" for i in targets}
    # buffering=0: the fused native sink writes via raw fds; the Python
    # fallback writes whole >=1MiB batches where a userspace buffer
    # only adds a copy.
    outs = {i: open(p, "wb", buffering=0) for i, p in tmp_paths.items()}
    crc_block = prot.block_size if prot is not None else BITROT_BLOCK_SIZE
    # The fused native sink (sn_sink_append) rolls the sidecar-granularity
    # CRC while the reconstructed bytes are cache-hot and writes straight
    # from the backend's output buffers — no per-batch tobytes(). A
    # byte-mutating fault needs materialized bytes, so an armed registry
    # routes through the Python sink (the chaos tests' semantic path).
    sink = make_shard_sink(
        list(outs.values()), block_size=crc_block, prefer_fused=not chaos
    )
    use_bytes_path = isinstance(sink, PyShardSink)
    # Native read plane (ec/native_io.py): the k source rows land via
    # one batched pread per batch, and the inline source verification
    # CRC rolls on the C++ side in the same cache-hot pass — the Python
    # _BlockCrcRoller stays as the bit-identical fallback (and the
    # chaos path keeps its byte seams below).
    from . import native_io

    use_native = not chaos and native_io.enabled()
    rollers = None
    ncrc_state = ncrc_filled = None
    ncrc_lists: list[list[int]] | None = None
    if inline_verify:
        if use_native:
            ncrc_state = np.zeros(k, np.uint32)
            ncrc_filled = np.zeros(k, np.uint64)
            ncrc_lists = [[] for _ in range(k)]
        else:
            rollers = {i: _BlockCrcRoller(crc_block) for i in src}

    if chaos:
        # PR1-faithful byte path: per-shard pread -> fault mutate ->
        # dict reconstruct -> (mutate ->) write.
        def produce():
            for off in range(0, shard_size, batch_size):
                width = min(batch_size, shard_size - off)
                block = {
                    i: np.frombuffer(
                        faults.mutate(
                            "ec.rebuild.read_shard",
                            os.pread(fds[i], width, off),
                            base=base, shard=i, offset=off,
                        ),
                        dtype=np.uint8,
                    )
                    for i in src
                }
                if any(len(b) != width for b in block.values()):
                    raise ECError(f"short shard read at offset {off}")
                yield off, block

        def transform(item):
            off, block = item
            return off, backend.reconstruct(block, want=targets)

        def consume(item):
            off, rec = item
            rows: list = []
            for i in targets:
                row = np.ascontiguousarray(rec[i], dtype=np.uint8)
                if use_bytes_path:
                    rows.append(
                        faults.mutate(
                            "ec.rebuild.shard_bytes", row.tobytes(),
                            base=base, shard=i, offset=off,
                        )
                    )
                else:
                    rows.append(row)
            sink.append_rows(rows)

    else:
        # Fused path: read all k sources into one (k, width) matrix
        # (inline CRC rolled while cache-hot), then a single
        # precomputed-coefficient GF(256) apply per batch — no per-batch
        # matrix inversion, no stack copy, no dict plumbing. The staged
        # variant dispatches that apply through the backend's async
        # hooks (run_staged_apply), so on a device batch N computes
        # while N+1 uploads and N-1 drains to disk.
        rs = gf256.ReedSolomon(ctx.data_shards, ctx.parity_shards)
        coeffs = _decode_coeffs(rs.matrix, k, tuple(targets), tuple(src))

        def produce():
            src_fds = [fds[i] for i in src]
            out_crcs = out_counts = None
            if ncrc_lists is not None:
                out_crcs = np.empty(
                    (k, batch_size // crc_block + 2), np.uint32
                )
                out_counts = np.empty(k, np.int32)
            for off in range(0, shard_size, batch_size):
                width = min(batch_size, shard_size - off)
                buf = np.empty((k, width), dtype=np.uint8)
                if use_native:
                    nxt = off + width
                    if nxt < shard_size:
                        nw = min(batch_size, shard_size - nxt)
                        for fd in src_fds:
                            native_io.prefetch(fd, nxt, nw)
                    try:
                        native_io.read_batch(
                            src_fds, [off] * k, buf, pad_eof=False,
                            granule=crc_block if ncrc_lists is not None else 0,
                            crc_state=ncrc_state, filled_state=ncrc_filled,
                            out_crcs=out_crcs, out_counts=out_counts,
                        )
                    except OSError as e:
                        raise _SourceReadError(
                            [src[getattr(e, "sn_row", 0)]]
                        ) from e
                    if ncrc_lists is not None:
                        for row in range(k):
                            c = int(out_counts[row])
                            ncrc_lists[row].extend(
                                int(x) for x in out_crcs[row, :c]
                            )
                else:
                    for row, i in enumerate(src):
                        try:
                            _pread_exact(fds[i], buf[row], off)
                        except OSError as e:
                            raise _SourceReadError([i]) from e
                        if rollers is not None:
                            rollers[i].update(buf[row])
                yield off, buf

        def transform(item):
            off, buf = item
            return off, backend.apply(coeffs, buf)

        def consume(_off, out):
            out = np.ascontiguousarray(out, dtype=np.uint8)
            sink.append_rows([out[p] for p in range(len(targets))])

    def _cleanup_temps() -> None:
        for f in outs.values():
            f.close()
        for p in tmp_paths.values():
            if os.path.exists(p):
                os.unlink(p)

    def _confirm_from_disk(suspects: list[int]) -> list[int]:
        """Re-verify suspect sources from disk: confirmed rot is
        excludable; a clean disk copy means the PIPELINE's read was
        transiently corrupted and publishing anything would launder it."""
        confirmed, transient = [], []
        with trace.stage(span, "verify"):
            for i in suspects:
                try:
                    still_bad = bool(
                        prot.verify_shard_file(
                            base + ctx.to_ext(i), i, stop_early=True
                        )
                    )
                except OSError:
                    still_bad = True
                (confirmed if still_bad else transient).append(i)
        if transient:
            raise ECError(
                f"source shards {transient} for {base} failed read-time "
                f"sidecar verification but verify clean on disk (transient "
                f"read corruption); refusing to publish"
            )
        return confirmed

    try:
        # Shared 3-stage overlap (ec/pipeline.py): surviving-shard reads
        # / Reed-Solomon reconstruct / fused write+CRC of the
        # regenerated shards — batch N reconstructs while N+1 is read
        # and N-1 drains to disk, same shape as the encode path. The
        # staged fused path additionally overlaps H2D/compute/D2H inside
        # the reconstruct stage (device dispatch in the calling thread,
        # result forced in the writer thread).
        join_timeout = 60.0 + 4.0 * batch_size / (16 << 20)
        if chaos or not staged:
            run_pipeline(
                produce,
                transform,
                consume if chaos else (lambda item: consume(*item)),
                join_timeout=join_timeout,
                describe="ec rebuild pipeline",
                span=span,
                stage_names=("disk_read", "reconstruct", "write_sink"),
            )
        else:
            run_staged_apply(
                backend,
                coeffs,
                produce,
                consume,
                join_timeout=join_timeout,
                describe="ec rebuild pipeline",
                priority=priority,
                scheduler=scheduler,
                span=span,
                # total stream cost for least-loaded routing: every
                # target row spans the whole shard extent
                cost_hint=len(targets) * shard_size,
                # a lone huge rebuild on an idle pod keeps the mesh
                # like a wide encode does — pinning it to one chip
                # would multiply MTTR exactly while redundancy is
                # reduced; same source-bytes threshold as encode
                wide=k * shard_size >= WIDE_STREAM_BYTES,
            )
    except _SourceReadError as e:
        _cleanup_temps()
        if inline_verify:
            return e.shards  # unreadable = untrustworthy; exclude + retry
        # No exclusion machinery active (no sidecar, or
        # unsafe_ignore_sidecar): the caller's _reclassify would not
        # remove the shard and the identical attempt would spin forever
        # — propagate instead, like the serial implementation did.
        raise ECError(str(e)) from e
    except BaseException:
        _cleanup_temps()
        raise
    finally:
        for fd in fds.values():
            os.close(fd)

    # --- inline source verification verdict (fast path) -------------------
    if rollers is not None or ncrc_lists is not None:
        if ncrc_lists is not None:
            # flush partial-tail CRC state (the native roller's finish)
            for row in range(k):
                if ncrc_filled[row]:
                    ncrc_lists[row].append(int(ncrc_state[row]))
                    ncrc_filled[row] = 0
            got = {i: ncrc_lists[row] for row, i in enumerate(src)}
        else:
            got = {i: rollers[i].finish() for i in src}
        suspects = [i for i in src if got[i] != prot.shard_crcs[i]]
        if verified_ok is not None:
            # the inline roller IS the block-CRC check _verify_full
            # performs — a retry after an exclusion must not re-read
            # the sources that just verified clean
            verified_ok.update(i for i in src if i not in suspects)
        if suspects:
            _cleanup_temps()
            return _confirm_from_disk(suspects)

    try:
        # Crash window: temp .rebuilding files written, not yet durable.
        faults.fire("ec.rebuild.before_fsync", base=base)
        with trace.stage(span, "fsync_publish"):
            for f in outs.values():
                f.flush()
                os.fsync(f.fileno())
    except BaseException:
        _cleanup_temps()
        raise

    for f in outs.values():
        f.close()

    # --- verify regenerated shards against the sidecar (fail closed) -----
    if prot is not None:
        out_sizes = sink.sizes
        out_crcs = sink.block_crcs()
        for pos, i in enumerate(targets):
            if (
                out_sizes[pos] != prot.shard_sizes[i]
                or out_crcs[pos] != prot.shard_crcs[i]
            ):
                for p in tmp_paths.values():
                    if os.path.exists(p):
                        os.unlink(p)
                raise ECError(
                    f"regenerated shard {i} for {base} fails sidecar "
                    f"verification; refusing to publish"
                )

    # Crash window: temps durable + sidecar-verified, renames pending. A
    # crash here (or between renames) leaves a mix of published shards
    # and .rebuilding temps; a restarted rebuild regenerates the rest.
    faults.fire("ec.rebuild.before_rename", base=base)
    with trace.stage(span, "fsync_publish"):
        for i in targets:
            os.replace(tmp_paths[i], base + ctx.to_ext(i))
            faults.fire("ec.rebuild.after_rename", base=base, shard=i)
        _fsync_dir(base + ".dat")
    return []
