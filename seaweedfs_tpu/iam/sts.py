"""STS: temporary credentials via AssumeRole.

Reference: weed/iam/sts (sts_service.go AssumeRole* flows) collapsed to
the self-hosted form: roles are named bundles of policy documents; an
identity whose policies allow ``sts:AssumeRole`` on the role's ARN can
mint short-lived credentials (ASIA… access key + session token) that
the S3 gateway verifies like any other identity, plus token expiry and
the x-amz-security-token header check.
"""

from __future__ import annotations

import hmac
import hashlib
import os
import threading
import time
from dataclasses import dataclass, field

from .policy import evaluate_policies


@dataclass
class Role:
    name: str
    policies: list[dict] = field(default_factory=list)
    # principals allowed to assume (access key ids or "*"); evaluated
    # IN ADDITION to the caller's own sts:AssumeRole policy grant
    trusted: list[str] = field(default_factory=lambda: ["*"])

    @property
    def arn(self) -> str:
        return f"arn:aws:iam:::role/{self.name}"


@dataclass
class TempCredential:
    access_key: str
    secret_key: str
    session_token: str
    role: Role
    expires_at: float

    @property
    def expired(self) -> bool:
        return time.time() >= self.expires_at


class StsService:
    MAX_DURATION = 12 * 3600
    MIN_DURATION = 900

    def __init__(self):
        self._roles: dict[str, Role] = {}
        self._creds: dict[str, TempCredential] = {}
        self._lock = threading.Lock()

    # --------------------------------------------------------------- roles

    def put_role(self, role: Role) -> None:
        with self._lock:
            self._roles[role.name] = role

    def get_role(self, name: str) -> Role | None:
        return self._roles.get(name)

    # ------------------------------------------------------------- assume

    def assume_role(
        self,
        caller_access_key: str,
        caller_policies: list[dict],
        role_name: str,
        duration: int = 3600,
    ) -> TempCredential:
        role = self._roles.get(role_name)
        if role is None:
            raise PermissionError(f"no such role {role_name!r}")
        if "*" not in role.trusted and caller_access_key not in role.trusted:
            raise PermissionError(f"{caller_access_key} not trusted by role")
        if caller_policies is not None and not evaluate_policies(
            caller_policies, "sts:AssumeRole", role.arn
        ):
            raise PermissionError("caller policy denies sts:AssumeRole")
        duration = max(self.MIN_DURATION, min(int(duration), self.MAX_DURATION))
        ak = "ASIA" + os.urandom(8).hex().upper()
        sk = os.urandom(20).hex()
        token = hmac.new(
            os.urandom(16), f"{ak}{time.time_ns()}".encode(), hashlib.sha256
        ).hexdigest()
        cred = TempCredential(
            access_key=ak,
            secret_key=sk,
            session_token=token,
            role=role,
            expires_at=time.time() + duration,
        )
        with self._lock:
            self._creds[ak] = cred
            self._gc_locked()
        return cred

    def lookup(self, access_key: str) -> TempCredential | None:
        cred = self._creds.get(access_key)
        if cred is None:
            return None
        if cred.expired:
            with self._lock:
                self._creds.pop(access_key, None)
            return None
        return cred

    def _gc_locked(self) -> None:
        now = time.time()
        for ak in [a for a, c in self._creds.items() if c.expires_at < now]:
            del self._creds[ak]
