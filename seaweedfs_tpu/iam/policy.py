"""AWS-style IAM policy evaluation.

Reference: weed/iam/policy/policy_engine.go (2,022 LoC: statement
matching with wildcards + condition evaluators) and
weed/s3api/auth_credentials.go (identity -> policy binding).

Documents are standard AWS policy JSON:

    {"Version": "2012-10-17",
     "Statement": [{"Sid": "ro", "Effect": "Allow",
                    "Action": ["s3:GetObject", "s3:ListBucket"],
                    "Resource": "arn:aws:s3:::logs/*",
                    "Condition": {"IpAddress": {"aws:SourceIp": "10.0.0.0/8"}}}]}

Evaluation order is AWS's: explicit Deny wins over any Allow; no
matching Allow = implicit deny.
"""

from __future__ import annotations

import fnmatch
import ipaddress
from typing import Iterable


class PolicyError(Exception):
    pass


def _as_list(v) -> list:
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _wildcard_match(pattern: str, value: str) -> bool:
    """AWS wildcard semantics: * matches any run (including '/'),
    ? matches one char. fnmatch's [seq] has no AWS meaning — escape."""
    pattern = pattern.replace("[", "[[]")
    return fnmatch.fnmatchcase(value, pattern)


# ------------------------------------------------------------- conditions


def _cond_string_equals(want: list[str], have: str) -> bool:
    return have in want


def _cond_string_like(want: list[str], have: str) -> bool:
    return any(_wildcard_match(w, have) for w in want)


def _cond_ip(want: list[str], have: str) -> bool:
    try:
        ip = ipaddress.ip_address(have)
    except ValueError:
        return False
    for cidr in want:
        try:
            if ip in ipaddress.ip_network(cidr, strict=False):
                return True
        except ValueError:
            continue
    return False


def _numeric(op):
    def check(want: list[str], have: str) -> bool:
        try:
            h = float(have)
        except (TypeError, ValueError):
            return False
        return any(op(h, float(w)) for w in want)

    return check


_CONDITION_EVALUATORS = {
    "StringEquals": _cond_string_equals,
    "StringNotEquals": lambda w, h: not _cond_string_equals(w, h),
    "StringLike": _cond_string_like,
    "StringNotLike": lambda w, h: not _cond_string_like(w, h),
    "IpAddress": _cond_ip,
    "NotIpAddress": lambda w, h: not _cond_ip(w, h),
    "NumericEquals": _numeric(lambda a, b: a == b),
    "NumericLessThan": _numeric(lambda a, b: a < b),
    "NumericLessThanEquals": _numeric(lambda a, b: a <= b),
    "NumericGreaterThan": _numeric(lambda a, b: a > b),
    "NumericGreaterThanEquals": _numeric(lambda a, b: a >= b),
    "Bool": lambda w, h: str(h).lower() in [str(x).lower() for x in w],
}


def _conditions_met(conditions: dict, context: dict) -> bool:
    """Every operator block and every key within it must pass (AWS
    ANDs condition operators and keys; values within a key are ORed —
    the evaluators above take the value list)."""
    for op_name, keys in (conditions or {}).items():
        evaluator = _CONDITION_EVALUATORS.get(op_name)
        if evaluator is None:
            return False  # unknown operator: fail closed
        for ckey, cvals in keys.items():
            have = context.get(ckey)
            if have is None:
                return False
            if not evaluator([str(v) for v in _as_list(cvals)], str(have)):
                return False
    return True


# -------------------------------------------------------------- statements


def _statement_matches(
    stmt: dict, action: str, resource: str, context: dict
) -> bool:
    if "NotAction" in stmt:
        nots = [str(a) for a in _as_list(stmt["NotAction"])]
        if any(_wildcard_match(a, action) for a in nots):
            return False
    else:
        actions = [str(a) for a in _as_list(stmt.get("Action"))]
        if not any(_wildcard_match(a, action) for a in actions):
            return False
    if "NotResource" in stmt:
        nots = [str(r) for r in _as_list(stmt["NotResource"])]
        if any(_wildcard_match(r, resource) for r in nots):
            return False
    else:
        resources = [str(r) for r in _as_list(stmt.get("Resource", "*"))]
        if not any(_wildcard_match(r, resource) for r in resources):
            return False
    return _conditions_met(stmt.get("Condition"), context)


def evaluate_policies_verdict(
    policies: Iterable[dict],
    action: str,
    resource: str,
    context: dict | None = None,
) -> str | None:
    """-> "deny" | "allow" | None (no matching statement). Explicit
    Deny anywhere wins — callers combining identity and resource
    policies need the three-way answer, because an identity explicit
    Deny must override a resource-policy Allow (AWS evaluation
    logic), which a boolean cannot express."""
    context = context or {}
    verdict: str | None = None
    for doc in policies:
        for stmt in _as_list(doc.get("Statement")):
            if not _statement_matches(stmt, action, resource, context):
                continue
            effect = str(stmt.get("Effect", "")).lower()
            if effect == "deny":
                return "deny"
            if effect == "allow":
                verdict = "allow"
    return verdict


def evaluate_policies(
    policies: Iterable[dict],
    action: str,
    resource: str,
    context: dict | None = None,
) -> bool:
    """True iff the action on the resource is allowed: explicit Deny
    anywhere wins; otherwise at least one Allow must match."""
    return evaluate_policies_verdict(policies, action, resource, context) == "allow"


def _principal_matches(stmt: dict, principal_arn: str) -> bool:
    """Bucket-policy Principal matching. Accepted shapes: "*",
    {"AWS": "*"}, {"AWS": [arn,...]}; an arn pattern may use
    wildcards. NotPrincipal inverts."""

    def match(spec) -> bool:
        if spec == "*":
            return True
        if isinstance(spec, dict):
            spec = spec.get("AWS", [])
        return any(
            _wildcard_match(str(p), principal_arn) for p in _as_list(spec)
        )

    if "NotPrincipal" in stmt:
        return not match(stmt["NotPrincipal"])
    if "Principal" not in stmt:
        return False  # resource policies require a principal
    return match(stmt["Principal"])


def evaluate_bucket_policy(
    doc: dict,
    action: str,
    resource: str,
    principal_arn: str,
    context: dict | None = None,
) -> str | None:
    """Resource-based (bucket) policy evaluation -> "deny" | "allow" |
    None (no matching statement). The caller combines this with
    identity-based results per AWS rules: explicit deny anywhere wins;
    a resource-policy allow suffices on its own (it can grant anonymous
    principals)."""
    context = context or {}
    verdict: str | None = None
    for stmt in _as_list(doc.get("Statement")):
        if not _principal_matches(stmt, principal_arn):
            continue
        if not _statement_matches(stmt, action, resource, context):
            continue
        effect = str(stmt.get("Effect", "")).lower()
        if effect == "deny":
            return "deny"
        if effect == "allow":
            verdict = "allow"
    return verdict


def bucket_policy_is_public(doc: dict) -> bool:
    """GetBucketPolicyStatus semantics: any Allow to Principal '*'
    without restrictive conditions counts as public."""
    for stmt in _as_list(doc.get("Statement")):
        if str(stmt.get("Effect", "")).lower() != "allow":
            continue
        p = stmt.get("Principal")
        if p == "*" or (isinstance(p, dict) and "*" in _as_list(p.get("AWS"))):
            if not stmt.get("Condition"):
                return True
    return False


def validate_bucket_policy(doc: dict, bucket: str) -> None:
    """Structural validation at PutBucketPolicy time (reference
    s3api_bucket_policy_handlers.go): statements must exist, carry
    principals, and reference only this bucket's ARNs."""
    stmts = _as_list(doc.get("Statement"))
    if not stmts:
        raise PolicyError("policy has no Statement")
    for stmt in stmts:
        if str(stmt.get("Effect", "")).lower() not in ("allow", "deny"):
            raise PolicyError(f"bad Effect {stmt.get('Effect')!r}")
        if "Principal" not in stmt and "NotPrincipal" not in stmt:
            raise PolicyError("bucket policy statement missing Principal")
        if "Action" not in stmt and "NotAction" not in stmt:
            raise PolicyError("statement missing Action")
        for r in _as_list(stmt.get("Resource")):
            r = str(r)
            if not (
                r == f"arn:aws:s3:::{bucket}"
                or r.startswith(f"arn:aws:s3:::{bucket}/")
            ):
                raise PolicyError(
                    f"resource {r!r} does not match bucket {bucket!r}"
                )


class PolicyEngine:
    """Named-policy registry + evaluation (reference policy_engine.go
    PolicyEngine with its policy store)."""

    def __init__(self):
        self._policies: dict[str, dict] = {}

    def put_policy(self, name: str, document: dict) -> None:
        if "Statement" not in document:
            raise PolicyError(f"policy {name}: no Statement")
        self._policies[name] = document

    def get_policy(self, name: str) -> dict | None:
        return self._policies.get(name)

    def delete_policy(self, name: str) -> None:
        self._policies.pop(name, None)

    def names(self) -> list[str]:
        return sorted(self._policies)

    def is_allowed(
        self,
        policy_names: Iterable[str],
        action: str,
        resource: str,
        context: dict | None = None,
    ) -> bool:
        docs = [
            self._policies[n] for n in policy_names if n in self._policies
        ]
        return evaluate_policies(docs, action, resource, context)


# ----------------------------------------------------- S3 request mapping


def s3_action_and_resource(
    method: str, bucket: str, key: str, q: dict
) -> tuple[str, str]:
    """Map one S3 request to its IAM action + resource ARN (reference
    s3api action constants in s3_constants + auth_credentials.go)."""
    if not bucket:
        return "s3:ListAllMyBuckets", "arn:aws:s3:::*"
    bucket_arn = f"arn:aws:s3:::{bucket}"
    obj_arn = f"{bucket_arn}/{key}" if key else bucket_arn
    if key:
        if "tagging" in q:
            return (
                {
                    "GET": "s3:GetObjectTagging",
                    "PUT": "s3:PutObjectTagging",
                    "DELETE": "s3:DeleteObjectTagging",
                }.get(method, "s3:GetObjectTagging"),
                obj_arn,
            )
        if "retention" in q:
            return (
                "s3:PutObjectRetention"
                if method == "PUT"
                else "s3:GetObjectRetention",
                obj_arn,
            )
        if "legal-hold" in q:
            return (
                "s3:PutObjectLegalHold"
                if method == "PUT"
                else "s3:GetObjectLegalHold",
                obj_arn,
            )
        if "acl" in q:
            return (
                "s3:PutObjectAcl" if method == "PUT" else "s3:GetObjectAcl",
                obj_arn,
            )
        if method in ("GET", "HEAD"):
            if "uploadId" in q:
                return "s3:ListMultipartUploadParts", obj_arn
            if "versionId" in q:
                return "s3:GetObjectVersion", obj_arn
            return "s3:GetObject", obj_arn
        if method == "PUT" or (method == "POST" and ("uploads" in q or "uploadId" in q)):
            return "s3:PutObject", obj_arn
        if method == "DELETE":
            if "uploadId" in q:
                return "s3:AbortMultipartUpload", obj_arn
            if "versionId" in q:
                return "s3:DeleteObjectVersion", obj_arn
            return "s3:DeleteObject", obj_arn
        return "s3:GetObject", obj_arn
    # bucket level
    if "policy" in q or "policyStatus" in q:
        return (
            {
                "GET": "s3:GetBucketPolicy",
                "PUT": "s3:PutBucketPolicy",
                "DELETE": "s3:DeleteBucketPolicy",
            }.get(method, "s3:GetBucketPolicy"),
            bucket_arn,
        )
    if "acl" in q:
        return (
            "s3:PutBucketAcl" if method == "PUT" else "s3:GetBucketAcl",
            bucket_arn,
        )
    if "encryption" in q:
        return (
            {
                "GET": "s3:GetEncryptionConfiguration",
                "PUT": "s3:PutEncryptionConfiguration",
                "DELETE": "s3:PutEncryptionConfiguration",
            }.get(method, "s3:GetEncryptionConfiguration"),
            bucket_arn,
        )
    if "lifecycle" in q:
        return (
            "s3:PutLifecycleConfiguration"
            if method in ("PUT", "DELETE")
            else "s3:GetLifecycleConfiguration",
            bucket_arn,
        )
    if "versioning" in q:
        return (
            "s3:PutBucketVersioning"
            if method == "PUT"
            else "s3:GetBucketVersioning",
            bucket_arn,
        )
    if "object-lock" in q:
        return (
            "s3:PutBucketObjectLockConfiguration"
            if method == "PUT"
            else "s3:GetBucketObjectLockConfiguration",
            bucket_arn,
        )
    if "cors" in q:
        return (
            {
                "GET": "s3:GetBucketCORS",
                "PUT": "s3:PutBucketCORS",
                "DELETE": "s3:PutBucketCORS",
            }.get(method, "s3:GetBucketCORS"),
            bucket_arn,
        )
    if "versions" in q:
        return "s3:ListBucketVersions", bucket_arn
    if "uploads" in q:
        return "s3:ListBucketMultipartUploads", bucket_arn
    if method in ("GET", "HEAD"):
        return "s3:ListBucket", bucket_arn
    if method == "PUT":
        return "s3:CreateBucket", bucket_arn
    if method == "DELETE":
        return "s3:DeleteBucket", bucket_arn
    if method == "POST" and "delete" in q:
        return "s3:DeleteObject", f"{bucket_arn}/*"
    return "s3:ListBucket", bucket_arn
