"""IAM: policy engine + STS temporary credentials.

Reference: weed/iam/policy (policy_engine.go), weed/iam/sts.
"""

from .policy import PolicyEngine, evaluate_policies  # noqa: F401
from .sts import StsService  # noqa: F401
