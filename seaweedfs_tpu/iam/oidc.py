"""OIDC bearer-token authentication for the S3 gateway.

Reference: weed/iam (OIDC provider wiring in the advanced IAM config):
clients present `Authorization: Bearer <jwt>`; the gateway verifies
the token against the configured issuer's keys and maps claims to an
identity with attached policies. Zero-egress build: keys are
CONFIGURED (shared secret for HS256 or PEM public key for RS256), not
fetched from a JWKS endpoint — the SPI seam (`OidcProvider.verify`)
is where a JWKS-fetching deployment plugs in.

Config shape (s3 config file / constructor):

    {"issuer": "https://idp.example", "audience": "seaweedfs",
     "hs256_secret": "...",            # or
     "rs256_public_key_pem": "-----BEGIN PUBLIC KEY-----...",
     "role_claim": "roles",
     "roles": {"admin": {"actions": ["Admin"]},
               "reader": {"policies": [{...}]}}}
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import time

from ..utils.security import _unb64 as _unb64_bytes


class OidcError(Exception):
    pass


def _unb64(s: str) -> bytes:
    return _unb64_bytes(s.encode())


class OidcProvider:
    def __init__(
        self,
        issuer: str,
        audience: str = "",
        hs256_secret: str = "",
        rs256_public_key_pem: str = "",
        role_claim: str = "roles",
        roles: dict | None = None,
        clock_skew: float = 60.0,
    ):
        if not hs256_secret and not rs256_public_key_pem:
            raise ValueError("OIDC needs hs256_secret or rs256_public_key_pem")
        self.issuer = issuer
        self.audience = audience
        self.role_claim = role_claim
        self.roles = roles or {}
        self.clock_skew = clock_skew
        self._hs_secret = hs256_secret.encode() if hs256_secret else None
        self._rs_key = None
        if rs256_public_key_pem:
            from cryptography.hazmat.primitives.serialization import (
                load_pem_public_key,
            )

            self._rs_key = load_pem_public_key(rs256_public_key_pem.encode())

    # ------------------------------------------------------------- verify

    def verify(self, token: str) -> dict:
        """-> validated claims dict; raises OidcError on ANY failure
        (fail closed: an unverifiable bearer is not anonymous, it is
        rejected)."""
        try:
            h_b64, p_b64, sig_b64 = token.split(".")
            header = json.loads(_unb64(h_b64))
            claims = json.loads(_unb64(p_b64))
            sig = _unb64(sig_b64)
        except (ValueError, json.JSONDecodeError) as e:
            raise OidcError(f"malformed token: {e}") from None
        alg = header.get("alg")
        signing_input = f"{h_b64}.{p_b64}".encode()
        if alg == "HS256":
            if self._hs_secret is None:
                raise OidcError("HS256 token but no shared secret configured")
            want = hmac_mod.new(
                self._hs_secret, signing_input, hashlib.sha256
            ).digest()
            if not hmac_mod.compare_digest(want, sig):
                raise OidcError("signature mismatch")
        elif alg == "RS256":
            if self._rs_key is None:
                raise OidcError("RS256 token but no public key configured")
            from cryptography.exceptions import InvalidSignature
            from cryptography.hazmat.primitives.asymmetric import padding
            from cryptography.hazmat.primitives.hashes import SHA256

            try:
                self._rs_key.verify(
                    sig, signing_input, padding.PKCS1v15(), SHA256()
                )
            except InvalidSignature:
                raise OidcError("signature mismatch") from None
        else:
            raise OidcError(f"unsupported alg {alg!r}")

        now = time.time()
        if claims.get("iss") != self.issuer:
            raise OidcError(f"wrong issuer {claims.get('iss')!r}")
        if self.audience:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.audience not in auds:
                raise OidcError(f"wrong audience {aud!r}")

        def num(name):
            v = claims.get(name)
            if v is None:
                return None
            try:
                return float(v)
            except (TypeError, ValueError):
                # contract: OidcError on ANY failure — a misbehaving
                # IdP's exp:"never" must 403, not 400/500
                raise OidcError(f"non-numeric {name} claim") from None

        exp = num("exp")
        if exp is None or now > exp + self.clock_skew:
            raise OidcError("token expired")
        nbf = num("nbf")
        if nbf is not None and now < nbf - self.clock_skew:
            raise OidcError("token not yet valid")
        return claims

    # ----------------------------------------------------------- identity

    def identity_for(self, claims: dict):
        """Map verified claims to an s3.auth.Identity via the role
        table; unmapped subjects get NO permissions (fail closed)."""
        from ..s3.auth import Identity

        raw = claims.get(self.role_claim) or []
        names = raw if isinstance(raw, list) else [raw]
        actions: list[str] = []
        policies: list[dict] = []
        for r in names:
            conf = self.roles.get(str(r))
            if not conf:
                continue
            actions.extend(conf.get("actions", []))
            policies.extend(conf.get("policies", []))
        return Identity(
            name=f"oidc:{claims.get('sub', '?')}",
            access_key="",
            secret_key="",
            actions=tuple(actions),
            policies=tuple(policies),
        )
