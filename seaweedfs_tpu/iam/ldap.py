"""LDAP identity provider: simple-bind authentication for STS.

Reference: weed/iam/ldap/ldap_provider.go (go-ldap backed; this is the
same provider surface on a hand-rolled LDAPv3 wire client — BER
encoding of BindRequest/BindResponse and a minimal search, RFC 4511).
Used by the gateway's ``AssumeRoleWithLdapIdentity`` STS action: a
successful bind as the templated user DN mints temporary credentials
for the mapped role.

Also ships ``MiniLdapServer``, an in-process LDAPv3 subset (bind +
unbind) used by the tests the way the reference uses its
mock_provider.go — and usable as a development stand-in.
"""

from __future__ import annotations

import socket
import threading


class LdapError(Exception):
    pass


# ------------------------------------------------------------------ BER


def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    out = b""
    while n:
        out = bytes([n & 0xFF]) + out
        n >>= 8
    return bytes([0x80 | len(out)]) + out


def _tlv(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(payload)) + payload


def _ber_int(v: int) -> bytes:
    out = v.to_bytes(max((v.bit_length() + 8) // 8, 1), "big", signed=True)
    return _tlv(0x02, out)


def _parse_tlv(buf: bytes, pos: int) -> tuple[int, bytes, int]:
    """-> (tag, payload, next_pos)."""
    if pos + 2 > len(buf):
        raise LdapError("short BER element")
    tag = buf[pos]
    ln = buf[pos + 1]
    pos += 2
    if ln & 0x80:
        n = ln & 0x7F
        if pos + n > len(buf):
            raise LdapError("short BER length")
        ln = int.from_bytes(buf[pos : pos + n], "big")
        pos += n
    if pos + ln > len(buf):
        # the declared content has not fully arrived: callers must
        # treat this as "read more", never parse a truncated payload
        # (a sliced-short resultCode of b"" reads as SUCCESS)
        raise LdapError("incomplete BER element")
    return tag, buf[pos : pos + ln], pos + ln


# ----------------------------------------------------------- the client


class LdapProvider:
    """Authenticates (username, password) by binding as the templated
    DN. ``bind_dn_template`` uses ``{username}``; e.g.
    ``uid={username},ou=users,dc=example,dc=com``."""

    def __init__(
        self,
        server: str,
        bind_dn_template: str,
        timeout: float = 5.0,
    ):
        if server.startswith("ldaps://"):
            # this client has no TLS: misparsing the URL would ship a
            # plaintext bind to host "ldaps" — refuse loudly instead
            raise LdapError(
                "ldaps:// is not supported by this client; terminate "
                "TLS in front of it or use ldap:// on a trusted network"
            )
        if server.startswith("ldap://"):
            server = server[len("ldap://") :]
        host, _, port = server.partition(":")
        self.host = host
        self.port = int(port or 389)
        self.bind_dn_template = bind_dn_template
        self.timeout = timeout

    def authenticate(self, username: str, password: str) -> str:
        """-> the bound DN on success; raises LdapError on bad
        credentials or transport failure. Empty passwords are REFUSED
        locally: RFC 4513 treats them as anonymous binds, which many
        servers 'succeed' — accepting that would authenticate anyone."""
        if not username or not password:
            raise LdapError("username and password required")
        if any(c in username for c in ",+=\"\\<>;\r\n\x00"):
            raise LdapError("invalid characters in username")
        dn = self.bind_dn_template.replace("{username}", username)
        try:
            return self._bind(dn, password)
        except OSError as e:
            raise LdapError(f"ldap transport: {e}") from None

    def _bind(self, dn: str, password: str) -> str:
        bind = _tlv(
            0x60,  # [APPLICATION 0] BindRequest
            _ber_int(3)  # version
            + _tlv(0x04, dn.encode())  # name
            + _tlv(0x80, password.encode()),  # simple auth [context 0]
        )
        msg = _tlv(0x30, _ber_int(1) + bind)
        with socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        ) as sock:
            sock.sendall(msg)
            buf = b""
            while True:
                got = sock.recv(4096)
                if not got:
                    raise LdapError("connection closed during bind")
                buf += got
                try:
                    tag, payload, _ = _parse_tlv(buf, 0)
                except LdapError:
                    continue
                if tag != 0x30:
                    raise LdapError(f"unexpected LDAP message tag {tag:#x}")
                break
            # LDAPMessage ::= { messageID, BindResponse }
            _t, _mid, pos = _parse_tlv(payload, 0)
            op_tag, op, _ = _parse_tlv(payload, pos)
            if op_tag != 0x61:  # [APPLICATION 1] BindResponse
                raise LdapError(f"unexpected response op {op_tag:#x}")
            code_tag, code, _ = _parse_tlv(op, 0)
            if code_tag != 0x0A or not code:
                # an EMPTY resultCode would int() to 0 == success —
                # fail-open on a malicious/buggy endpoint
                raise LdapError("malformed BindResponse")
            result = int.from_bytes(code, "big")
            # polite unbind; best effort
            try:
                sock.sendall(_tlv(0x30, _ber_int(2) + _tlv(0x42, b"")))
            except OSError:
                pass
        if result != 0:
            raise LdapError(f"bind failed (resultCode {result})")
        return dn


# ------------------------------------------------- test/dev LDAP server


class MiniLdapServer:
    """LDAPv3 subset: simple bind against a {dn: password} table.
    Wrong passwords get resultCode 49 (invalidCredentials); empty
    passwords get 53 (unwillingToPerform) like hardened servers."""

    def __init__(self, users: dict[str, str], ip: str = "127.0.0.1"):
        self.users = users
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((ip, 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self.binds = 0
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            buf = b""
            while True:
                got = conn.recv(4096)
                if not got:
                    return
                buf += got
                while buf:
                    try:
                        _tag, payload, end = _parse_tlv(buf, 0)
                    except LdapError:
                        break
                    if end > len(buf):
                        break
                    buf = buf[end:]
                    _t, mid_raw, pos = _parse_tlv(payload, 0)
                    mid = int.from_bytes(mid_raw, "big", signed=True)
                    op_tag, op, _ = _parse_tlv(payload, pos)
                    if op_tag == 0x42:  # UnbindRequest
                        return
                    if op_tag != 0x60:
                        continue
                    _vt, _ver, p2 = _parse_tlv(op, 0)
                    _nt, name, p3 = _parse_tlv(op, p2)
                    at, secret, _ = _parse_tlv(op, p3)
                    dn = name.decode(errors="replace")
                    self.binds += 1
                    if at != 0x80 or not secret:
                        code = 53  # unwillingToPerform
                    elif self.users.get(dn) == secret.decode(
                        errors="replace"
                    ):
                        code = 0
                    else:
                        code = 49  # invalidCredentials
                    resp = _tlv(
                        0x61,
                        _tlv(0x0A, bytes([code]))
                        + _tlv(0x04, b"")
                        + _tlv(0x04, b""),
                    )
                    conn.sendall(_tlv(0x30, _ber_int(mid) + resp))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
