"""POST-policy form uploads (browser-based uploads).

Reference: weed/s3api/s3api_object_handlers_postpolicy.go +
weed/s3api/policy/post-policy.go. A browser POSTs multipart/form-data
to the bucket URL with a base64 policy document, a SigV4 signature
over that exact base64 string, and the file; the server verifies the
signature with the credential's secret, checks the policy's expiration
and conditions, then stores the object.
"""

from __future__ import annotations

import base64
import datetime as _dt
import hmac
import json

from .auth import S3AuthError, signing_key


def parse_multipart_form(body: bytes, content_type: str) -> tuple[dict, bytes, str]:
    """-> (fields, file_bytes, filename). Minimal RFC 2046 parser: the
    S3 POST form is flat (no nested multiparts), fields are text, and
    exactly one part is named `file` (everything after it is ignored,
    per AWS)."""
    boundary = ""
    for seg in content_type.split(";"):
        seg = seg.strip()
        if seg.startswith("boundary="):
            boundary = seg[len("boundary=") :].strip('"')
    if not boundary:
        raise S3AuthError("MalformedPOSTRequest", "missing multipart boundary")
    # RFC 2046 framing: parts are delimited by CRLF + "--boundary"; the
    # CRLF belongs to the DELIMITER, not the payload, so splitting on it
    # preserves payloads that themselves end in CR/LF bytes (a
    # .strip(b"\r\n") here would silently corrupt such files).
    delim = b"\r\n--" + boundary.encode()
    fields: dict[str, str] = {}
    file_bytes: bytes | None = None
    filename = ""
    segments = (b"\r\n" + body).split(delim)
    for part in segments[1:]:  # [0] is the preamble
        if part.startswith(b"--"):
            break  # closing delimiter
        if part.startswith(b"\r\n"):
            part = part[2:]
        head, _, payload = part.partition(b"\r\n\r\n")
        disp = ""
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-disposition:"):
                disp = line.decode("utf-8", "replace")
        name = ""
        fname = ""
        for seg in disp.split(";"):
            seg = seg.strip()
            if seg.startswith("name="):
                name = seg[5:].strip('"')
            elif seg.startswith("filename="):
                fname = seg[9:].strip('"')
        if not name:
            continue
        if name == "file":
            if file_bytes is None:
                file_bytes = payload
                filename = fname
        else:
            fields[name.lower()] = payload.decode("utf-8", "replace")
    if file_bytes is None:
        raise S3AuthError("MalformedPOSTRequest", "form has no file part")
    return fields, file_bytes, filename


def verify_post_signature(identities, fields: dict, region: str):
    """SigV4 policy signature check -> the signing Identity."""
    policy_b64 = fields.get("policy")
    if not policy_b64:
        raise S3AuthError("AccessDenied", "POST without policy")
    algo = fields.get("x-amz-algorithm", "")
    if algo != "AWS4-HMAC-SHA256":
        raise S3AuthError("AccessDenied", f"unsupported algorithm {algo!r}")
    cred = fields.get("x-amz-credential", "")
    try:
        access_key, date, cred_region, service, term = cred.split("/")
    except ValueError:
        raise S3AuthError("AccessDenied", f"malformed credential {cred!r}") from None
    if service != "s3" or term != "aws4_request":
        raise S3AuthError("AccessDenied", "malformed credential scope")
    ident = identities.lookup(access_key)
    if ident is None:
        raise S3AuthError("InvalidAccessKeyId", access_key)
    sk = signing_key(ident.secret_key, date, cred_region)
    want = hmac.new(sk, policy_b64.encode(), "sha256").hexdigest()
    got = fields.get("x-amz-signature", "")
    if not hmac.compare_digest(want, got):
        raise S3AuthError("SignatureDoesNotMatch", "POST policy signature")
    return ident


def check_policy_document(
    fields: dict, file_size: int, bucket: str, key: str
) -> None:
    """Enforce expiration + conditions of the (already authenticated)
    policy document against the submitted form."""
    try:
        doc = json.loads(base64.b64decode(fields["policy"]))
    except Exception:
        raise S3AuthError("MalformedPOSTRequest", "policy is not base64 JSON") from None

    exp = doc.get("expiration")
    if not exp:
        raise S3AuthError("MalformedPOSTRequest", "policy missing expiration")
    try:
        when = _dt.datetime.fromisoformat(exp.replace("Z", "+00:00"))
    except ValueError:
        raise S3AuthError("MalformedPOSTRequest", f"bad expiration {exp!r}") from None
    if when <= _dt.datetime.now(_dt.timezone.utc):
        raise S3AuthError("AccessDenied", "policy expired")

    def form_value(name: str) -> str:
        if name == "bucket":
            return bucket
        if name == "key":
            return key
        return fields.get(name.lower(), "")

    # AWS rule: every form field except x-amz-signature, file, policy
    # and x-ignore-* MUST be covered by a condition — otherwise the
    # holder of a signed form could append unauthorized fields (e.g.
    # acl=public-read-write) the signer never approved.
    covered: set[str] = {"bucket"}
    for cond in doc.get("conditions", []):
        if isinstance(cond, dict):
            covered.update(k.lower() for k in cond)
        elif isinstance(cond, list) and len(cond) == 3:
            covered.add(str(cond[1]).lstrip("$").lower())
    exempt = {"policy", "x-amz-signature", "file"}
    for name in fields:
        if name in exempt or name.startswith("x-ignore-"):
            continue
        if name not in covered:
            raise S3AuthError(
                "AccessDenied",
                f"form field {name!r} is not covered by the policy",
            )

    for cond in doc.get("conditions", []):
        if isinstance(cond, dict):
            for k, v in cond.items():
                if form_value(k) != str(v):
                    raise S3AuthError(
                        "AccessDenied",
                        f"policy condition failed: {k} == {v!r}",
                    )
        elif isinstance(cond, list) and len(cond) == 3:
            op, name, val = cond
            op = str(op).lower()
            if op == "content-length-range":
                lo, hi = int(name), int(val)
                if not (lo <= file_size <= hi):
                    raise S3AuthError(
                        "EntityTooLarge"
                        if file_size > hi
                        else "EntityTooSmall",
                        f"file size {file_size} outside [{lo}, {hi}]",
                    )
                continue
            field = str(name).lstrip("$")
            have = form_value(field)
            if op == "eq" and have != str(val):
                raise S3AuthError(
                    "AccessDenied", f"policy condition failed: {field} eq {val!r}"
                )
            if op == "starts-with" and not have.startswith(str(val)):
                raise S3AuthError(
                    "AccessDenied",
                    f"policy condition failed: {field} starts-with {val!r}",
                )
        else:
            raise S3AuthError(
                "MalformedPOSTRequest", f"unparseable condition {cond!r}"
            )
