"""Server-side encryption: SSE-C (customer keys) and SSE-S3 (managed
keyring), with a KMS SPI for external key services.

Reference surface: weed/s3api/s3_sse_c.go (customer-key validation,
MD5 binding), weed/s3api/s3_sse_kms.go + weed/kms/ (provider SPI,
envelope encryption). The cipher here is AES-256-CTR: it is
length-preserving (ciphertext length == plaintext length, so
Content-Length/Range arithmetic is unchanged) and seekable (a range
read decrypts from any 16-byte block boundary without touching
preceding bytes).

Envelope scheme for SSE-S3: every object gets a fresh random 256-bit
data key; the data key is wrapped by the keyring's master key
(AES-256-GCM, nonce||ct||tag) and stored in the entry's extended
attributes. Rotating the master key never requires re-encrypting data,
only re-wrapping keys.
"""

from __future__ import annotations

import base64
import hashlib
import os

# `cryptography` is an optional dependency: the S3 gateway itself (and
# the read-path bench/tests) must import without it — only the SSE
# features need the cipher, and they raise NotImplemented when it is
# absent instead of poisoning the whole gateway import.
try:
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
        modes,
    )
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - exercised in slim containers
    Cipher = algorithms = modes = AESGCM = None

# entry.extended attribute keys
SSE_ALGO_KEY = "s3-sse"  # b"SSE-C" | b"AES256"
SSE_IV_KEY = "s3-sse-iv"
SSE_KEY_MD5_KEY = "s3-sse-c-key-md5"  # base64 MD5 of the customer key
SSE_WRAPPED_KEY = "s3-sse-wrapped-key"  # keyring-wrapped data key
SSE_KEY_ID_KEY = "s3-sse-key-id"
# multipart objects: JSON [[plaintext_len, iv_hex], ...] in part order.
# Each part is an INDEPENDENT CTR stream under the object's data key
# with its own random IV (a re-uploaded part gets a fresh IV, so no
# counter stream is ever reused with different plaintext).
SSE_PART_MAP_KEY = "s3-sse-parts"

CUSTOMER_PREFIX = "x-amz-server-side-encryption-customer-"
COPY_CUSTOMER_PREFIX = "x-amz-copy-source-server-side-encryption-customer-"


class SseError(Exception):
    """Carries the S3 error code the gateway should map to."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _require_crypto() -> None:
    if Cipher is None:
        raise SseError(
            "NotImplemented",
            "SSE requires the 'cryptography' package (not installed)",
        )


def _ctr_apply(key: bytes, iv: bytes, data: bytes, block_offset: int = 0) -> bytes:
    """AES-256-CTR transform (encrypt == decrypt). block_offset seeks
    the counter forward for range reads (units of 16-byte blocks)."""
    _require_crypto()
    if block_offset:
        ctr = (int.from_bytes(iv, "big") + block_offset) % (1 << 128)
        iv = ctr.to_bytes(16, "big")
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return enc.update(data) + enc.finalize()


def encrypt(key: bytes, data: bytes) -> tuple[bytes, bytes]:
    """-> (iv, ciphertext)."""
    iv = os.urandom(16)
    return iv, _ctr_apply(key, iv, data)


def decrypt(key: bytes, iv: bytes, data: bytes) -> bytes:
    return _ctr_apply(key, iv, data)


def decrypt_range(key: bytes, iv: bytes, ct_from_aligned: bytes, offset: int) -> bytes:
    """Decrypt a ciphertext slice read starting at the 16-byte-aligned
    offset `offset - offset % 16`; returns the plaintext for the
    requested offset (prefix within the first block dropped)."""
    skip = offset % 16
    pt = _ctr_apply(key, iv, ct_from_aligned, block_offset=offset // 16)
    return pt[skip:]


def key_md5_b64(key: bytes) -> str:
    return base64.b64encode(hashlib.md5(key).digest()).decode()


def parse_customer_headers(headers, prefix: str = CUSTOMER_PREFIX) -> bytes | None:
    """Validate the SSE-C header triple; returns the 256-bit key or
    None when no SSE-C headers are present. Key-MD5 binding is
    mandatory (reference s3_sse_c.go: a transposed key must fail
    closed, not decrypt garbage)."""
    algo = headers.get(prefix + "algorithm")
    key_b64 = headers.get(prefix + "key")
    md5_b64 = headers.get(prefix + "key-MD5") or headers.get(prefix + "key-md5")
    if not algo and not key_b64:
        return None
    if algo != "AES256":
        raise SseError(
            "InvalidArgument", f"unsupported SSE-C algorithm {algo!r}"
        )
    if not key_b64 or not md5_b64:
        raise SseError("InvalidArgument", "SSE-C requires key and key-MD5")
    try:
        key = base64.b64decode(key_b64, validate=True)
    except Exception:
        raise SseError("InvalidArgument", "SSE-C key is not valid base64") from None
    if len(key) != 32:
        raise SseError("InvalidArgument", "SSE-C key must be 256 bits")
    if key_md5_b64(key) != md5_b64:
        raise SseError("InvalidArgument", "SSE-C key MD5 mismatch")
    return key


# ---------------------------------------------------------------------------
# KMS SPI + local keyring
# ---------------------------------------------------------------------------


class KmsProvider:
    """SPI for data-key generation/unwrap (reference weed/kms/). An
    external KMS plugs in by implementing these two methods."""

    key_id: str

    def generate_data_key(self) -> tuple[str, bytes, bytes]:
        """-> (key_id, plaintext_data_key, wrapped_data_key)."""
        raise NotImplementedError

    def decrypt_data_key(self, key_id: str, wrapped: bytes) -> bytes:
        raise NotImplementedError


class LocalKeyring(KmsProvider):
    """SSE-S3 default: a single local master key wrapping per-object
    data keys with AES-256-GCM."""

    def __init__(self, master_key: bytes, key_id: str = "local-0"):
        if len(master_key) != 32:
            raise ValueError("master key must be 256 bits")
        # without `cryptography` the keyring still constructs (the
        # gateway boots); only actually wrapping/unwrapping keys raises
        self._master = AESGCM(master_key) if AESGCM is not None else None
        self.key_id = key_id

    def generate_data_key(self) -> tuple[str, bytes, bytes]:
        if self._master is None:
            _require_crypto()
        dk = os.urandom(32)
        nonce = os.urandom(12)
        wrapped = nonce + self._master.encrypt(nonce, dk, self.key_id.encode())
        return self.key_id, dk, wrapped

    def decrypt_data_key(self, key_id: str, wrapped: bytes) -> bytes:
        if self._master is None:
            _require_crypto()
        if key_id != self.key_id:
            raise SseError("InvalidArgument", f"unknown SSE-S3 key id {key_id!r}")
        try:
            return self._master.decrypt(
                wrapped[:12], wrapped[12:], key_id.encode()
            )
        except Exception:
            raise SseError(
                "InternalError", "SSE-S3 data key unwrap failed"
            ) from None


def load_or_create_keyring(kv_get, kv_put, kv_put_if_absent=None) -> LocalKeyring:
    """Master key persisted in the filer KV store so every gateway
    instance over the same filer shares it. First-boot creation uses
    the store's atomic create-if-absent when available (both embedded
    stores provide it), so two racing gateways deterministically adopt
    the ONE stored key — a lost race with plain put/re-read would leave
    a process holding a divergent in-memory key whose wrapped objects
    become undecryptable after restart."""
    k = b"s3-sse/master-key"
    raw = kv_get(k)
    if raw is not None and len(raw) == 32:
        return LocalKeyring(raw)
    if raw is None and kv_put_if_absent is not None:
        raw = kv_put_if_absent(k, os.urandom(32))
    else:  # no atomic primitive — or a CORRUPT stored value, which
        #    put-if-absent could never repair (it returns the existing
        #    bytes): overwrite, then adopt whatever the store holds
        kv_put(k, os.urandom(32))
        raw = kv_get(k)
    if raw is None or len(raw) != 32:  # pragma: no cover - kv broken
        raise SseError("InternalError", "could not persist SSE master key")
    return LocalKeyring(raw)


# ---------------------------------------------------------------------------
# entry helpers (shared by PUT/GET/HEAD/copy paths)
# ---------------------------------------------------------------------------


def encrypt_for_put(
    data: bytes,
    ssec_key: bytes | None,
    sse_algo: str,
    keyring: KmsProvider | None,
) -> tuple[bytes, dict, dict]:
    """-> (stored_bytes, extended_attrs, response_headers)."""
    if ssec_key is not None and sse_algo:
        raise SseError(
            "InvalidArgument", "SSE-C and x-amz-server-side-encryption conflict"
        )
    if ssec_key is not None:
        iv, ct = encrypt(ssec_key, data)
        ext = {
            SSE_ALGO_KEY: b"SSE-C",
            SSE_IV_KEY: iv,
            SSE_KEY_MD5_KEY: key_md5_b64(ssec_key).encode(),
        }
        hdrs = {
            CUSTOMER_PREFIX + "algorithm": "AES256",
            CUSTOMER_PREFIX + "key-MD5": key_md5_b64(ssec_key),
        }
        return ct, ext, hdrs
    if sse_algo:
        if sse_algo == "aws:kms":
            # Honest 501 over silently downgrading to the local keyring
            # and reporting AES256 (compliance tooling would believe
            # KMS-wrapped keys are in use).
            raise SseError(
                "NotImplemented", "aws:kms requires an external KMS provider"
            )
        if sse_algo != "AES256":
            raise SseError(
                "InvalidArgument",
                f"unsupported x-amz-server-side-encryption {sse_algo!r}",
            )
        if keyring is None:
            raise SseError("InvalidRequest", "SSE-S3 keyring not configured")
        key_id, dk, wrapped = keyring.generate_data_key()
        iv, ct = encrypt(dk, data)
        ext = {
            SSE_ALGO_KEY: b"AES256",
            SSE_IV_KEY: iv,
            SSE_WRAPPED_KEY: wrapped,
            SSE_KEY_ID_KEY: key_id.encode(),
        }
        return ct, ext, {"x-amz-server-side-encryption": "AES256"}
    return data, {}, {}


def resolve_put_encryption(headers, bucket_default: str = ""):
    """One header triage for EVERY write path (single PUT, copy dest,
    multipart initiate): -> (ssec_key | None, algo str). Raises
    SseError for SSE-C/algo conflicts and for aws:kms (honest 501 —
    silently downgrading to the local keyring would misreport
    compliance)."""
    ssec_key = parse_customer_headers(headers)
    algo = headers.get("x-amz-server-side-encryption", "")
    if ssec_key is not None and algo:
        raise SseError(
            "InvalidArgument", "SSE-C and x-amz-server-side-encryption conflict"
        )
    if ssec_key is None and not algo:
        algo = bucket_default
    if algo == "aws:kms":
        raise SseError(
            "NotImplemented", "aws:kms requires an external KMS provider"
        )
    if algo and algo != "AES256":
        raise SseError(
            "InvalidArgument",
            f"unsupported x-amz-server-side-encryption {algo!r}",
        )
    return ssec_key, algo


def entry_sse_algo(entry) -> str:
    return (entry.extended.get(SSE_ALGO_KEY) or b"").decode()


def decrypt_key_for_entry(
    entry, ssec_key: bytes | None, keyring: KmsProvider | None
) -> bytes | None:
    """Resolve the data key needed to read `entry` (None = plaintext
    object). Raises SseError when required key material is absent or
    wrong — fail closed, never serve ciphertext as content."""
    algo = entry_sse_algo(entry)
    if not algo:
        if ssec_key is not None:
            raise SseError(
                "InvalidRequest", "object is not SSE-C encrypted"
            )
        return None
    if algo == "SSE-C":
        if ssec_key is None:
            raise SseError(
                "InvalidRequest",
                "object was stored with SSE-C; key headers required",
            )
        want = (entry.extended.get(SSE_KEY_MD5_KEY) or b"").decode()
        if key_md5_b64(ssec_key) != want:
            raise SseError("AccessDenied", "SSE-C key does not match object key")
        return ssec_key
    if algo == "AES256":
        if keyring is None:
            raise SseError("InternalError", "SSE-S3 keyring not configured")
        key_id = (entry.extended.get(SSE_KEY_ID_KEY) or b"").decode()
        wrapped = entry.extended.get(SSE_WRAPPED_KEY) or b""
        return keyring.decrypt_data_key(key_id, wrapped)
    raise SseError("InternalError", f"unknown SSE algorithm {algo!r}")


def read_decrypted(read_fn, entry, key: bytes, offset: int, size: int) -> bytes:
    """Decrypt entry bytes [offset, offset+size) (size < 0 = to end).
    read_fn(off, sz) returns ciphertext from the store. Handles both
    single-IV objects and multipart part-maps (each part its own CTR
    stream; range reads seek within the owning part's counter)."""
    import json as _json

    pm_raw = entry.extended.get(SSE_PART_MAP_KEY)
    if not pm_raw:
        iv = entry.extended.get(SSE_IV_KEY) or b""
        aligned = offset - offset % 16
        want = size if size < 0 else size + (offset - aligned)
        ct = read_fn(aligned, want)
        pt = decrypt_range(key, iv, ct, offset)
        return pt if size < 0 else pt[:size]
    parts = _json.loads(pm_raw)
    total = sum(int(length) for length, _iv in parts)
    end = total if size < 0 else min(offset + size, total)
    out = bytearray()
    part_start = 0
    for length, iv_hex in parts:
        length = int(length)
        lo = max(offset, part_start)
        hi = min(end, part_start + length)
        if lo < hi:
            in_off = lo - part_start
            aligned_in = in_off - in_off % 16
            ct = read_fn(
                part_start + aligned_in, (hi - part_start) - aligned_in
            )
            pt = decrypt_range(key, bytes.fromhex(iv_hex), ct, in_off)
            out += pt[: hi - lo]
        part_start += length
        if part_start >= end:
            break
    return bytes(out)


def response_headers_for_entry(entry) -> dict:
    algo = entry_sse_algo(entry)
    if algo == "SSE-C":
        return {
            CUSTOMER_PREFIX + "algorithm": "AES256",
            CUSTOMER_PREFIX
            + "key-MD5": (entry.extended.get(SSE_KEY_MD5_KEY) or b"").decode(),
        }
    if algo == "AES256":
        return {"x-amz-server-side-encryption": "AES256"}
    return {}
