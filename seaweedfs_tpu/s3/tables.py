"""Iceberg REST catalog + AWS S3-Tables API on the S3 gateway.

Reference: weed/s3api/iceberg/ (REST catalog per the Apache Iceberg
spec, backed by table-bucket storage) and weed/s3api/s3api_tables.go
(the AWS S3Tables surface: table buckets -> namespaces -> tables,
driven either by X-Amz-Target JSON posts or the CLI's REST paths).

Implemented subset:
- Iceberg REST v1 under /iceberg/v1 (and /iceberg/v1/{prefix} where
  prefix names a table bucket): config, namespace CRUD + property
  updates, table list/create/load/exists/drop/rename, and commits that
  set/remove properties (each commit writes a NEW metadata file and
  appends to the metadata log, as the spec requires).
- S3Tables: CreateTableBucket / ListTableBuckets / DeleteTableBucket,
  Create/List/Get/DeleteNamespace, Create/List/Get/DeleteTable via
  X-Amz-Target; ARN-path REST aliases for the same ops.

Metadata files are ordinary S3 objects in the table bucket
(<ns>/<table>/metadata/NNNNN-<uuid>.metadata.json), so any Iceberg
reader pointed at the gateway can load them; the catalog pointers live
in the filer KV.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
import uuid

from ..filer.filer_store import NotFound

DEFAULT_BUCKET = "default"  # un-prefixed /v1 routes land here
_ARN_RE = re.compile(r"arn:aws:s3tables:[^/:]*:[^/:]*:bucket/[^/]+")
_REST_RE = re.compile(
    r"^/(buckets(/arn:aws:s3tables:|$|/$)"
    r"|namespaces/arn:aws:s3tables:"
    r"|tables/arn:aws:s3tables:)"
)


def is_s3tables_path(path: str) -> bool:
    """CLI-style S3Tables REST path (ARN-rooted, or the bare /buckets
    collection) — matched on the path PREFIX so an ordinary object key
    merely containing an ARN substring is never hijacked."""
    return bool(_REST_RE.match(path))


class TablesError(Exception):
    def __init__(self, code: int, typ: str, message: str):
        super().__init__(message)
        self.code = code
        self.typ = typ


_NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9._\-]{0,254}$")


def _check_name(kind: str, name: str) -> str:
    """Catalog identifiers: no empty names, no KV-separator (:) or
    path (/) characters — 'a' + ns 'b:c' must never share a KV key
    with bucket 'a:b' + ns 'c'."""
    if not _NAME_RE.match(name or ""):
        raise TablesError(
            400, "BadRequestException", f"invalid {kind} name {name!r}"
        )
    return name


class TablesCatalog:
    """Catalog state in the filer KV; metadata files in the bucket.

    A process-wide lock serializes every read-modify-write of the KV
    docs: ThreadingHTTPServer handles requests concurrently and a lost
    update here orphans metadata files."""

    def __init__(self, srv):
        self.srv = srv  # S3Server (filer + put_object access)
        self._lock = threading.RLock()

    # ------------------------------------------------------------ kv

    def _kv(self, key: str) -> dict:
        raw = self.srv.filer.store.kv_get(key.encode())
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except ValueError:
            return {}

    def _kv_put(self, key: str, doc: dict) -> None:
        self.srv.filer.store.kv_put(key.encode(), json.dumps(doc).encode())

    # -------------------------------------------------------- buckets

    def buckets(self) -> dict:
        return self._kv("s3tables:buckets")

    def create_bucket(self, name: str) -> dict:
        _check_name("bucket", name)
        with self._lock:
            return self._create_bucket_locked(name)

    def _create_bucket_locked(self, name: str) -> dict:
        b = self.buckets()
        if name in b:
            raise TablesError(409, "ConflictException", f"bucket {name} exists")
        arn = f"arn:aws:s3tables:local:000000000000:bucket/{name}"
        b[name] = {"arn": arn, "createdAt": time.time()}
        self._kv_put("s3tables:buckets", b)
        # the table bucket is a REAL s3 bucket: metadata/data objects
        # live in it and are readable over the ordinary S3 surface
        from ..filer.entry import new_entry

        if not self.srv.filer.exists(f"/buckets/{name}"):
            self.srv.filer.create_entry(
                new_entry(f"/buckets/{name}", is_directory=True, mode=0o755)
            )
        return b[name]

    def require_bucket(self, name: str) -> dict:
        b = self.buckets().get(name)
        if b is None:
            raise TablesError(
                404, "NotFoundException", f"table bucket {name} not found"
            )
        return b

    def delete_bucket(self, name: str) -> None:
        with self._lock:
            self._delete_bucket_locked(name)

    def _delete_bucket_locked(self, name: str) -> None:
        self.require_bucket(name)
        if self._kv(f"s3tables:ns:{name}"):
            raise TablesError(
                409, "ConflictException", "table bucket is not empty"
            )
        b = self.buckets()
        b.pop(name, None)
        self._kv_put("s3tables:buckets", b)

    # ----------------------------------------------------- namespaces

    def namespaces(self, bucket: str) -> dict:
        return self._kv(f"s3tables:ns:{bucket}")

    def create_namespace(self, bucket: str, ns: str, props: dict) -> None:
        _check_name("namespace", ns)
        with self._lock:
            self._create_namespace_locked(bucket, ns, props)

    def _create_namespace_locked(self, bucket: str, ns: str, props: dict) -> None:
        self.require_bucket(bucket)
        all_ns = self.namespaces(bucket)
        if ns in all_ns:
            raise TablesError(
                409, "AlreadyExistsException", f"namespace {ns} exists"
            )
        all_ns[ns] = {"properties": props or {}, "createdAt": time.time()}
        self._kv_put(f"s3tables:ns:{bucket}", all_ns)

    def require_namespace(self, bucket: str, ns: str) -> dict:
        got = self.namespaces(bucket).get(ns)
        if got is None:
            raise TablesError(
                404, "NoSuchNamespaceException", f"namespace {ns} not found"
            )
        return got

    def update_namespace_props(
        self, bucket: str, ns: str, removals: list, updates: dict
    ) -> dict:
        with self._lock:
            return self._update_ns_props_locked(bucket, ns, removals, updates)

    def _update_ns_props_locked(
        self, bucket: str, ns: str, removals: list, updates: dict
    ) -> dict:
        all_ns = self.namespaces(bucket)
        rec = all_ns.get(ns)
        if rec is None:
            raise TablesError(
                404, "NoSuchNamespaceException", f"namespace {ns} not found"
            )
        missing = [r for r in removals or [] if r not in rec["properties"]]
        for r in removals or []:
            rec["properties"].pop(r, None)
        rec["properties"].update(updates or {})
        self._kv_put(f"s3tables:ns:{bucket}", all_ns)
        return {
            "removed": [r for r in removals or [] if r not in missing],
            "updated": sorted((updates or {}).keys()),
            "missing": missing,
        }

    def drop_namespace(self, bucket: str, ns: str) -> None:
        with self._lock:
            self._drop_namespace_locked(bucket, ns)

    def _drop_namespace_locked(self, bucket: str, ns: str) -> None:
        self.require_namespace(bucket, ns)
        if self.tables(bucket, ns) or self.views(bucket, ns):
            raise TablesError(
                409, "NamespaceNotEmptyException", f"namespace {ns} not empty"
            )
        all_ns = self.namespaces(bucket)
        all_ns.pop(ns, None)
        self._kv_put(f"s3tables:ns:{bucket}", all_ns)

    # --------------------------------------------------------- tables

    def tables(self, bucket: str, ns: str) -> dict:
        return self._kv(f"s3tables:tables:{bucket}:{ns}")

    def _write_metadata(
        self, bucket: str, ns: str, name: str, metadata: dict, version: int
    ) -> str:
        body = json.dumps(metadata, indent=2).encode()
        key = (
            f"{ns}/{name}/metadata/"
            f"{version:05d}-{uuid.uuid4().hex}.metadata.json"
        )
        self.srv.put_object(
            bucket, key, body, mime="application/json"
        )
        return f"s3://{bucket}/{key}"

    def create_table(
        self, bucket: str, ns: str, name: str, schema: dict, props: dict
    ) -> dict:
        _check_name("table", name)
        with self._lock:
            return self._create_table_locked(bucket, ns, name, schema, props)

    def _create_table_locked(
        self, bucket: str, ns: str, name: str, schema: dict, props: dict
    ) -> dict:
        self.require_namespace(bucket, ns)
        self._check_identifier_free(bucket, ns, name)
        tables = self.tables(bucket, ns)
        schema = schema or {"type": "struct", "schema-id": 0, "fields": []}
        schema.setdefault("schema-id", 0)
        last_col = max(
            (f.get("id", 0) for f in schema.get("fields", [])), default=0
        )
        tuid = str(uuid.uuid4())
        location = f"s3://{bucket}/{ns}/{name}"
        metadata = {
            "format-version": 2,
            "table-uuid": tuid,
            "location": location,
            "last-sequence-number": 0,
            "last-updated-ms": int(time.time() * 1000),
            "last-column-id": last_col,
            "current-schema-id": schema["schema-id"],
            "schemas": [schema],
            "default-spec-id": 0,
            "partition-specs": [{"spec-id": 0, "fields": []}],
            "last-partition-id": 999,
            "default-sort-order-id": 0,
            "sort-orders": [{"order-id": 0, "fields": []}],
            "properties": props or {},
            "current-snapshot-id": -1,
            "snapshots": [],
            "snapshot-log": [],
            "metadata-log": [],
        }
        loc = self._write_metadata(bucket, ns, name, metadata, 0)
        tables[name] = {
            "uuid": tuid,
            "location": location,
            "metadata_location": loc,
            "version": 0,
            "createdAt": time.time(),
        }
        self._kv_put(f"s3tables:tables:{bucket}:{ns}", tables)
        return {"metadata-location": loc, "metadata": metadata}

    def table_exists(self, bucket: str, ns: str, name: str) -> bool:
        return name in self.tables(bucket, ns)

    def load_table(self, bucket: str, ns: str, name: str) -> dict:
        return self._load_metadata_doc("tables", bucket, ns, name)

    def _load_metadata_doc(
        self, kind: str, bucket: str, ns: str, name: str
    ) -> dict:
        rec = self._registry(kind, bucket, ns).get(name)
        if rec is None:
            raise self._missing(kind, ns, name)
        loc = rec["metadata_location"]
        key = loc.split(f"s3://{bucket}/", 1)[1]
        entry = self.srv.filer.find_entry(f"/buckets/{bucket}/{key}")
        body = self.srv.filer.read_entry(entry)
        return {
            "metadata-location": loc,
            "metadata": json.loads(body),
            "config": {},
        }

    def commit_table(
        self,
        bucket: str,
        ns: str,
        name: str,
        updates: list,
        requirements: list | None = None,
    ) -> dict:
        with self._lock:
            return self._commit_table_locked(
                bucket, ns, name, updates, requirements
            )

    def _commit_table_locked(
        self,
        bucket: str,
        ns: str,
        name: str,
        updates: list,
        requirements: list | None = None,
    ) -> dict:
        """Apply a commit's updates (the Iceberg spec's
        TableUpdate kinds — see _apply_metadata_update); every commit
        writes a NEW metadata file and logs the old one. The
        `requirements` (TableRequirement) are the writer's optimistic-
        concurrency preconditions — a failed one MUST 409 so the
        client rebases and retries instead of silently clobbering a
        concurrent commit."""
        metadata = self._prepare_commit_locked(
            bucket, ns, name, updates, requirements
        )
        return self._persist_commit_locked(bucket, ns, name, metadata)

    def _prepare_commit_locked(
        self,
        bucket: str,
        ns: str,
        name: str,
        updates: list,
        requirements: list | None = None,
    ) -> dict:
        """Validate phase: check requirements and apply every update to
        an in-memory copy. Raises without persisting anything — the
        split lets commit_transaction validate ALL tables before any
        metadata file is written."""
        metadata = self.load_table(bucket, ns, name)["metadata"]
        for req in requirements or []:
            _check_table_requirement(metadata, req)
        for u in updates or []:
            _apply_metadata_update(metadata, u)
        return metadata

    def _stamp_and_write_locked(
        self, bucket: str, ns: str, name: str, metadata: dict
    ) -> tuple[str, int]:
        """Write the new metadata file; the catalog pointer is NOT
        moved yet. An orphaned file from a later failure is harmless —
        nothing references it."""
        rec = self.tables(bucket, ns)[name]
        metadata["last-updated-ms"] = int(time.time() * 1000)
        metadata.setdefault("metadata-log", []).append(
            {
                "timestamp-ms": metadata["last-updated-ms"],
                "metadata-file": rec["metadata_location"],
            }
        )
        version = rec.get("version", 0) + 1
        loc = self._write_metadata(bucket, ns, name, metadata, version)
        return loc, version

    def _swap_pointer_locked(
        self, bucket: str, ns: str, name: str, metadata: dict,
        loc: str, version: int,
    ) -> dict:
        tables = self.tables(bucket, ns)
        rec = tables[name]
        rec["metadata_location"] = loc
        rec["version"] = version
        # an assign-uuid commit must keep the catalog record (the
        # source of the S3Tables ARN) in step with the metadata
        rec["uuid"] = metadata.get("table-uuid", rec.get("uuid"))
        self._kv_put(f"s3tables:tables:{bucket}:{ns}", tables)
        return {"metadata-location": loc, "metadata": metadata}

    def _persist_commit_locked(
        self, bucket: str, ns: str, name: str, metadata: dict
    ) -> dict:
        loc, version = self._stamp_and_write_locked(bucket, ns, name, metadata)
        return self._swap_pointer_locked(
            bucket, ns, name, metadata, loc, version
        )

    def commit_transaction(self, bucket: str, table_changes: list) -> None:
        """Multi-table transaction (Iceberg REST /v1/transactions/commit):
        every change's requirements AND updates are validated first;
        only when the whole set passes is anything persisted, so a 409
        on table N leaves tables 1..N-1 untouched."""
        with self._lock:
            prepared = []
            seen = set()
            for ch in table_changes or []:
                ident = ch.get("identifier") or {}
                ns = ".".join(ident.get("namespace") or [])
                name = ident.get("name", "")
                if (ns, name) in seen:
                    # each prepare loads the PRE-transaction metadata:
                    # a second change for the same table would silently
                    # discard the first one's updates at persist time
                    raise TablesError(
                        400,
                        "BadRequestException",
                        f"duplicate table {ns}.{name} in transaction",
                    )
                seen.add((ns, name))
                prepared.append(
                    (
                        ns,
                        name,
                        self._prepare_commit_locked(
                            bucket,
                            ns,
                            name,
                            ch.get("updates", []),
                            ch.get("requirements", []),
                        ),
                    )
                )
            # metadata files first, catalog-pointer swaps last: a file
            # write failing mid-set leaves every pointer untouched
            # (orphaned files reference nothing); only the KV swaps —
            # small, local, far less failure-prone — remain after
            written = [
                (ns, name, metadata)
                + self._stamp_and_write_locked(bucket, ns, name, metadata)
                for ns, name, metadata in prepared
            ]
            for ns, name, metadata, loc, version in written:
                self._swap_pointer_locked(
                    bucket, ns, name, metadata, loc, version
                )

    def expire_snapshots(
        self, older_than_ms: int, bucket: str = "", dry_run: bool = False
    ) -> dict:
        """Snapshot expiry across the catalog (reference weed worker
        `iceberg` maintenance task: expire old table snapshots).
        Snapshots still reachable from any ref — including the current
        one — are NEVER expired regardless of age; expiry goes through
        the same remove-snapshots update path as a client commit, so
        snapshot-log/refs cleanup and metadata versioning are identical.
        """
        # enumerate under the lock, then sweep one table at a time so
        # API traffic only ever stalls behind ONE table's expiry, not
        # the whole catalog walk (each commit writes a metadata file)
        with self._lock:
            buckets = (
                [bucket]
                if bucket
                else sorted({DEFAULT_BUCKET, *self.buckets()})
            )
            idents = [
                (b, ns, t)
                for b in buckets
                for ns in self.namespaces(b)
                for t in self.tables(b, ns)
            ]
        out = {
            "tables_scanned": 0,
            "tables_updated": 0,
            "snapshots_expired": 0,
        }
        for b, ns, t in idents:
            with self._lock:
                try:
                    md = self.load_table(b, ns, t)["metadata"]
                except (TablesError, NotFound):
                    continue  # dropped since enumeration
                out["tables_scanned"] += 1
                keep = {
                    r.get("snapshot-id")
                    for r in md.get("refs", {}).values()
                }
                cur = md.get("current-snapshot-id", -1)
                if cur != -1:
                    keep.add(cur)
                stale = [
                    s["snapshot-id"]
                    for s in md.get("snapshots", [])
                    if s.get("timestamp-ms", 0) < older_than_ms
                    and s.get("snapshot-id") not in keep
                ]
                if not stale:
                    continue
                out["tables_updated"] += 1
                out["snapshots_expired"] += len(stale)
                if not dry_run:
                    self._commit_table_locked(
                        b,
                        ns,
                        t,
                        [
                            {
                                "action": "remove-snapshots",
                                "snapshot-ids": stale,
                            }
                        ],
                    )
        return out

    # ------------------------------------ kind-generic drop/rename/load
    # ("tables" | "views": one registry layout, one exception naming
    # scheme — a private copy per kind is how the cross-kind identifier
    # invariant gets missed)

    _NOT_FOUND = {
        "tables": ("table", "NoSuchTableException"),
        "views": ("view", "NoSuchViewException"),
    }

    def _registry(self, kind: str, bucket: str, ns: str) -> dict:
        return self._kv(f"s3tables:{kind}:{bucket}:{ns}")

    def _missing(self, kind: str, ns: str, name: str) -> TablesError:
        noun, exc = self._NOT_FOUND[kind]
        return TablesError(404, exc, f"{noun} {ns}.{name} not found")

    def _check_identifier_free(
        self, bucket: str, ns: str, name: str, skip: tuple = ()
    ) -> None:
        """Spec invariant: a table and a view can never share an
        identifier. skip: (kind, ns, name) of the record being moved,
        so a same-name rename does not collide with itself."""
        for kind in ("tables", "views"):
            if (kind, ns, name) == skip:
                continue
            if name in self._registry(kind, bucket, ns):
                noun, _ = self._NOT_FOUND[kind]
                raise TablesError(
                    409,
                    "AlreadyExistsException",
                    f"a {noun} named {name} exists in {ns}",
                )

    def _drop_locked(self, kind: str, bucket: str, ns: str, name: str) -> None:
        reg = self._registry(kind, bucket, ns)
        if name not in reg:
            raise self._missing(kind, ns, name)
        reg.pop(name)
        self._kv_put(f"s3tables:{kind}:{bucket}:{ns}", reg)

    def _rename_locked(
        self, kind: str, bucket: str,
        src_ns: str, src: str, dst_ns: str, dst: str,
    ) -> None:
        self.require_namespace(bucket, dst_ns)
        src_reg = self._registry(kind, bucket, src_ns)
        rec = src_reg.get(src)
        if rec is None:
            raise self._missing(kind, src_ns, src)
        self._check_identifier_free(
            bucket, dst_ns, dst, skip=(kind, src_ns, src)
        )
        src_reg.pop(src)
        self._kv_put(f"s3tables:{kind}:{bucket}:{src_ns}", src_reg)
        dst_reg = self._registry(kind, bucket, dst_ns)
        dst_reg[dst] = rec
        self._kv_put(f"s3tables:{kind}:{bucket}:{dst_ns}", dst_reg)

    def drop_table(self, bucket: str, ns: str, name: str) -> None:
        with self._lock:
            self._drop_locked("tables", bucket, ns, name)

    def rename_table(
        self, bucket: str, src_ns: str, src: str, dst_ns: str, dst: str
    ) -> None:
        _check_name("table", dst)
        with self._lock:
            self._rename_locked("tables", bucket, src_ns, src, dst_ns, dst)


    # ------------------------------------------------------------ views

    def views(self, bucket: str, ns: str) -> dict:
        return self._kv(f"s3tables:views:{bucket}:{ns}")

    def create_view(
        self,
        bucket: str,
        ns: str,
        name: str,
        schema: dict,
        view_version: dict,
        props: dict,
    ) -> dict:
        """Iceberg view (spec view metadata v1): versions carry the SQL
        representations; reference weed/s3api/iceberg view routes."""
        _check_name("view", name)
        with self._lock:
            self.require_namespace(bucket, ns)
            self._check_identifier_free(bucket, ns, name)
            views = self.views(bucket, ns)
            schema = schema or {
                "type": "struct", "schema-id": 0, "fields": [],
            }
            schema.setdefault("schema-id", 0)
            version = dict(view_version or {})
            version.setdefault("version-id", 1)
            version.setdefault("timestamp-ms", int(time.time() * 1000))
            version.setdefault("schema-id", schema["schema-id"])
            version.setdefault("summary", {})
            version.setdefault("representations", [])
            version.setdefault("default-namespace", ns.split("."))
            vuid = str(uuid.uuid4())
            metadata = {
                "view-uuid": vuid,
                "format-version": 1,
                "location": f"s3://{bucket}/{ns}/{name}",
                "schemas": [schema],
                "current-version-id": version["version-id"],
                "versions": [version],
                "version-log": [
                    {
                        "timestamp-ms": version["timestamp-ms"],
                        "version-id": version["version-id"],
                    }
                ],
                "properties": props or {},
            }
            loc = self._write_metadata(bucket, ns, name, metadata, 0)
            views[name] = {
                "uuid": vuid,
                "metadata_location": loc,
                "version": 0,
                "createdAt": time.time(),
            }
            self._kv_put(f"s3tables:views:{bucket}:{ns}", views)
            return {"metadata-location": loc, "metadata": metadata}

    def view_exists(self, bucket: str, ns: str, name: str) -> bool:
        return name in self.views(bucket, ns)

    def load_view(self, bucket: str, ns: str, name: str) -> dict:
        return self._load_metadata_doc("views", bucket, ns, name)

    def drop_view(self, bucket: str, ns: str, name: str) -> None:
        with self._lock:
            self._drop_locked("views", bucket, ns, name)

    def rename_view(
        self, bucket: str, src_ns: str, src: str, dst_ns: str, dst: str
    ) -> None:
        _check_name("view", dst)
        with self._lock:
            self._rename_locked("views", bucket, src_ns, src, dst_ns, dst)

    def commit_view(
        self,
        bucket: str,
        ns: str,
        name: str,
        updates: list,
        requirements: list | None = None,
    ) -> dict:
        with self._lock:
            metadata = self.load_view(bucket, ns, name)["metadata"]
            for req in requirements or []:
                typ = req.get("type", "")
                if typ == "assert-view-uuid":
                    want = req.get("uuid")
                    if metadata.get("view-uuid") != want:
                        raise TablesError(
                            409,
                            "CommitFailedException",
                            f"requirement assert-view-uuid: expected "
                            f"{want}, view has {metadata.get('view-uuid')}",
                        )
                else:
                    raise TablesError(
                        400,
                        "BadRequestException",
                        f"unknown view requirement type {typ!r}",
                    )
            for u in updates or []:
                _apply_view_update(metadata, u)
            views = self.views(bucket, ns)
            rec = views[name]
            version = rec.get("version", 0) + 1
            loc = self._write_metadata(bucket, ns, name, metadata, version)
            rec["metadata_location"] = loc
            rec["version"] = version
            rec["uuid"] = metadata.get("view-uuid", rec.get("uuid"))
            self._kv_put(f"s3tables:views:{bucket}:{ns}", views)
            return {"metadata-location": loc, "metadata": metadata}


def _apply_view_update(metadata: dict, u: dict) -> None:
    """One Iceberg ViewUpdate (the spec's kinds for view commits).
    Unknown kinds fail loudly, mirroring _apply_metadata_update."""
    action = u.get("action", "")
    if action == "assign-uuid":
        metadata["view-uuid"] = u.get("uuid", metadata["view-uuid"])
    elif action == "set-properties":
        metadata["properties"].update(u.get("updates", {}))
    elif action == "remove-properties":
        for k in u.get("removals", []):
            metadata["properties"].pop(k, None)
    elif action == "set-location":
        metadata["location"] = u.get("location", metadata["location"])
    elif action == "add-schema":
        schema = u.get("schema") or {}
        metadata.setdefault("schemas", []).append(schema)
    elif action == "add-view-version":
        version = dict(u.get("view-version") or {})
        if "version-id" not in version:
            raise TablesError(
                400, "BadRequestException",
                "add-view-version needs a version-id",
            )
        if any(
            v.get("version-id") == version["version-id"]
            for v in metadata.get("versions", [])
        ):
            raise TablesError(
                409, "ConflictException",
                f"view version {version['version-id']} already exists",
            )
        version.setdefault("timestamp-ms", int(time.time() * 1000))
        metadata.setdefault("versions", []).append(version)
    elif action == "set-current-view-version":
        vid = int(u.get("view-version-id", -1))
        if vid == -1:  # spec: -1 = the version added in this commit
            vid = metadata["versions"][-1].get("version-id")
        if not any(
            v.get("version-id") == vid
            for v in metadata.get("versions", [])
        ):
            raise TablesError(
                400, "BadRequestException", f"unknown view version {vid}"
            )
        metadata["current-version-id"] = vid
        metadata.setdefault("version-log", []).append(
            {"timestamp-ms": int(time.time() * 1000), "version-id": vid}
        )
    else:
        raise TablesError(
            400, "BadRequestException", f"unknown view update {action!r}"
        )


def _max_field_id(node) -> int:
    """Largest field/element/key/value id anywhere in an Iceberg schema
    tree (struct fields, list element-id, map key-id/value-id)."""
    best = 0
    if isinstance(node, dict):
        for k in ("id", "element-id", "key-id", "value-id"):
            v = node.get(k)
            if isinstance(v, int):
                best = max(best, v)
        for v in node.values():
            if isinstance(v, (dict, list)):
                best = max(best, _max_field_id(v))
    elif isinstance(node, list):
        for item in node:
            best = max(best, _max_field_id(item))
    return best


# requirement type -> (request key, metadata key): all five "assert this
# id matches" kinds are one compare
_ID_REQUIREMENTS = {
    "assert-last-assigned-field-id": (
        "last-assigned-field-id", "last-column-id",
    ),
    "assert-current-schema-id": ("current-schema-id", "current-schema-id"),
    "assert-last-assigned-partition-id": (
        "last-assigned-partition-id", "last-partition-id",
    ),
    "assert-default-spec-id": ("default-spec-id", "default-spec-id"),
    "assert-default-sort-order-id": (
        "default-sort-order-id", "default-sort-order-id",
    ),
}


def _check_table_requirement(metadata: dict, req: dict) -> None:
    """One Iceberg TableRequirement (the commit's optimistic-concurrency
    precondition, reference weed/s3api iceberg catalog + Iceberg REST
    spec). Violations raise 409 CommitFailedException so the writer
    rebases; unknown kinds fail loudly like unknown updates do."""

    def fail(what: str) -> None:
        raise TablesError(409, "CommitFailedException", what)

    typ = req.get("type", "")
    if typ == "assert-create":
        # commit of an existing table can never satisfy assert-create
        fail("requirement assert-create: table already exists")
    elif typ == "assert-table-uuid":
        want = req.get("uuid")
        if metadata.get("table-uuid") != want:
            fail(
                f"requirement assert-table-uuid: expected {want}, "
                f"table has {metadata.get('table-uuid')}"
            )
    elif typ == "assert-ref-snapshot-id":
        ref = req.get("ref", "")
        want = req.get("snapshot-id")  # null = ref must not exist
        have = metadata.get("refs", {}).get(ref)
        have_id = have.get("snapshot-id") if have else None
        if want is None:
            if have is not None:
                fail(f"requirement assert-ref-snapshot-id: ref {ref} exists")
        elif have is None or have_id != want:
            fail(
                f"requirement assert-ref-snapshot-id: ref {ref} is at "
                f"{have_id}, expected {want}"
            )
    elif typ in _ID_REQUIREMENTS:
        req_key, md_key = _ID_REQUIREMENTS[typ]
        want = req.get(req_key)
        if metadata.get(md_key) != want:
            fail(
                f"requirement {typ}: table has {metadata.get(md_key)}, "
                f"expected {want}"
            )
    else:
        raise TablesError(
            400, "BadRequestException", f"unknown requirement type {typ!r}"
        )


def _apply_metadata_update(metadata: dict, u: dict) -> None:
    """One Iceberg TableUpdate against the v2 metadata JSON (the kinds
    real writers — pyiceberg, Spark — emit in commits). Unknown kinds
    fail loudly: silently dropping an update would corrupt the table's
    history invisibly."""
    action = u.get("action", "")
    if action == "set-properties":
        metadata["properties"].update(u.get("updates", {}))
    elif action == "remove-properties":
        for k in u.get("removals", []):
            metadata["properties"].pop(k, None)
    elif action == "assign-uuid":
        metadata["table-uuid"] = u.get("uuid", metadata["table-uuid"])
    elif action == "upgrade-format-version":
        fv = int(u.get("format-version", metadata["format-version"]))
        if fv < metadata["format-version"]:
            raise TablesError(
                400, "BadRequestException", "cannot downgrade format-version"
            )
        if fv > 2:
            # this catalog writes v2 metadata; CLAIMING v3 without its
            # required fields (next-row-id, ...) would persist files
            # spec-compliant readers reject
            raise TablesError(
                400,
                "UnsupportedOperationException",
                f"format-version {fv} not supported (v2 catalog)",
            )
        metadata["format-version"] = fv
    elif action == "set-location":
        metadata["location"] = u.get("location", metadata["location"])
    elif action == "add-schema":
        schema = u.get("schema") or {}
        metadata.setdefault("schemas", []).append(schema)
        lc = u.get("last-column-id")
        if lc is None:
            # the highest field id can live inside a nested struct /
            # list / map — a top-level-only scan would persist a
            # too-low last-column-id and 409 correct writers later
            lc = max(
                _max_field_id(schema),
                metadata.get("last-column-id", 0),
            )
        metadata["last-column-id"] = max(
            metadata.get("last-column-id", 0), int(lc)
        )
    elif action == "set-current-schema":
        sid = int(u.get("schema-id", -1))
        if sid == -1:  # spec: -1 = the schema added in this commit
            sid = metadata["schemas"][-1].get("schema-id", 0)
        if not any(
            sc.get("schema-id") == sid for sc in metadata.get("schemas", [])
        ):
            raise TablesError(
                400, "BadRequestException", f"unknown schema-id {sid}"
            )
        metadata["current-schema-id"] = sid
    elif action == "add-spec":
        metadata.setdefault("partition-specs", []).append(u.get("spec") or {})
        fields = (u.get("spec") or {}).get("fields", [])
        metadata["last-partition-id"] = max(
            metadata.get("last-partition-id", 999),
            max((f.get("field-id", 0) for f in fields), default=0),
        )
    elif action == "set-default-spec":
        sid = int(u.get("spec-id", -1))
        if sid == -1:
            sid = metadata["partition-specs"][-1].get("spec-id", 0)
        if not any(
            sp.get("spec-id") == sid
            for sp in metadata.get("partition-specs", [])
        ):
            raise TablesError(
                400, "BadRequestException", f"unknown spec-id {sid}"
            )
        metadata["default-spec-id"] = sid
    elif action == "add-sort-order":
        metadata.setdefault("sort-orders", []).append(
            u.get("sort-order") or {}
        )
    elif action == "set-default-sort-order":
        oid = int(u.get("sort-order-id", -1))
        if oid == -1:
            oid = metadata["sort-orders"][-1].get("order-id", 0)
        if not any(
            so.get("order-id") == oid
            for so in metadata.get("sort-orders", [])
        ):
            raise TablesError(
                400, "BadRequestException", f"unknown sort-order-id {oid}"
            )
        metadata["default-sort-order-id"] = oid
    elif action == "add-snapshot":
        snap = u.get("snapshot") or {}
        if "snapshot-id" not in snap:
            raise TablesError(
                400, "BadRequestException", "snapshot needs snapshot-id"
            )
        metadata.setdefault("snapshots", []).append(snap)
        metadata["last-sequence-number"] = max(
            metadata.get("last-sequence-number", 0),
            int(snap.get("sequence-number", 0)),
        )
    elif action == "set-snapshot-ref":
        ref = u.get("ref-name", "main")
        sid = int(u.get("snapshot-id", -1))
        if not any(
            sn.get("snapshot-id") == sid
            for sn in metadata.get("snapshots", [])
        ):
            raise TablesError(
                400, "BadRequestException", f"unknown snapshot-id {sid}"
            )
        metadata.setdefault("refs", {})[ref] = {
            "snapshot-id": sid,
            "type": u.get("type", "branch"),
        }
        if ref == "main":
            metadata["current-snapshot-id"] = sid
            metadata.setdefault("snapshot-log", []).append(
                {
                    "timestamp-ms": int(time.time() * 1000),
                    "snapshot-id": sid,
                }
            )
    elif action == "remove-snapshot-ref":
        ref = u.get("ref-name", "")
        metadata.get("refs", {}).pop(ref, None)
        if ref == "main":
            # removing the main branch leaves no current snapshot
            metadata["current-snapshot-id"] = -1
    elif action == "remove-snapshots":
        gone = set(u.get("snapshot-ids", []))
        metadata["snapshots"] = [
            sn
            for sn in metadata.get("snapshots", [])
            if sn.get("snapshot-id") not in gone
        ]
        # nothing may keep POINTING at an expired snapshot: drop refs,
        # log entries, and the current pointer with it
        metadata["refs"] = {
            rn: rv
            for rn, rv in metadata.get("refs", {}).items()
            if rv.get("snapshot-id") not in gone
        }
        metadata["snapshot-log"] = [
            e
            for e in metadata.get("snapshot-log", [])
            if e.get("snapshot-id") not in gone
        ]
        if metadata.get("current-snapshot-id") in gone:
            metadata["current-snapshot-id"] = -1
    else:
        raise TablesError(
            400,
            "UnsupportedOperationException",
            f"unsupported metadata update {action!r}",
        )


# ------------------------------------------------------------ handlers


def _json_resp(h, code: int, doc: dict | list | None = None) -> None:
    body = b"" if doc is None else json.dumps(doc).encode()
    h.send_response(code)
    h.send_header("Content-Type", "application/json")
    h.send_header("Content-Length", str(len(body)))
    h.end_headers()
    if body and h.command != "HEAD":
        h.wfile.write(body)


def _err(h, e: TablesError) -> None:
    _json_resp(
        h,
        e.code,
        {"error": {"message": str(e), "type": e.typ, "code": e.code}},
    )


def _ns_of(part: str) -> str:
    # Iceberg multipart namespaces join on the 0x1F unit separator
    return urllib.parse.unquote(part).replace("\x1f", ".")


def handle_iceberg(h, catalog: TablesCatalog, path: str) -> None:
    """Route /iceberg/v1/... (optionally /iceberg/v1/{prefix}/... where
    prefix names a table bucket)."""
    parts = [p for p in path.split("/") if p][2:]  # drop iceberg, v1
    m = h.command
    try:
        if parts == ["config"]:
            warehouse = urllib.parse.parse_qs(
                urllib.parse.urlparse(h.path).query
            ).get("warehouse", [DEFAULT_BUCKET])[0]
            return _json_resp(
                h,
                200,
                {
                    "defaults": {"prefix": warehouse},
                    "overrides": {},
                },
            )
        # optional {prefix} segment = table bucket
        bucket = DEFAULT_BUCKET
        if parts and parts[0] not in (
            "namespaces", "tables", "views", "transactions", "maintenance",
        ):
            bucket = urllib.parse.unquote(parts[0])
            parts = parts[1:]
        body = {}
        if m == "POST":
            raw = h._read_body()
            if raw:
                body = json.loads(raw)
        if parts == ["transactions", "commit"] and m == "POST":
            catalog.commit_transaction(
                bucket, body.get("table-changes", [])
            )
            return _json_resp(h, 204)
        if parts == ["maintenance"] and m == "POST":
            # catalog maintenance: snapshot expiry (the worker fleet's
            # `iceberg` task posts here; operators can too)
            older = body.get("older-than-ms")
            if older is None:
                days = float(body.get("older-than-days", 30))
                older = int(time.time() * 1000) - int(days * 86400_000)
            out = catalog.expire_snapshots(
                int(older),
                bucket="" if body.get("all-buckets") else bucket,
                dry_run=bool(body.get("dry-run")),
            )
            return _json_resp(h, 200, out)
        if parts == ["namespaces"]:
            if m == "GET":
                return _json_resp(
                    h,
                    200,
                    {
                        "namespaces": [
                            ns.split(".")
                            for ns in sorted(catalog.namespaces(bucket))
                        ]
                    },
                )
            if m == "POST":
                ns = ".".join(body.get("namespace", []))
                if not ns:
                    raise TablesError(
                        400, "BadRequestException", "namespace required"
                    )
                if bucket == DEFAULT_BUCKET and not catalog.buckets().get(
                    bucket
                ):
                    catalog.create_bucket(bucket)
                catalog.create_namespace(
                    bucket, ns, body.get("properties", {})
                )
                return _json_resp(
                    h,
                    200,
                    {
                        "namespace": ns.split("."),
                        "properties": body.get("properties", {}),
                    },
                )
        if len(parts) == 2 and parts[0] == "namespaces":
            ns = _ns_of(parts[1])
            if m in ("GET", "HEAD"):
                rec = catalog.require_namespace(bucket, ns)
                if m == "HEAD":
                    return _json_resp(h, 204)
                return _json_resp(
                    h,
                    200,
                    {
                        "namespace": ns.split("."),
                        "properties": rec["properties"],
                    },
                )
            if m == "DELETE":
                catalog.drop_namespace(bucket, ns)
                return _json_resp(h, 204)
        if (
            len(parts) == 3
            and parts[0] == "namespaces"
            and parts[2] == "properties"
            and m == "POST"
        ):
            ns = _ns_of(parts[1])
            out = catalog.update_namespace_props(
                bucket, ns, body.get("removals", []), body.get("updates", {})
            )
            return _json_resp(h, 200, out)
        if len(parts) == 3 and parts[0] == "namespaces" and parts[2] == "tables":
            ns = _ns_of(parts[1])
            if m == "GET":
                catalog.require_namespace(bucket, ns)
                return _json_resp(
                    h,
                    200,
                    {
                        "identifiers": [
                            {"namespace": ns.split("."), "name": t}
                            for t in sorted(catalog.tables(bucket, ns))
                        ]
                    },
                )
            if m == "POST":
                out = catalog.create_table(
                    bucket,
                    ns,
                    body.get("name", ""),
                    body.get("schema"),
                    body.get("properties", {}),
                )
                return _json_resp(h, 200, out)
        if len(parts) == 4 and parts[0] == "namespaces" and parts[2] == "tables":
            ns, table = _ns_of(parts[1]), urllib.parse.unquote(parts[3])
            if m == "HEAD":
                if not catalog.table_exists(bucket, ns, table):
                    raise TablesError(
                        404, "NoSuchTableException", f"{ns}.{table}"
                    )
                return _json_resp(h, 204)
            if m == "GET":
                return _json_resp(
                    h, 200, catalog.load_table(bucket, ns, table)
                )
            if m == "DELETE":
                catalog.drop_table(bucket, ns, table)
                return _json_resp(h, 204)
            if m == "POST":  # commit
                out = catalog.commit_table(
                    bucket,
                    ns,
                    table,
                    body.get("updates", []),
                    body.get("requirements", []),
                )
                return _json_resp(h, 200, out)
        if len(parts) == 3 and parts[0] == "namespaces" and parts[2] == "views":
            ns = _ns_of(parts[1])
            if m == "GET":
                catalog.require_namespace(bucket, ns)
                return _json_resp(
                    h,
                    200,
                    {
                        "identifiers": [
                            {"namespace": ns.split("."), "name": v}
                            for v in sorted(catalog.views(bucket, ns))
                        ]
                    },
                )
            if m == "POST":
                out = catalog.create_view(
                    bucket,
                    ns,
                    body.get("name", ""),
                    body.get("schema"),
                    body.get("view-version"),
                    body.get("properties", {}),
                )
                return _json_resp(h, 200, out)
        if len(parts) == 4 and parts[0] == "namespaces" and parts[2] == "views":
            ns, view = _ns_of(parts[1]), urllib.parse.unquote(parts[3])
            if m == "HEAD":
                if not catalog.view_exists(bucket, ns, view):
                    raise TablesError(
                        404, "NoSuchViewException", f"{ns}.{view}"
                    )
                return _json_resp(h, 204)
            if m == "GET":
                return _json_resp(h, 200, catalog.load_view(bucket, ns, view))
            if m == "DELETE":
                catalog.drop_view(bucket, ns, view)
                return _json_resp(h, 204)
            if m == "POST":  # commit (replace view)
                out = catalog.commit_view(
                    bucket,
                    ns,
                    view,
                    body.get("updates", []),
                    body.get("requirements", []),
                )
                return _json_resp(h, 200, out)
        if parts == ["views", "rename"] and m == "POST":
            src, dst = body.get("source", {}), body.get("destination", {})
            catalog.rename_view(
                bucket,
                ".".join(src.get("namespace", [])),
                src.get("name", ""),
                ".".join(dst.get("namespace", [])),
                dst.get("name", ""),
            )
            return _json_resp(h, 204)
        if parts == ["tables", "rename"] and m == "POST":
            src, dst = body.get("source", {}), body.get("destination", {})
            catalog.rename_table(
                bucket,
                ".".join(src.get("namespace", [])),
                src.get("name", ""),
                ".".join(dst.get("namespace", [])),
                dst.get("name", ""),
            )
            return _json_resp(h, 204)
        raise TablesError(404, "NotFoundException", f"no route {m} {path}")
    except TablesError as e:
        return _err(h, e)
    except NotFound as e:
        return _err(h, TablesError(404, "NotFoundException", str(e)))
    except (ValueError, KeyError, TypeError) as e:
        # TypeError: JSON null / wrong-shaped values hitting int()/float()
        return _err(h, TablesError(400, "BadRequestException", str(e)))


def _arn_bucket(arn: str) -> str:
    return urllib.parse.unquote(arn).rsplit("/", 1)[-1]


def handle_s3tables(h, catalog: TablesCatalog) -> None:
    """AWS S3Tables ops: X-Amz-Target JSON posts AND the CLI's ARN REST
    paths (reference s3api_tables.go)."""
    target = h.headers.get("X-Amz-Target", "")
    u = urllib.parse.urlparse(h.path)
    path = urllib.parse.unquote(u.path)
    m = h.command
    try:
        body = {}
        if m in ("POST", "PUT"):
            raw = h._read_body()
            if raw:
                body = json.loads(raw)
        op = target[len("S3Tables.") :] if target else ""
        if not op:  # REST routing; the ARN itself contains a slash, so
            # split AROUND it with the reference's regex
            # (s3api_tables.go tableBucketARNRegex)
            kind = path.split("/", 2)[1] if path.count("/") else ""
            arn_m = _ARN_RE.search(path)
            arn = arn_m.group(0) if arn_m else ""
            rest = (
                [s for s in path[arn_m.end() :].split("/") if s]
                if arn_m
                else []
            )
            if kind == "buckets":
                if m == "PUT" and not arn:
                    op = "CreateTableBucket"
                elif m == "GET" and not arn:
                    op = "ListTableBuckets"
                elif m == "GET":
                    op, body = "GetTableBucket", {"tableBucketARN": arn}
                elif m == "DELETE":
                    op, body = "DeleteTableBucket", {"tableBucketARN": arn}
            elif kind == "namespaces" and arn:
                if m == "PUT":
                    body = {**body, "tableBucketARN": arn}
                    op = "CreateNamespace"
                elif m == "GET" and not rest:
                    op, body = "ListNamespaces", {"tableBucketARN": arn}
                elif m == "GET" and rest:
                    op = "GetNamespace"
                    body = {"tableBucketARN": arn, "namespace": rest[0]}
                elif m == "DELETE" and rest:
                    op = "DeleteNamespace"
                    body = {"tableBucketARN": arn, "namespace": rest[0]}
            elif kind == "tables" and arn:
                if m == "PUT" and rest:
                    body = {
                        **body,
                        "tableBucketARN": arn,
                        "namespace": rest[0],
                    }
                    op = "CreateTable"
                elif m == "GET" and not rest:
                    op, body = "ListTables", {"tableBucketARN": arn}
                elif m == "GET" and len(rest) >= 2:
                    op = "GetTable"
                    body = {
                        "tableBucketARN": arn,
                        "namespace": rest[0],
                        "name": rest[1],
                    }
                elif m == "DELETE" and len(rest) >= 2:
                    op = "DeleteTable"
                    body = {
                        "tableBucketARN": arn,
                        "namespace": rest[0],
                        "name": rest[1],
                    }
        if not op:
            raise TablesError(400, "BadRequestException", "unroutable request")

        if op == "CreateTableBucket":
            rec = catalog.create_bucket(body.get("name", ""))
            return _json_resp(h, 200, {"arn": rec["arn"]})
        if op == "ListTableBuckets":
            return _json_resp(
                h,
                200,
                {
                    "tableBuckets": [
                        {"arn": rec["arn"], "name": name}
                        for name, rec in sorted(catalog.buckets().items())
                    ]
                },
            )
        if op == "GetTableBucket":
            name = _arn_bucket(body["tableBucketARN"])
            rec = catalog.require_bucket(name)
            return _json_resp(h, 200, {"arn": rec["arn"], "name": name})
        if op == "DeleteTableBucket":
            catalog.delete_bucket(_arn_bucket(body["tableBucketARN"]))
            return _json_resp(h, 204)
        if op == "CreateNamespace":
            bucket = _arn_bucket(body["tableBucketARN"])
            ns = body.get("namespace", [])
            ns = ns[0] if isinstance(ns, list) else ns
            catalog.create_namespace(bucket, ns, {})
            return _json_resp(
                h, 200, {"namespace": [ns], "tableBucketARN": body["tableBucketARN"]}
            )
        if op == "ListNamespaces":
            bucket = _arn_bucket(body["tableBucketARN"])
            catalog.require_bucket(bucket)
            return _json_resp(
                h,
                200,
                {
                    "namespaces": [
                        {"namespace": [ns]}
                        for ns in sorted(catalog.namespaces(bucket))
                    ]
                },
            )
        if op == "GetNamespace":
            bucket = _arn_bucket(body["tableBucketARN"])
            ns = body["namespace"]
            catalog.require_namespace(bucket, ns)
            return _json_resp(h, 200, {"namespace": [ns]})
        if op == "DeleteNamespace":
            catalog.drop_namespace(
                _arn_bucket(body["tableBucketARN"]), body["namespace"]
            )
            return _json_resp(h, 204)
        if op == "CreateTable":
            bucket = _arn_bucket(body["tableBucketARN"])
            out = catalog.create_table(
                bucket,
                body["namespace"],
                body.get("name", ""),
                None,
                {},
            )
            tables = catalog.tables(bucket, body["namespace"])
            rec = tables[body["name"]]
            return _json_resp(
                h,
                200,
                {
                    "tableARN": f"arn:aws:s3tables:local:000000000000:"
                    f"bucket/{bucket}/table/{rec['uuid']}",
                    "versionToken": str(rec["version"]),
                    "metadataLocation": out["metadata-location"],
                },
            )
        if op == "ListTables":
            bucket = _arn_bucket(body["tableBucketARN"])
            catalog.require_bucket(bucket)
            out = []
            for ns in sorted(catalog.namespaces(bucket)):
                for t in sorted(catalog.tables(bucket, ns)):
                    out.append({"namespace": [ns], "name": t})
            return _json_resp(h, 200, {"tables": out})
        if op == "GetTable":
            bucket = _arn_bucket(body["tableBucketARN"])
            loaded = catalog.load_table(
                bucket, body["namespace"], body["name"]
            )
            return _json_resp(
                h,
                200,
                {
                    "name": body["name"],
                    "namespace": [body["namespace"]],
                    "metadataLocation": loaded["metadata-location"],
                    "format": "ICEBERG",
                },
            )
        if op == "DeleteTable":
            catalog.drop_table(
                _arn_bucket(body["tableBucketARN"]),
                body["namespace"],
                body["name"],
            )
            return _json_resp(h, 204)
        raise TablesError(
            400, "UnsupportedOperationException", f"unsupported op {op}"
        )
    except TablesError as e:
        return _err(h, e)
    except NotFound as e:
        return _err(h, TablesError(404, "NotFoundException", str(e)))
    except (ValueError, KeyError, TypeError) as e:
        # TypeError: JSON null / wrong-shaped values hitting int()/float()
        return _err(h, TablesError(400, "BadRequestException", str(e)))
