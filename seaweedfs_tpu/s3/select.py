"""S3 Select (SelectObjectContent): SQL over one object's content.

Reference: weed/s3api query/RPC surface (the reference volume server
exposes a Query RPC and the s3api a ?select&select-type=2 route). The
expression engine is the framework's own SQL executor (query/engine):
the S3-Select dialect's `SELECT ... FROM S3Object s WHERE s.col ...`
is normalized (alias stripping) and run through QueryEngine.execute_rows
over rows parsed from the object (CSV with header modes, JSON lines or
document, optional gzip), then serialized back as CSV/JSON records
inside the AWS event-stream framing real SDK clients parse.
"""

from __future__ import annotations

import gzip as _gzip
import io
import json
import re
import struct
import zlib

from ..query.engine import QueryEngine, QueryError, Select, parse

# ---------------------------------------------------------------- input


def _rows_csv(data: bytes, conf: dict):
    import csv

    delim = conf.get("FieldDelimiter") or ","
    quote = conf.get("QuoteCharacter") or '"'
    header = (conf.get("FileHeaderInfo") or "NONE").upper()
    text = io.StringIO(data.decode("utf-8", "replace"))
    reader = csv.reader(text, delimiter=delim, quotechar=quote)
    names: list[str] | None = None
    for i, rec in enumerate(reader):
        if not rec:
            continue
        if i == 0 and header in ("USE", "IGNORE"):
            if header == "USE":
                names = rec
            continue
        if names:
            yield {names[j]: _coerce(v) for j, v in enumerate(rec) if j < len(names)}
        else:
            # positional columns: _1.._N (AWS semantics for NONE/IGNORE)
            yield {f"_{j + 1}": _coerce(v) for j, v in enumerate(rec)}


def _coerce(v: str):
    """CSV fields are text; numeric-looking values compare numerically
    (matching the engine's JSON-typed rows)."""
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


def _rows_json(data: bytes, conf: dict):
    kind = (conf.get("Type") or "DOCUMENT").upper()
    if kind == "LINES":
        for line in data.splitlines():
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if isinstance(doc, dict):
                yield doc
    else:
        doc = json.loads(data or b"null")
        if isinstance(doc, list):
            for d in doc:
                if isinstance(d, dict):
                    yield d
        elif isinstance(doc, dict):
            yield doc


def parse_rows(data: bytes, input_ser: dict):
    if (input_ser.get("CompressionType") or "NONE").upper() == "GZIP":
        data = _gzip.decompress(data)
    if "JSON" in input_ser:
        return _rows_json(data, input_ser["JSON"])
    return _rows_csv(data, input_ser.get("CSV", {}))


# ----------------------------------------------------------- expression

_ALIAS_RE = re.compile(r"\bFROM\s+S3Object(?:\s+(?:AS\s+)?(\w+))?", re.I)


def normalize_expression(expr: str) -> str:
    """S3-Select dialect -> the engine's dialect: resolve the S3Object
    alias and strip its prefix from column references — OUTSIDE string
    literals only (a literal like 's.local' must survive intact)."""
    m = _ALIAS_RE.search(expr)
    alias = None
    if m:
        alias = m.group(1)
        expr = expr[: m.start()] + " FROM s3object " + expr[m.end() :]
    prefixes = [p for p in {alias, "s3object", "S3Object"} if p]
    # split on single-quoted spans (SQL escapes quotes by doubling, so
    # '' stays inside one span); rewrite only even (unquoted) segments
    parts = re.split(r"('(?:[^']|'')*')", expr)
    for i in range(0, len(parts), 2):
        for prefix in prefixes:
            parts[i] = re.sub(
                rf"\b{re.escape(prefix)}\.(\w+)", r"\1", parts[i]
            )
    return "".join(parts)


# ------------------------------------------------------------- output


def serialize_rows(result, output_ser: dict) -> bytes:
    if "JSON" in output_ser:
        rd = output_ser["JSON"].get("RecordDelimiter") or "\n"
        out = []
        for row in result.rows:
            out.append(
                json.dumps(
                    {
                        c: v
                        for c, v in zip(result.columns, row)
                        if v is not None
                    }
                )
            )
        return (rd.join(out) + (rd if out else "")).encode()
    conf = output_ser.get("CSV", {})
    delim = conf.get("FieldDelimiter") or ","
    rd = conf.get("RecordDelimiter") or "\n"
    import csv

    buf = io.StringIO()
    w = csv.writer(buf, delimiter=delim, lineterminator=rd)
    for row in result.rows:
        w.writerow(["" if v is None else v for v in row])
    return buf.getvalue().encode()


# --------------------------------------------------------- event stream


def _event_message(headers: list[tuple[str, str]], payload: bytes) -> bytes:
    """AWS event-stream message: [total u32][hdr_len u32][prelude crc]
    [headers][payload][message crc] — the framing every AWS SDK's
    SelectObjectContent reader expects."""
    hdr = b""
    for name, value in headers:
        nb = name.encode()
        vb = value.encode()
        hdr += struct.pack(">B", len(nb)) + nb
        hdr += b"\x07" + struct.pack(">H", len(vb)) + vb  # type 7: string
    total = 12 + len(hdr) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hdr))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


def event_stream(records: bytes, scanned: int, processed: int) -> bytes:
    """Records + Stats + End events."""
    out = b""
    if records:
        out += _event_message(
            [
                (":message-type", "event"),
                (":event-type", "Records"),
                (":content-type", "application/octet-stream"),
            ],
            records,
        )
    stats = (
        "<Stats><BytesScanned>{s}</BytesScanned>"
        "<BytesProcessed>{s}</BytesProcessed>"
        "<BytesReturned>{r}</BytesReturned></Stats>"
    ).format(s=scanned, r=processed)
    out += _event_message(
        [
            (":message-type", "event"),
            (":event-type", "Stats"),
            (":content-type", "text/xml"),
        ],
        stats.encode(),
    )
    out += _event_message(
        [(":message-type", "event"), (":event-type", "End")], b""
    )
    return out


# ---------------------------------------------------------------- main


def select_object_content(
    data: bytes, expression: str, input_ser: dict, output_ser: dict
) -> bytes:
    """-> the complete event-stream response body. Raises QueryError
    for unsupported/invalid expressions."""
    sel = parse(normalize_expression(expression))
    if not isinstance(sel, Select):
        raise QueryError("only SELECT is supported")
    engine = QueryEngine(broker=None)
    result = engine.execute_rows(sel, parse_rows(data, input_ser))
    records = serialize_rows(result, output_ser)
    return event_stream(records, len(data), len(records))
