"""aws-chunked (streaming SigV4) body decoding with signature checks.

Reference: weed/s3api/chunked_reader_v4.go — most real AWS SDKs send
PUT bodies as STREAMING-AWS4-HMAC-SHA256-PAYLOAD: the Authorization
header signs a seed, then every chunk frame
``hex(size);chunk-signature=<sig>\r\n<data>\r\n`` carries a signature
chained from the previous one. The unsigned-trailer variants
(STREAMING-UNSIGNED-PAYLOAD-TRAILER) frame chunks without signatures
and append trailing checksum headers after the final 0-chunk.
"""

from __future__ import annotations

from .auth import (
    S3AuthError,
    SigningContext,
    verify_chunk_signature,
    verify_trailer_signature,
)


def decode_aws_chunked(
    body: bytes,
    ctx: SigningContext | None = None,
    signed: bool = False,
) -> bytes:
    """Strip aws-chunked framing; verify the chunk-signature chain when
    `signed` (requires ctx from header auth).

    Raises S3AuthError on any broken or missing chunk signature —
    a truncated or tampered stream must not be stored.
    """
    out = []
    pos = 0
    prev_sig = ctx.seed_signature if ctx is not None else ""
    saw_final = False
    while pos < len(body):
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            break
        header = body[pos:nl]
        if b":" in header.split(b";")[0]:
            # trailer header block after the final chunk
            break
        parts = header.split(b";")
        try:
            size = int(parts[0], 16)
        except ValueError as e:
            raise S3AuthError("InvalidRequest", f"bad chunk header {header!r}") from e
        chunk = body[nl + 2 : nl + 2 + size]
        if len(chunk) != size:
            raise S3AuthError("IncompleteBody", "truncated chunk")
        if signed:
            sig = ""
            for p in parts[1:]:
                if p.startswith(b"chunk-signature="):
                    sig = p[len(b"chunk-signature=") :].decode()
            if ctx is None or not sig:
                raise S3AuthError("AccessDenied", "missing chunk signature")
            want = verify_chunk_signature(ctx, prev_sig, chunk)
            if not _ct_eq(want, sig):
                raise S3AuthError(
                    "SignatureDoesNotMatch", "chunk signature mismatch"
                )
            prev_sig = sig
        if size == 0:
            saw_final = True
            pos = nl + 2
            break
        out.append(chunk)
        pos = nl + 2 + size + 2
    if signed and not saw_final:
        raise S3AuthError("IncompleteBody", "missing final chunk")
    # trailer block (x-amz-checksum-*, x-amz-trailer-signature)
    if signed and pos < len(body):
        trailer = body[pos:]
        lines = [ln for ln in trailer.split(b"\r\n") if ln]
        canonical = []
        trailer_sig = ""
        for ln in lines:
            k, _, v = ln.partition(b":")
            if k.strip().lower() == b"x-amz-trailer-signature":
                trailer_sig = v.strip().decode()
            else:
                canonical.append(k.strip().lower() + b":" + v.strip() + b"\n")
        if trailer_sig:
            want = verify_trailer_signature(ctx, prev_sig, b"".join(canonical))
            if not _ct_eq(want, trailer_sig):
                raise S3AuthError(
                    "SignatureDoesNotMatch", "trailer signature mismatch"
                )
    return b"".join(out)


def _ct_eq(a: str, b: str) -> bool:
    import hmac as _hmac

    return _hmac.compare_digest(a, b)


def encode_aws_chunked(
    data: bytes, ctx: SigningContext, chunk_size: int = 64 * 1024
) -> bytes:
    """Produce a signed aws-chunked body (test helper mirroring what an
    AWS SDK client sends)."""
    out = []
    prev = ctx.seed_signature
    chunks = [data[i : i + chunk_size] for i in range(0, len(data), chunk_size)]
    chunks.append(b"")
    for c in chunks:
        sig = verify_chunk_signature(ctx, prev, c)
        out.append(f"{len(c):x};chunk-signature={sig}\r\n".encode())
        out.append(c)
        out.append(b"\r\n")
        prev = sig
    return b"".join(out)
