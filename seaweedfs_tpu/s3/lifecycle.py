"""Bucket lifecycle rules + expiry scanner.

Reference: weed/s3api lifecycle handlers + S3_LIFECYCLE_REDESIGN.md and
the worker task weed/worker/tasks/s3_lifecycle. Rules are stored by the
gateway in the filer KV (raw XML for GET round-trip + parsed JSON for
the scanner); the scanner walks each configured bucket and applies:

- Expiration (Days | Date) on current versions — delete-marker
  semantics when the bucket is versioned, hard delete otherwise;
- NoncurrentVersionExpiration (NoncurrentDays) on archived versions;
- AbortIncompleteMultipartUpload (DaysAfterInitiation) on stale
  multipart upload directories.
"""

from __future__ import annotations

import json
import time
import xml.etree.ElementTree as ET

from ..filer.entry import normalize_path
from ..filer.filer_store import NotFound
from ..utils.glog import logger
from . import versioning as vtag

log = logger("s3.lifecycle")

BUCKETS_ROOT = "/buckets"
UPLOADS_DIR = ".uploads"


def parse_lifecycle_xml(body: bytes) -> list[dict]:
    """<LifecycleConfiguration><Rule>... → rule dicts; raises ValueError
    on malformed input."""
    try:
        doc = ET.fromstring(body)
    except ET.ParseError as e:
        raise ValueError(f"bad XML: {e}") from e
    ns = doc.tag[: doc.tag.index("}") + 1] if doc.tag.startswith("{") else ""
    rules = []
    for r in doc.findall(f"{ns}Rule"):
        rule: dict = {
            "ID": r.findtext(f"{ns}ID") or f"rule-{len(rules)}",
            "Status": r.findtext(f"{ns}Status") or "Enabled",
            "Prefix": (
                r.findtext(f"{ns}Filter/{ns}Prefix")
                or r.findtext(f"{ns}Prefix")
                or ""
            ),
        }
        exp = r.find(f"{ns}Expiration")
        if exp is not None:
            days = exp.findtext(f"{ns}Days")
            date = exp.findtext(f"{ns}Date")
            if days:
                rule["ExpirationDays"] = int(days)
            if date:
                rule["ExpirationDate"] = date
        nce = r.find(f"{ns}NoncurrentVersionExpiration")
        if nce is not None:
            nd = nce.findtext(f"{ns}NoncurrentDays")
            if nd:
                rule["NoncurrentDays"] = int(nd)
        ab = r.find(f"{ns}AbortIncompleteMultipartUpload")
        if ab is not None:
            d = ab.findtext(f"{ns}DaysAfterInitiation")
            if d:
                rule["AbortMultipartDays"] = int(d)
        if not any(
            k in rule
            for k in (
                "ExpirationDays",
                "ExpirationDate",
                "NoncurrentDays",
                "AbortMultipartDays",
            )
        ):
            raise ValueError(f"rule {rule['ID']} has no action")
        rules.append(rule)
    return rules


class LifecycleScanner:
    """Applies stored lifecycle rules across all buckets. Runs inside
    the S3 gateway (background thread) and as a worker-fleet task."""

    def __init__(self, filer):
        self.filer = filer

    # ------------------------------------------------------------ helpers

    def _bucket_rules(self, bucket: str) -> list[dict]:
        raw = self.filer.store.kv_get(f"lifecycle-rules/{bucket}".encode())
        if raw is None:
            return []
        try:
            return json.loads(raw)
        except ValueError:
            return []

    def _versioning(self, bucket: str) -> str:
        raw = self.filer.store.kv_get(f"versioning/{bucket}".encode())
        return raw.decode() if raw else ""

    def _walk_files(self, dir_path: str, key_prefix: str = ""):
        try:
            entries = list(self.filer.list_entries(dir_path, limit=100_000))
        except NotFound:
            return
        for e in entries:
            if e.is_directory:
                if key_prefix == "" and e.name in (
                    vtag.VERSIONS_DIR,
                    UPLOADS_DIR,
                ):
                    continue
                yield from self._walk_files(
                    e.full_path, key_prefix + e.name + "/"
                )
            else:
                yield key_prefix + e.name, e

    # ------------------------------------------------------------ actions

    def run_once(self, now: float | None = None, bucket: str = "") -> dict:
        """One scan of every bucket with rules (or just `bucket`);
        returns counters."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "noncurrent_expired": 0, "aborted_uploads": 0}
        try:
            buckets = [
                e.name
                for e in self.filer.list_entries(BUCKETS_ROOT, limit=10_000)
                if e.is_directory and e.name != UPLOADS_DIR
                and (not bucket or e.name == bucket)
            ]
        except NotFound:
            return stats
        for bucket in buckets:
            rules = self._bucket_rules(bucket)
            if not rules:
                continue
            try:
                self._apply_bucket(bucket, rules, now, stats)
            except Exception as e:  # a broken bucket must not stall others
                log.warning("lifecycle: bucket %s: %s", bucket, e)
        return stats

    def _apply_bucket(
        self, bucket: str, rules: list[dict], now: float, stats: dict
    ) -> None:
        versioned = self._versioning(bucket)  # "" | Enabled | Suspended
        active = [r for r in rules if r.get("Status") == "Enabled"]
        if not active:
            return
        exp_rules = [
            r for r in active if "ExpirationDays" in r or "ExpirationDate" in r
        ]
        if exp_rules:
            for key, entry in list(self._walk_files(f"{BUCKETS_ROOT}/{bucket}")):
                if vtag.is_delete_marker(entry):
                    continue
                for r in exp_rules:
                    if not key.startswith(r.get("Prefix", "")):
                        continue
                    if self._expired(entry.attr.mtime, r, now):
                        if self._expire_current(bucket, key, versioned):
                            stats["expired"] += 1
                        break
        nc_rules = [r for r in active if "NoncurrentDays" in r]
        if nc_rules:
            vroot = f"{BUCKETS_ROOT}/{bucket}/{vtag.VERSIONS_DIR}"
            for vkey, ventry in list(self._walk_files(vroot, "")):
                # vkey = "<object key>/<version id>"
                okey = vkey.rsplit("/", 1)[0]
                for r in nc_rules:
                    if not okey.startswith(r.get("Prefix", "")):
                        continue
                    if entry_age_days(ventry.attr.mtime, now) >= r["NoncurrentDays"]:
                        try:
                            # expiry must not destroy retention-locked
                            # or legal-held versions
                            vtag.check_deletable(ventry)
                        except vtag.LockViolation:
                            break
                        self.filer.delete_entry(
                            ventry.full_path, gc_chunks=True
                        )
                        stats["noncurrent_expired"] += 1
                        break
        ab_rules = [r for r in active if "AbortMultipartDays" in r]
        if ab_rules:
            days = min(r["AbortMultipartDays"] for r in ab_rules)
            updir = f"{BUCKETS_ROOT}/{UPLOADS_DIR}/{bucket}"
            try:
                uploads = list(self.filer.list_entries(updir, limit=10_000))
            except NotFound:
                uploads = []
            for u in uploads:
                if u.is_directory and entry_age_days(u.attr.crtime, now) >= days:
                    self.filer.delete_entry(u.full_path, recursive=True)
                    self.filer.store.kv_delete(f"upload/{u.name}".encode())
                    stats["aborted_uploads"] += 1

    @staticmethod
    def _expired(mtime: int, rule: dict, now: float) -> bool:
        if "ExpirationDays" in rule:
            return entry_age_days(mtime, now) >= rule["ExpirationDays"]
        if "ExpirationDate" in rule:
            import calendar

            try:
                # AWS dates are UTC instants, never server-local time
                t = calendar.timegm(
                    time.strptime(rule["ExpirationDate"][:10], "%Y-%m-%d")
                )
            except ValueError:
                return False
            return now >= t
        return False

    def _expire_current(self, bucket: str, key: str, versioned: str) -> bool:
        path = normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}")
        if versioned:
            # delete-marker semantics: the data stays reachable as a
            # noncurrent version until NoncurrentVersionExpiration
            vtag.write_delete_marker(
                self.filer, BUCKETS_ROOT, bucket, key, versioned
            )
            return True
        try:
            vtag.check_deletable(self.filer.find_entry(path))
        except vtag.LockViolation:
            return False
        except NotFound:
            return False
        self.filer.delete_entry(path, gc_chunks=True)
        return True


def entry_age_days(ts: int, now: float) -> float:
    return max(0.0, (now - (ts or 0)) / 86400.0)
