"""S3 REST gateway (layer 6) over the filer."""

from .auth import Identity, IdentityStore, S3AuthError
from .server import S3Server
