"""S3 REST gateway over the filer.

Reference: weed/s3api (s3api_server.go routes, filer_multipart.go,
s3api_object_handlers*.go). Buckets live at /buckets/<name> in the filer
namespace; multipart parts are filer entries whose chunk lists are
spliced (no data copy) on complete.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..filer.entry import new_entry, normalize_path
from ..filer.filer import Filer, FilerError
from ..filer.filer_store import NotFound
from ..pb import filer_pb2 as fpb
from .auth import Identity, IdentityStore, S3AuthError, verify_v4

BUCKETS_ROOT = "/buckets"
UPLOADS_DIR = ".uploads"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

import re as _re

# S3 bucket naming (subset): 2-63 chars, lowercase/digits/dot/hyphen,
# starting and ending alphanumeric — also satisfies the master's
# collection-name rules
_BUCKET_RE = _re.compile(r"^[a-z0-9][a-z0-9.\-]{0,61}[a-z0-9]$")


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _xml_ns(doc: ET.Element) -> str:
    """'{ns}' prefix of a parsed document ('' when un-namespaced)."""
    return doc.tag[: doc.tag.index("}") + 1] if doc.tag.startswith("{") else ""


def _iso(ts: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts or 0))


class S3Server:
    def __init__(
        self,
        filer: Filer,
        ip: str = "localhost",
        port: int = 8333,
        identities: IdentityStore | None = None,
        region: str = "us-east-1",
    ):
        self.filer = filer
        self.ip = ip
        self.port = port
        self.region = region
        self.identities = identities or IdentityStore()
        self._http = ThreadingHTTPServer((ip, port), self._handler_class())
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)
        try:
            self.filer.create_entry(
                new_entry(BUCKETS_ROOT, is_directory=True, mode=0o755)
            )
        except FilerError:
            pass

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    # ------------------------------------------------------------ handler

    def _handler_class(self):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            # ---- plumbing ----

            def _respond(self, code: int, body: bytes = b"", ctype="application/xml", extra=None):
                self.send_response(code)
                merged = {**getattr(self, "_cors", {}), **(extra or {})}
                for k, v in merged.items():
                    self.send_header(k, v)
                if code == 204:
                    self.end_headers()
                    return
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD" and body:
                    self.wfile.write(body)

            def _error(self, code: int, s3code: str, msg: str):
                root = ET.Element("Error")
                _el(root, "Code", s3code)
                _el(root, "Message", msg)
                _el(root, "Resource", urllib.parse.urlparse(self.path).path)
                self._respond(code, _xml(root))

            def _auth(self, payload: bytes | None = None) -> Identity | None:
                if srv.identities.empty:
                    return None  # open mode
                u = urllib.parse.urlparse(self.path)
                phash = self.headers.get(
                    "x-amz-content-sha256", "UNSIGNED-PAYLOAD"
                )
                ident = verify_v4(
                    srv.identities,
                    self.command,
                    u.path,
                    u.query,
                    self.headers,
                    phash,
                )
                # Integrity-bind the signed x-amz-content-sha256 to the
                # actual body: without this, a signed PUT body is
                # malleable by an on-path attacker (the signature only
                # covers the *claimed* hash).
                if (
                    ident is not None
                    and "Authorization" in self.headers
                    and phash != "UNSIGNED-PAYLOAD"
                    and not phash.startswith("STREAMING-")
                ):
                    body = self._read_body()
                    if hashlib.sha256(body).hexdigest() != phash.lower():
                        raise S3AuthError(
                            "XAmzContentSHA256Mismatch",
                            "x-amz-content-sha256 does not match body",
                        )
                return ident

            def _bucket_key(self):
                u = urllib.parse.urlparse(self.path)
                parts = urllib.parse.unquote(u.path).lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                return bucket, key, dict(
                    urllib.parse.parse_qsl(u.query, keep_blank_values=True)
                )

            def _read_body(self) -> bytes:
                if self._body_read:
                    return self._body_cache
                n = int(self.headers.get("Content-Length", "0") or "0")
                body = self.rfile.read(n)
                self._body_read = True
                # aws-chunked (streaming sigv4) transfer decoding
                if "aws-chunked" in (
                    self.headers.get("Content-Encoding", "")
                ) or self.headers.get("x-amz-content-sha256", "").startswith(
                    "STREAMING-"
                ):
                    body = _decode_aws_chunked(body)
                self._body_cache = body
                return body

            # ---- dispatch ----

            def _handle(self):
                self._body_read = False
                self._body_cache = b""
                self._cors = {}
                try:
                    bucket, key, q = self._bucket_key()
                    m = self.command
                    if m == "OPTIONS":
                        # browser preflights carry no Authorization by
                        # spec: they must be evaluated BEFORE auth
                        return self._preflight(bucket)
                    if bucket and self.headers.get("Origin"):
                        # every response (incl. errors and writes) needs
                        # the allow-origin header or browsers block it
                        self._cors = self._cors_response_headers(bucket)
                    try:
                        ident = self._auth()
                    except S3AuthError as e:
                        return self._error(403, e.code, str(e))
                    if ident is not None and not ident.allows(
                        _required_action(m, bucket, key)
                    ):
                        return self._error(
                            403, "AccessDenied", "identity lacks permission"
                        )
                    if bucket == "":
                        if m in ("GET", "HEAD"):
                            return self._list_buckets()
                        return self._error(405, "MethodNotAllowed", m)
                    if key == "":
                        return self._bucket_op(bucket, q)
                    return self._object_op(bucket, key, q)
                except NotFound:
                    return self._error(404, "NoSuchKey", "not found")
                except FilerError as e:
                    return self._error(409, "OperationAborted", str(e))
                except (ValueError, ET.ParseError, binascii.Error) as e:
                    return self._error(400, "InvalidArgument", str(e))
                except BrokenPipeError:
                    pass
                finally:
                    # drain any unread body so HTTP/1.1 keep-alive
                    # connections stay in sync
                    try:
                        if not self._body_read:
                            n = int(self.headers.get("Content-Length", "0") or "0")
                            if n:
                                self.rfile.read(n)
                                self._body_read = True
                    except (OSError, ValueError):
                        pass

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = do_OPTIONS = _handle

            # ---- cors ----

            def _cors_rules(self, bucket: str) -> list[dict]:
                raw = srv.filer.store.kv_get(f"cors-rules/{bucket}".encode())
                if raw is None:
                    return []
                try:
                    return json.loads(raw)
                except ValueError:
                    return []

            def _match_cors(self, bucket: str, origin: str, method: str):
                for rule in self._cors_rules(bucket):
                    if method not in rule["methods"]:
                        continue
                    for o in rule["origins"]:
                        if o == "*" or o == origin:
                            return rule, o
                return None, None

            def _preflight(self, bucket: str):
                origin = self.headers.get("Origin", "")
                method = self.headers.get("Access-Control-Request-Method", "")
                rule, matched = self._match_cors(bucket, origin, method)
                if rule is None:
                    return self._error(403, "AccessForbidden", "CORSResponse")
                self._respond(
                    200,
                    extra={
                        "Access-Control-Allow-Origin": "*" if matched == "*" else origin,
                        "Access-Control-Allow-Methods": ", ".join(rule["methods"]),
                        "Access-Control-Allow-Headers": ", ".join(
                            rule["headers"]
                        )
                        or "*",
                        "Access-Control-Max-Age": "3600",
                    },
                )

            def _cors_response_headers(self, bucket: str) -> dict:
                origin = self.headers.get("Origin", "")
                if not origin:
                    return {}
                rule, matched = self._match_cors(bucket, origin, self.command)
                if rule is None:
                    return {}
                return {
                    "Access-Control-Allow-Origin": "*" if matched == "*" else origin,
                    "Vary": "Origin",
                }

            # ---- service ----

            def _list_buckets(self):
                root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
                owner = _el(root, "Owner")
                _el(owner, "ID", "seaweedfs_tpu")
                buckets = _el(root, "Buckets")
                try:
                    for e in srv.filer.list_entries(BUCKETS_ROOT, limit=10_000):
                        if not e.is_directory or e.name == UPLOADS_DIR:
                            continue
                        b = _el(buckets, "Bucket")
                        _el(b, "Name", e.name)
                        _el(b, "CreationDate", _iso(e.attr.crtime))
                except NotFound:
                    pass
                self._respond(200, _xml(root))

            # ---- bucket ----

            def _bucket_op(self, bucket: str, q: dict):
                path = f"{BUCKETS_ROOT}/{bucket}"
                m = self.command
                if m == "PUT" and "cors" in q:
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    body = self._read_body()
                    try:
                        doc = ET.fromstring(body)
                    except ET.ParseError:
                        return self._error(400, "MalformedXML", "cors config")
                    ns = _xml_ns(doc)
                    rules = []
                    for rule in doc.iter(f"{ns}CORSRule"):
                        rules.append(
                            {
                                "origins": [
                                    e.text or ""
                                    for e in rule.findall(f"{ns}AllowedOrigin")
                                ],
                                "methods": [
                                    e.text or ""
                                    for e in rule.findall(f"{ns}AllowedMethod")
                                ],
                                "headers": [
                                    e.text or ""
                                    for e in rule.findall(f"{ns}AllowedHeader")
                                ],
                            }
                        )
                    if not rules:
                        return self._error(400, "MalformedXML", "no CORSRule")
                    # parsed ONCE here; the hot read path loads JSON
                    srv.filer.store.kv_put(f"cors/{bucket}".encode(), body)
                    srv.filer.store.kv_put(
                        f"cors-rules/{bucket}".encode(),
                        json.dumps(rules).encode(),
                    )
                    return self._respond(200)
                if m == "DELETE" and "cors" in q:
                    srv.filer.store.kv_delete(f"cors/{bucket}".encode())
                    srv.filer.store.kv_delete(f"cors-rules/{bucket}".encode())
                    return self._respond(204)
                if m == "PUT":
                    if "versioning" in q:
                        # advertised off; enabling it is unimplemented —
                        # never misroute into bucket creation
                        return self._error(
                            501, "NotImplemented", "bucket versioning"
                        )
                    # bucket names double as volume collections: enforce
                    # S3 naming up front so object uploads can't fail on
                    # the master's collection validation later
                    if not _BUCKET_RE.match(bucket):
                        return self._error(400, "InvalidBucketName", bucket)
                    if srv.filer.exists(path):
                        return self._error(
                            409, "BucketAlreadyExists", bucket
                        )
                    srv.filer.create_entry(
                        new_entry(path, is_directory=True, mode=0o755)
                    )
                    return self._respond(200, extra={"Location": "/" + bucket})
                if m == "HEAD":
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    return self._respond(200)
                if m == "DELETE":
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    children = list(srv.filer.list_entries(path, limit=2))
                    if children:
                        return self._error(409, "BucketNotEmpty", bucket)
                    srv.filer.delete_entry(path, recursive=True)
                    # a future bucket of the same name must not inherit
                    # this one's CORS grants
                    srv.filer.store.kv_delete(f"cors/{bucket}".encode())
                    srv.filer.store.kv_delete(f"cors-rules/{bucket}".encode())
                    # fast space reclaim: drop the bucket's collection
                    # volumes cluster-wide (reference bucket=collection)
                    try:
                        srv.filer.ops.master.collection_delete(bucket)
                    except Exception:
                        pass
                    return self._respond(204)
                if m == "POST" and "delete" in q:
                    return self._delete_objects(bucket)
                if m == "GET":
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    if "location" in q:
                        root = ET.Element("LocationConstraint", xmlns=XMLNS)
                        root.text = srv.region
                        return self._respond(200, _xml(root))
                    if "cors" in q:
                        raw = srv.filer.store.kv_get(f"cors/{bucket}".encode())
                        if raw is None:
                            return self._error(
                                404, "NoSuchCORSConfiguration", bucket
                            )
                        return self._respond(200, raw)
                    if "versioning" in q:
                        # versioning is not implemented; report it off
                        root = ET.Element("VersioningConfiguration", xmlns=XMLNS)
                        return self._respond(200, _xml(root))
                    if "uploads" in q:
                        return self._list_uploads(bucket)
                    return self._list_objects(bucket, q)
                return self._error(405, "MethodNotAllowed", m)

            def _list_objects(self, bucket: str, q: dict):
                prefix = q.get("prefix", "")
                delimiter = q.get("delimiter", "")
                v2 = q.get("list-type") == "2"
                max_keys = min(int(q.get("max-keys", "1000") or "1000"), 1000)
                token = (
                    q.get("continuation-token") or q.get("start-after") or ""
                    if v2
                    else q.get("marker", "")
                )
                if v2 and q.get("continuation-token"):
                    token = base64.urlsafe_b64decode(
                        q["continuation-token"].encode()
                    ).decode()

                contents, common, truncated, next_token = srv._walk_keys(
                    bucket, prefix, delimiter, token, max_keys
                )
                root = ET.Element("ListBucketResult", xmlns=XMLNS)
                _el(root, "Name", bucket)
                _el(root, "Prefix", prefix)
                if delimiter:
                    _el(root, "Delimiter", delimiter)
                _el(root, "MaxKeys", max_keys)
                _el(root, "KeyCount", len(contents) + len(common))
                _el(root, "IsTruncated", "true" if truncated else "false")
                if v2 and truncated:
                    _el(
                        root,
                        "NextContinuationToken",
                        base64.urlsafe_b64encode(next_token.encode()).decode(),
                    )
                elif not v2:
                    _el(root, "Marker", q.get("marker", ""))
                    if truncated:
                        _el(root, "NextMarker", next_token)
                for key, entry in contents:
                    c = _el(root, "Contents")
                    _el(c, "Key", key)
                    _el(c, "LastModified", _iso(entry.attr.mtime))
                    _el(c, "ETag", f'"{_entry_etag(entry)}"')
                    _el(c, "Size", entry.file_size)
                    _el(c, "StorageClass", "STANDARD")
                for p in sorted(common):
                    cp = _el(root, "CommonPrefixes")
                    _el(cp, "Prefix", p)
                self._respond(200, _xml(root))

            def _delete_objects(self, bucket: str):
                body = self._read_body()
                doc = ET.fromstring(body)
                ns = _xml_ns(doc)
                quiet = (doc.findtext(f"{ns}Quiet") or "").lower() == "true"
                root = ET.Element("DeleteResult", xmlns=XMLNS)
                for obj in doc.findall(f"{ns}Object"):
                    key = obj.findtext(f"{ns}Key") or ""
                    try:
                        srv.filer.delete_entry(
                            f"{BUCKETS_ROOT}/{bucket}/{key}", recursive=True
                        )
                        if not quiet:
                            d = _el(root, "Deleted")
                            _el(d, "Key", key)
                    except FilerError as e:
                        er = _el(root, "Error")
                        _el(er, "Key", key)
                        _el(er, "Code", "InternalError")
                        _el(er, "Message", str(e))
                self._respond(200, _xml(root))

            # ---- object ----

            def _object_op(self, bucket: str, key: str, q: dict):
                bpath = f"{BUCKETS_ROOT}/{bucket}"
                if not srv.filer.exists(bpath):
                    return self._error(404, "NoSuchBucket", bucket)
                path = normalize_path(f"{bpath}/{key}")
                m = self.command
                if m == "POST" and "uploads" in q:
                    return self._initiate_multipart(bucket, key)
                if m == "PUT" and "partNumber" in q and "uploadId" in q:
                    return self._upload_part(bucket, key, q)
                if m == "POST" and "uploadId" in q:
                    return self._complete_multipart(bucket, key, q)
                if m == "DELETE" and "uploadId" in q:
                    return self._abort_multipart(bucket, key, q)
                if m == "GET" and "uploadId" in q:
                    return self._list_parts(bucket, key, q)

                if "tagging" in q:
                    return self._object_tagging(bucket, key, path)

                if m == "PUT":
                    src = self.headers.get("x-amz-copy-source", "")
                    if src:
                        return self._copy_object(bucket, key, src)
                    data = self._read_body()
                    entry = srv.filer.write_file(
                        path,
                        data,
                        mime=self.headers.get("Content-Type", "")
                        or "application/octet-stream",
                        collection=bucket,
                    )
                    etag = entry.attr.md5.hex()
                    return self._respond(200, extra={"ETag": f'"{etag}"'})
                if m in ("GET", "HEAD"):
                    entry = srv.filer.find_entry(path)
                    if entry.is_directory:
                        return self._error(404, "NoSuchKey", key)
                    total = entry.file_size
                    headers = {
                        **self._cors_response_headers(bucket),
                        "ETag": f'"{_entry_etag(entry)}"',
                        "Last-Modified": time.strftime(
                            "%a, %d %b %Y %H:%M:%S GMT",
                            time.gmtime(entry.attr.mtime),
                        ),
                        "Accept-Ranges": "bytes",
                    }
                    ctype = entry.attr.mime or "application/octet-stream"
                    if m == "HEAD":
                        self.send_response(200)
                        for k, v in headers.items():
                            self.send_header(k, v)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(total))
                        self.end_headers()
                        return
                    rng = self.headers.get("Range", "")
                    offset, size, status = 0, -1, 200
                    if rng.startswith("bytes="):
                        try:
                            lo_s, _, hi_s = rng[6:].split(",")[0].partition("-")
                            lo = int(lo_s) if lo_s else max(total - int(hi_s), 0)
                            hi = int(hi_s) if hi_s and lo_s else total - 1
                            if lo > hi or lo >= max(total, 1):
                                return self._respond(
                                    416,
                                    extra={"Content-Range": f"bytes */{total}"},
                                )
                            offset, size, status = lo, hi - lo + 1, 206
                            headers["Content-Range"] = (
                                f"bytes {lo}-{min(hi, total - 1)}/{total}"
                            )
                        except ValueError:
                            pass
                    data = srv.filer.read_entry(entry, offset, size)
                    return self._respond(status, data, ctype, headers)
                if m == "DELETE":
                    srv.filer.delete_entry(path, recursive=False, gc_chunks=True)
                    return self._respond(204)
                return self._error(405, "MethodNotAllowed", m)

            def _object_tagging(self, bucket: str, key: str, path: str):
                """Get/Put/DeleteObjectTagging: tags ride the entry's
                extended attributes (reference s3api tagging handlers)."""
                entry = srv.filer.find_entry(path)
                if entry.is_directory:
                    return self._error(404, "NoSuchKey", key)
                m = self.command
                if m == "GET":
                    root = ET.Element("Tagging", xmlns=XMLNS)
                    tagset = _el(root, "TagSet")
                    raw = entry.extended.get("s3-tags", b"{}")
                    for k2, v2 in sorted(json.loads(raw).items()):
                        t = _el(tagset, "Tag")
                        _el(t, "Key", k2)
                        _el(t, "Value", v2)
                    return self._respond(200, _xml(root))
                if m == "PUT":
                    doc = ET.fromstring(self._read_body())
                    ns = _xml_ns(doc)
                    tags = {}
                    for t in doc.iter(f"{ns}Tag"):
                        k2 = t.findtext(f"{ns}Key") or ""
                        # AWS rejects bad tag sets rather than storing a subset
                        if not k2 or k2 in tags:
                            return self._error(
                                400, "InvalidTag", f"empty or duplicate key {k2!r}"
                            )
                        tags[k2] = t.findtext(f"{ns}Value") or ""
                    if len(tags) > 10:
                        return self._error(
                            400, "BadRequest", "object tag set exceeds 10 tags"
                        )
                    srv.filer.mutate_entry(
                        path,
                        lambda e: e.extended.__setitem__(
                            "s3-tags", json.dumps(tags, sort_keys=True).encode()
                        ),
                    )
                    return self._respond(200)
                if m == "DELETE":
                    srv.filer.mutate_entry(
                        path, lambda e: e.extended.pop("s3-tags", None)
                    )
                    return self._respond(204)
                return self._error(405, "MethodNotAllowed", m)

            def _copy_object(self, bucket: str, key: str, src: str):
                src = urllib.parse.unquote(src)
                if not src.startswith("/"):
                    src = "/" + src
                src_path = normalize_path(f"{BUCKETS_ROOT}{src}")
                entry = srv.filer.find_entry(src_path)
                data = srv.filer.read_entry(entry)
                dst = srv.filer.write_file(
                    normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}"),
                    data,
                    mime=entry.attr.mime,
                    collection=bucket,
                )
                root = ET.Element("CopyObjectResult", xmlns=XMLNS)
                _el(root, "ETag", f'"{dst.attr.md5.hex()}"')
                _el(root, "LastModified", _iso(dst.attr.mtime))
                self._respond(200, _xml(root))

            # ---- multipart ----

            def _initiate_multipart(self, bucket: str, key: str):
                upload_id = uuid.uuid4().hex
                meta_path = srv._upload_dir(bucket, upload_id)
                e = new_entry(meta_path, is_directory=True, mode=0o755)
                srv.filer.create_entry(e)
                srv.filer.store.kv_put(
                    f"upload/{upload_id}".encode(),
                    json.dumps(
                        {
                            "bucket": bucket,
                            "key": key,
                            "mime": self.headers.get("Content-Type", ""),
                        }
                    ).encode(),
                )
                root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "UploadId", upload_id)
                self._respond(200, _xml(root))

            def _upload_part(self, bucket: str, key: str, q: dict):
                upload_id = q["uploadId"]
                part = int(q["partNumber"])
                if srv.filer.store.kv_get(f"upload/{upload_id}".encode()) is None:
                    return self._error(404, "NoSuchUpload", upload_id)
                data = self._read_body()
                entry = srv.filer.write_file(
                    f"{srv._upload_dir(bucket, upload_id)}/{part:05d}.part",
                    data,
                    collection=bucket,
                    inline=False,  # completion splices chunk lists
                )
                self._respond(200, extra={"ETag": f'"{entry.attr.md5.hex()}"'})

            def _complete_multipart(self, bucket: str, key: str, q: dict):
                upload_id = q["uploadId"]
                meta_raw = srv.filer.store.kv_get(f"upload/{upload_id}".encode())
                if meta_raw is None:
                    return self._error(404, "NoSuchUpload", upload_id)
                meta = json.loads(meta_raw)
                updir = srv._upload_dir(bucket, upload_id)
                parts = sorted(
                    (
                        e
                        for e in srv.filer.list_entries(updir, limit=10_000)
                        if e.name.endswith(".part")
                    ),
                    key=lambda e: e.name,
                )
                # honor the client's part list when provided
                body = self._read_body()
                if body.strip():
                    doc = ET.fromstring(body)
                    ns = _xml_ns(doc)
                    wanted = {
                        int(p.findtext(f"{ns}PartNumber") or "0")
                        for p in doc.findall(f"{ns}Part")
                    }
                    if wanted:
                        chosen = [
                            e for e in parts if int(e.name.split(".")[0]) in wanted
                        ]
                        if len(chosen) != len(wanted):
                            return self._error(
                                400, "InvalidPart", "listed part missing"
                            )
                        parts = chosen
                # splice chunk lists: no data copy (filer_multipart.go)
                chunks, offset, md5s = [], 0, []
                for p in parts:
                    if p.content and not p.chunks:
                        # a part stored inline (e.g. pre-inline=False
                        # uploads) must become a chunk or its bytes
                        # would vanish from the spliced object
                        fid = srv.filer.ops.upload(
                            p.content, collection=bucket
                        )
                        c0 = fpb.FileChunk(
                            fid=fid,
                            offset=0,
                            size=len(p.content),
                            modified_ts_ns=time.time_ns(),
                        )
                        p.chunks.append(c0)
                    for c in p.chunks:
                        nc = fpb.FileChunk()
                        nc.CopyFrom(c)
                        nc.offset = offset + c.offset
                        chunks.append(nc)
                    offset += p.file_size
                    md5s.append(p.attr.md5)
                final_path = normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}")
                final = new_entry(final_path, mime=meta.get("mime", ""))
                final.chunks = chunks
                final.attr.file_size = offset
                etag = hashlib.md5(b"".join(md5s)).hexdigest() + f"-{len(parts)}"
                final.extended["s3-etag"] = etag.encode()
                # an overwritten object's chunks must be GC'd (write_file
                # does this for the simple-PUT path)
                try:
                    old = srv.filer.find_entry(final_path)
                except NotFound:
                    old = None
                srv.filer.create_entry(final)
                if old is not None and not old.is_directory:
                    srv.filer.gc_chunks(old.chunks)
                # drop part entries WITHOUT GC'ing chunks (now referenced
                # by the final entry)
                for p in parts:
                    srv.filer.delete_entry(p.full_path, gc_chunks=False)
                srv.filer.delete_entry(updir, recursive=True, gc_chunks=False)
                srv.filer.store.kv_delete(f"upload/{upload_id}".encode())
                root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "ETag", f'"{etag}"')
                self._respond(200, _xml(root))

            def _abort_multipart(self, bucket: str, key: str, q: dict):
                upload_id = q["uploadId"]
                srv.filer.delete_entry(
                    srv._upload_dir(bucket, upload_id), recursive=True
                )
                srv.filer.store.kv_delete(f"upload/{upload_id}".encode())
                self._respond(204)

            def _list_parts(self, bucket: str, key: str, q: dict):
                upload_id = q["uploadId"]
                updir = srv._upload_dir(bucket, upload_id)
                if srv.filer.store.kv_get(
                    f"upload/{upload_id}".encode()
                ) is None or not srv.filer.exists(updir):
                    return self._error(404, "NoSuchUpload", upload_id)
                root = ET.Element("ListPartsResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "UploadId", upload_id)
                try:
                    for e in srv.filer.list_entries(updir, limit=10_000):
                        if not e.name.endswith(".part"):
                            continue
                        p = _el(root, "Part")
                        _el(p, "PartNumber", int(e.name.split(".")[0]))
                        _el(p, "ETag", f'"{e.attr.md5.hex()}"')
                        _el(p, "Size", e.file_size)
                except NotFound:
                    return self._error(404, "NoSuchUpload", upload_id)
                self._respond(200, _xml(root))

            def _list_uploads(self, bucket: str):
                root = ET.Element("ListMultipartUploadsResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                updir = f"{BUCKETS_ROOT}/{UPLOADS_DIR}/{bucket}"
                try:
                    for e in srv.filer.list_entries(updir, limit=10_000):
                        meta_raw = srv.filer.store.kv_get(
                            f"upload/{e.name}".encode()
                        )
                        if meta_raw is None:
                            continue
                        meta = json.loads(meta_raw)
                        u = _el(root, "Upload")
                        _el(u, "Key", meta["key"])
                        _el(u, "UploadId", e.name)
                except NotFound:
                    pass
                self._respond(200, _xml(root))

        return Handler

    # -------------------------------------------------------------- walk

    def _upload_dir(self, bucket: str, upload_id: str) -> str:
        return f"{BUCKETS_ROOT}/{UPLOADS_DIR}/{bucket}/{upload_id}"

    def _walk_keys(
        self, bucket: str, prefix: str, delimiter: str, after: str, max_keys: int
    ):
        """Flat key listing with prefix/delimiter grouping.

        DFS over the filer tree in sorted order (the namespace IS the
        key space, reference s3api list semantics over the filer)."""
        bpath = f"{BUCKETS_ROOT}/{bucket}"
        contents: list = []
        common: set[str] = set()
        truncated = False
        last_emitted = ""

        def cap_reached() -> bool:
            nonlocal truncated
            if len(contents) + len(common) >= max_keys:
                truncated = True
                return True
            return False

        def dfs(dir_path: str, key_prefix: str) -> bool:
            nonlocal last_emitted
            for e in self.filer.list_entries(dir_path, limit=100_000):
                key = key_prefix + e.name
                if e.is_directory:
                    sub = key + "/"
                    # prune subtrees that cannot contain matching keys
                    if prefix and not (
                        sub.startswith(prefix) or prefix.startswith(sub)
                    ):
                        continue
                    if delimiter == "/" and sub.startswith(prefix) and sub != prefix:
                        cut = prefix + sub[len(prefix) :].split("/")[0] + "/"
                        if after.startswith(cut):
                            continue  # group already emitted on a prior page
                        if cut <= after:
                            continue
                        if cut in common:
                            continue
                        if cap_reached():
                            return False
                        common.add(cut)
                        last_emitted = cut
                        continue
                    if not dfs(e.full_path, sub):
                        return False
                else:
                    if prefix and not key.startswith(prefix):
                        continue
                    if after and key <= after:
                        continue
                    if cap_reached():
                        return False
                    contents.append((key, e))
                    last_emitted = key
            return True

        try:
            dfs(bpath, "")
        except NotFound:
            pass
        return contents, common, truncated, last_emitted


def _required_action(method: str, bucket: str, key: str) -> str:
    """Map a request to the coarse action model (reference
    auth_credentials.go identity actions: Admin/Read/Write/List)."""
    if key == "":
        if method in ("GET", "HEAD"):
            return "List"
        if method == "POST":  # batch delete
            return "Write"
        return "Admin"  # bucket create/delete
    return "Read" if method in ("GET", "HEAD") else "Write"


def _entry_etag(entry) -> str:
    s3etag = entry.extended.get("s3-etag")
    if s3etag:
        return s3etag.decode()
    return entry.attr.md5.hex() if entry.attr.md5 else ""


def _decode_aws_chunked(body: bytes) -> bytes:
    """Strip aws-chunked framing (chunk-size;chunk-signature=...\r\n)."""
    out = []
    pos = 0
    while pos < len(body):
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            break
        header = body[pos:nl]
        size = int(header.split(b";")[0], 16)
        if size == 0:
            break
        out.append(body[nl + 2 : nl + 2 + size])
        pos = nl + 2 + size + 2
    return b"".join(out)
