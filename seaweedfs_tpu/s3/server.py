"""S3 REST gateway over the filer.

Reference: weed/s3api (s3api_server.go routes, filer_multipart.go,
s3api_object_handlers*.go). Buckets live at /buckets/<name> in the filer
namespace; multipart parts are filer entries whose chunk lists are
spliced (no data copy) on complete.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..filer.entry import new_entry, normalize_path
from ..filer.filer import Filer, FilerError
from ..filer.filer_store import NotFound
from ..pb import filer_pb2 as fpb
from .auth import Identity, IdentityStore, S3AuthError, verify_v4_ex
from .chunked import decode_aws_chunked
from . import post_policy as ppol
from . import sse
from . import versioning as vtag
from .versioning import (
    LockViolation,
    archive_current,
    check_deletable,
    entry_vid,
    is_delete_marker,
    iter_versions,
    new_version_id,
    promote_latest,
    versions_dir,
)

BUCKETS_ROOT = "/buckets"
UPLOADS_DIR = ".uploads"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

import re as _re

# S3 bucket naming (subset): 2-63 chars, lowercase/digits/dot/hyphen,
# starting and ending alphanumeric — also satisfies the master's
# collection-name rules
_BUCKET_RE = _re.compile(r"^[a-z0-9][a-z0-9.\-]{0,61}[a-z0-9]$")


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _xml_ns(doc: ET.Element) -> str:
    """'{ns}' prefix of a parsed document ('' when un-namespaced)."""
    return doc.tag[: doc.tag.index("}") + 1] if doc.tag.startswith("{") else ""


def _iso(ts: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts or 0))


def _saturation_error_doc() -> tuple[str, bytes]:
    """503 body for a saturated gateway: a well-formed S3 error
    document (Code=SlowDown, AWS's throttle code) so SDK clients parse
    and back off instead of choking on a bare close."""
    root = ET.Element("Error")
    _el(root, "Code", "SlowDown")
    _el(
        root,
        "Message",
        "gateway saturated: worker pool and accept queue are full; "
        "reduce your request rate",
    )
    _el(root, "Resource", "/")
    return "application/xml", _xml(root)


def _shed_error_doc(tenant: str) -> tuple[str, bytes]:
    """503 body for per-tenant residency shedding: same SlowDown code
    SDKs already back off on, but the message says WHY this tenant
    (and not the server) is being told to slow down."""
    root = ET.Element("Error")
    _el(root, "Code", "SlowDown")
    _el(
        root,
        "Message",
        f"tenant {tenant!r} exceeds its fair device share during pod "
        "overload; reduce your request rate and retry",
    )
    _el(root, "Resource", "/")
    return "application/xml", _xml(root)


class S3Server:
    def __init__(
        self,
        filer: Filer,
        ip: str = "localhost",
        port: int = 8333,
        identities: IdentityStore | None = None,
        region: str = "us-east-1",
        lifecycle_interval: float = 3600.0,
        sts=None,
        tls=None,
        oidc=None,
        ldap=None,
        http_workers: int = 32,
        http_queue: int = 128,
        tenant: str = "default",
    ):
        """`http_workers`/`http_queue`: the bounded worker-pool front
        end (utils/http_pool.py) — `http_workers` request workers plus
        an `http_queue`-deep connection budget; past it new connections
        get an immediate 503 SlowDown XML error document with
        Retry-After. `http_workers=0` restores the unbounded
        one-thread-per-connection stdlib server (also used when `tls`
        is configured).

        `tenant` names this gateway's accounting domain on the EC
        residency ledger: when the pod is in sustained device
        oversubscription AND this tenant's device usage exceeds its
        fair share, object data-plane requests get an early 503
        SlowDown + Retry-After (per-tenant shedding — a well-behaved
        tenant on the same pod keeps serving)."""
        self.tenant = tenant
        self.filer = filer
        self.ip = ip
        self.port = port
        self.region = region
        # Layer filer-persisted dynamic credentials (shell `s3.*`
        # family writes s3/identity.json) over any static store.
        from .config import FilerIdentityStore

        self.identities = FilerIdentityStore(filer, base=identities)
        # STS service (iam.StsService): AssumeRole on the service
        # endpoint + temp-credential lookup during SigV4 auth
        self.sts_service = sts
        if sts is not None and self.identities.sts is None:
            self.identities.sts = sts
        # OIDC bearer tokens (iam/oidc.py OidcProvider): an alternative
        # authentication path beside SigV4
        self.oidc = oidc
        # LDAP simple-bind provider (iam/ldap.py): backs the STS action
        # AssumeRoleWithLdapIdentity
        self.ldap = ldap
        # SSE-S3 keyring: master key shared via the filer KV store so
        # every gateway over the same filer can decrypt (KMS SPI:
        # replace with an external provider via `sse_keyring=`).
        try:
            self.sse_keyring = sse.load_or_create_keyring(
                filer.store.kv_get,
                filer.store.kv_put,
                getattr(filer.store, "kv_put_if_absent", None),
            )
        except Exception:
            self.sse_keyring = None
        from .tables import TablesCatalog

        self.tables_catalog = TablesCatalog(self)
        # Striped per-key write locks: a conditional PUT's precondition
        # must be atomic against EVERY write to that key (a plain PUT,
        # multipart completion, POST-policy upload, or DELETE racing a
        # CAS would otherwise be silently lost). REENTRANT because the
        # conditional-PUT path holds its stripe around put_object,
        # which takes the same stripe as the common funnel.
        self._put_locks = [threading.RLock() for _ in range(64)]
        from ..utils.http_pool import build_http_server

        self._http = build_http_server(
            (ip, port),
            self._handler_class(),
            server_kind="s3",
            workers=http_workers,
            accept_queue=http_queue,
            tls=tls,
            reject_body=_saturation_error_doc,
        )
        self.tls = tls
        if tls is not None:
            tls.wrap_server(self._http)
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)
        from .lifecycle import LifecycleScanner

        self.lifecycle = LifecycleScanner(filer)
        self._lc_interval = lifecycle_interval
        self._lc_stop = threading.Event()
        self._lc_thread = threading.Thread(target=self._lc_loop, daemon=True)
        try:
            self.filer.create_entry(
                new_entry(BUCKETS_ROOT, is_directory=True, mode=0o755)
            )
        except FilerError:
            pass

    def start(self) -> None:
        self._thread.start()
        if self._lc_interval > 0:
            self._lc_thread.start()

    def stop(self) -> None:
        self._lc_stop.set()
        self._http.shutdown()
        self._http.server_close()

    def _lc_loop(self) -> None:
        while not self._lc_stop.wait(self._lc_interval):
            try:
                self.lifecycle.run_once()
            except Exception:
                pass

    def _shed_retry_after(self) -> float | None:
        """Retry-After seconds when the residency shed policy wants
        THIS tenant backed off right now, else None. Never raises —
        overload safety must not add a failure mode to serving."""
        from ..ec.device_queue import shed_advice

        return shed_advice(self.tenant)

    # ------------------------------------------------------------ handler

    def _handler_class(self):
        srv = self

        from ..utils.request_id import RequestTracingMixin

        class Handler(RequestTracingMixin, BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            trace_server_kind = "s3"

            def log_message(self, *a):
                pass

            # ---- plumbing ----

            def _respond(self, code: int, body: bytes = b"", ctype="application/xml", extra=None):
                self.send_response(code)
                merged = {**getattr(self, "_cors", {}), **(extra or {})}
                for k, v in merged.items():
                    self.send_header(k, v)
                if code == 204:
                    self.end_headers()
                    return
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD" and body:
                    # Warm-path GET bodies leave through the native
                    # scatter-gather sender when the pooled front end +
                    # native plane are on (GIL released for the whole
                    # send); bit-identical wfile fallback otherwise.
                    from ..utils.http_pool import send_body

                    send_body(self, body)

            def _error(self, code: int, s3code: str, msg: str):
                root = ET.Element("Error")
                _el(root, "Code", s3code)
                _el(root, "Message", msg)
                _el(root, "Resource", urllib.parse.urlparse(self.path).path)
                self._respond(code, _xml(root))

            def _auth(self, payload: bytes | None = None) -> Identity | None:
                auth_hdr = self.headers.get("Authorization", "")
                if srv.oidc is not None and auth_hdr.startswith("Bearer "):
                    # OIDC path: an unverifiable bearer is REJECTED,
                    # never downgraded to anonymous
                    from ..iam.oidc import OidcError

                    try:
                        claims = srv.oidc.verify(auth_hdr[len("Bearer ") :])
                    except OidcError as e:
                        raise S3AuthError(
                            "InvalidToken", f"OIDC: {e}"
                        ) from None
                    return srv.oidc.identity_for(claims)
                if srv.identities.empty:
                    if srv.oidc is not None:
                        # OIDC-only deployment: an empty SigV4 store
                        # must NOT mean open mode — tokenless requests
                        # are ANONYMOUS (bucket policy may still grant)
                        self._anonymous = True
                        return None
                    return None  # open mode
                u = urllib.parse.urlparse(self.path)
                if "Authorization" not in self.headers and "X-Amz-Signature" not in u.query:
                    # No credentials at all: ANONYMOUS, not an auth
                    # failure — bucket policies and public ACLs may
                    # still grant access (evaluated in _handle).
                    self._anonymous = True
                    return None
                phash = self.headers.get(
                    "x-amz-content-sha256", "UNSIGNED-PAYLOAD"
                )
                ident, self._sig_ctx = verify_v4_ex(
                    srv.identities,
                    self.command,
                    u.path,
                    u.query,
                    self.headers,
                    phash,
                )
                # Integrity-bind the signed x-amz-content-sha256 to the
                # actual body: without this, a signed PUT body is
                # malleable by an on-path attacker (the signature only
                # covers the *claimed* hash).
                if (
                    ident is not None
                    and "Authorization" in self.headers
                    and phash != "UNSIGNED-PAYLOAD"
                    and not phash.startswith("STREAMING-")
                ):
                    body = self._read_body()
                    if hashlib.sha256(body).hexdigest() != phash.lower():
                        raise S3AuthError(
                            "XAmzContentSHA256Mismatch",
                            "x-amz-content-sha256 does not match body",
                        )
                return ident

            def _authorize(
                self, ident, m: str, bucket: str, key: str, q: dict
            ) -> str | None:
                """Combine identity policies, the bucket (resource)
                policy, and canned ACLs per AWS evaluation logic:
                explicit Deny ANYWHERE (identity or bucket policy)
                wins; otherwise any applicable Allow grants; anonymous
                callers need a resource grant (bucket policy Principal
                "*" or a public canned ACL), and ACL grants cover only
                data-plane actions. Returns an error message, or None
                when authorized."""
                from ..iam.policy import (
                    evaluate_bucket_policy,
                    evaluate_policies_verdict,
                    s3_action_and_resource,
                )

                action, resource = s3_action_and_resource(m, bucket, key, q)
                pctx = {
                    "aws:SourceIp": self.client_address[0],
                    "aws:username": ident.name if ident else "",
                    "s3:prefix": q.get("prefix", ""),
                }
                bp_verdict = None
                pdoc = srv.bucket_policy(bucket) if bucket else None
                if pdoc is not None:
                    principal = (
                        f"arn:aws:iam:::user/{ident.name}" if ident else "*"
                    )
                    bp_verdict = evaluate_bucket_policy(
                        pdoc, action, resource, principal, pctx
                    )
                    if bp_verdict == "deny":
                        return f"{action} denied by bucket policy"
                if self._anonymous:
                    if not bucket:
                        return "anonymous access denied"
                    if bp_verdict == "allow":
                        return None
                    if srv.acl_allows_anonymous(bucket, key, action):
                        return None
                    return "anonymous access denied"
                if ident is None:
                    return None  # open mode
                if ident.policies:
                    iv = evaluate_policies_verdict(
                        list(ident.policies), action, resource, pctx
                    )
                    # identity explicit Deny overrides a bucket-policy
                    # Allow (deny anywhere wins)
                    if iv == "deny":
                        return f"{action} on {resource} denied by policy"
                    if iv == "allow" or bp_verdict == "allow":
                        return None
                    return f"{action} on {resource} denied by policy"
                if bp_verdict == "allow" or ident.allows(
                    _required_action(m, bucket, key)
                ):
                    return None
                return "identity lacks permission"

            def _bucket_key(self):
                u = urllib.parse.urlparse(self.path)
                parts = urllib.parse.unquote(u.path).lstrip("/").split("/", 1)
                bucket = parts[0]
                key = parts[1] if len(parts) > 1 else ""
                return bucket, key, dict(
                    urllib.parse.parse_qsl(u.query, keep_blank_values=True)
                )

            def _read_body(self) -> bytes:
                if self._body_read:
                    return self._body_cache
                n = int(self.headers.get("Content-Length", "0") or "0")
                body = self.rfile.read(n)
                self._body_read = True
                # aws-chunked (streaming sigv4) transfer decoding; the
                # signed form verifies the chunk-signature chain seeded
                # by the Authorization signature (chunked_reader_v4.go)
                phash = self.headers.get("x-amz-content-sha256", "")
                if phash.startswith("STREAMING-AWS4-HMAC-SHA256-PAYLOAD"):
                    # verify the chunk chain only when header auth
                    # produced a signing context; open-mode and
                    # presigned requests have no seed to chain from
                    ctx = getattr(self, "_sig_ctx", None)
                    body = decode_aws_chunked(body, ctx, signed=ctx is not None)
                elif phash.startswith("STREAMING-") or "aws-chunked" in (
                    self.headers.get("Content-Encoding", "")
                ):
                    body = decode_aws_chunked(body)
                self._body_cache = body
                return body

            # ---- dispatch ----

            def _handle(self):
                self._body_read = False
                self._body_cache = b""
                self._cors = {}
                self._sig_ctx = None
                self._anonymous = False
                try:
                    bucket, key, q = self._bucket_key()
                    m = self.command
                    # SLO op class (sw_request_seconds{server="s3",op})
                    if key:
                        self._sw_op = {
                            "GET": "get_object",
                            "HEAD": "head_object",
                            "PUT": "put_object",
                            "POST": "post_object",
                            "DELETE": "delete_object",
                        }.get(m, m.lower())
                    elif bucket:
                        self._sw_op = f"bucket_{m.lower()}"
                    if m == "OPTIONS":
                        # browser preflights carry no Authorization by
                        # spec: they must be evaluated BEFORE auth
                        return self._preflight(bucket)
                    if bucket and self.headers.get("Origin"):
                        # every response (incl. errors and writes) needs
                        # the allow-origin header or browsers block it
                        self._cors = self._cors_response_headers(bucket)
                    if key and m in ("GET", "HEAD", "PUT", "POST", "DELETE"):
                        # Per-tenant graceful shedding: when the EC
                        # residency ledger says THIS gateway's tenant
                        # is over its fair device share during pod
                        # overload, the object data plane backs off
                        # here — before auth, before any device work —
                        # with the same SlowDown+Retry-After contract
                        # the saturated accept path already speaks.
                        # Bucket/control ops stay up so operators can
                        # still inspect and reconfigure mid-storm.
                        ra = srv._shed_retry_after()
                        if ra is not None:
                            ctype, body = _shed_error_doc(srv.tenant)
                            return self._respond(
                                503,
                                body,
                                ctype=ctype,
                                extra={"Retry-After": str(max(1, int(ra)))},
                            )
                    if (
                        m == "POST"
                        and bucket
                        and key == ""
                        and "delete" not in q
                        and self.headers.get("Content-Type", "").startswith(
                            "multipart/form-data"
                        )
                    ):
                        # POST-policy browser upload: authn is the
                        # SigV4 signature over the policy document in
                        # the form itself, not the Authorization header
                        return self._post_policy_upload(bucket)
                    try:
                        # gateway stage: SigV4/OIDC verification cost of
                        # this request (trace.current() = the HTTP root
                        # span the mixin opened; no-op disarmed)
                        from ..utils import trace as _trace

                        with _trace.stage(_trace.current(), "s3.auth"):
                            ident = self._auth()
                    except S3AuthError as e:
                        return self._error(403, e.code, str(e))
                    u = urllib.parse.urlparse(self.path)
                    raw_path = urllib.parse.unquote(u.path)
                    from . import tables as _tables

                    # Precise matchers (no substring hijack of ordinary
                    # object keys): /iceberg/v1/..., the S3Tables
                    # X-Amz-Target protocol, or the CLI's ARN-rooted
                    # REST paths. A user bucket literally named
                    # 'iceberg'/'buckets' is shadowed, exactly like the
                    # reference's own route registration.
                    is_tables = self.headers.get(
                        "X-Amz-Target", ""
                    ).startswith("S3Tables.") or _tables.is_s3tables_path(
                        raw_path
                    )
                    if raw_path.startswith("/iceberg/v1/") or is_tables:
                        # Catalog mutation = admin surface: anonymous
                        # callers are refused, and configured
                        # identities must hold the Admin action (the
                        # normal _authorize path never runs here).
                        if self._anonymous:
                            return self._error(
                                403, "AccessDenied", "catalog requires auth"
                            )
                        if ident is not None and not ident.allows("Admin"):
                            return self._error(
                                403,
                                "AccessDenied",
                                "catalog requires the Admin action",
                            )
                        if raw_path.startswith("/iceberg/v1/"):
                            return _tables.handle_iceberg(
                                self, srv.tables_catalog, raw_path
                            )
                        return _tables.handle_s3tables(
                            self, srv.tables_catalog
                        )
                    if bucket == "" and m == "POST":
                        # STS rides the service endpoint (form POST
                        # with Action=AssumeRole, reference weed/iamapi)
                        form = dict(
                            urllib.parse.parse_qsl(
                                self._read_body().decode("utf-8", "replace")
                            )
                        )
                        if form.get("Action") == "AssumeRole":
                            return self._sts_assume_role(ident, form)
                        from . import iamapi as _iam

                        if form.get("Action") in _iam.ACTIONS:
                            # embedded IAM API (reference weed/iamapi):
                            # credential management is an Admin surface
                            if self._anonymous or (
                                ident is not None
                                and not ident.allows("Admin")
                            ):
                                return self._error(
                                    403,
                                    "AccessDenied",
                                    "IAM requires the Admin action",
                                )
                            try:
                                body = _iam.execute(srv.filer.store, form)
                            except _iam.IamError as e:
                                return self._respond(
                                    e.code, _iam.error_xml(e)
                                )
                            return self._respond(200, body)
                        if (
                            form.get("Action")
                            == "AssumeRoleWithLdapIdentity"
                        ):
                            return self._sts_assume_role_ldap(form)
                        return self._error(405, "MethodNotAllowed", m)
                    err = self._authorize(ident, m, bucket, key, q)
                    if err is not None:
                        return self._error(403, "AccessDenied", err)
                    if bucket == "":
                        if m in ("GET", "HEAD"):
                            return self._list_buckets()
                        return self._error(405, "MethodNotAllowed", m)
                    if key == "":
                        return self._bucket_op(bucket, q)
                    return self._object_op(bucket, key, q)
                except sse.SseError as e:
                    code = {
                        "AccessDenied": 403,
                        "InternalError": 500,
                        "NotImplemented": 501,
                    }.get(e.code, 400)
                    return self._error(code, e.code, str(e))
                except S3AuthError as e:
                    # post-dispatch failures: chunk-signature errors are
                    # auth (403); malformed/truncated bodies are client
                    # errors (400, AWS semantics — SDKs treat 403 as a
                    # credential failure and won't retry)
                    code = (
                        400
                        if e.code
                        in (
                            "IncompleteBody",
                            "InvalidRequest",
                            "MalformedXML",
                            "InvalidArgument",
                        )
                        else 403
                    )
                    return self._error(code, e.code, str(e))
                except NotFound:
                    return self._error(404, "NoSuchKey", "not found")
                except FilerError as e:
                    return self._error(409, "OperationAborted", str(e))
                except (ValueError, ET.ParseError, binascii.Error) as e:
                    return self._error(400, "InvalidArgument", str(e))
                except BrokenPipeError:
                    pass
                finally:
                    # drain any unread body so HTTP/1.1 keep-alive
                    # connections stay in sync
                    try:
                        if not self._body_read:
                            n = int(self.headers.get("Content-Length", "0") or "0")
                            if n:
                                self.rfile.read(n)
                                self._body_read = True
                    except (OSError, ValueError):
                        pass

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = do_OPTIONS = _handle

            # ---- sts ----

            def _sts_assume_role_ldap(self, form: dict):
                """AssumeRoleWithLdapIdentity (reference weed/iam/ldap
                + sts AssumeRoleWithLdapIdentity): the LDAP bind IS the
                authentication, so no SigV4 identity is required. The
                role must trust "*" or "ldap:<username>"."""
                if srv.sts_service is None or srv.ldap is None:
                    return self._error(
                        400, "InvalidAction", "LDAP STS not configured"
                    )
                from ..iam.ldap import LdapError

                username = form.get("LdapUsername", "")
                try:
                    srv.ldap.authenticate(
                        username, form.get("LdapPassword", "")
                    )
                except LdapError as e:
                    return self._error(403, "AccessDenied", f"LDAP: {e}")
                role_name = (
                    form.get("RoleArn", "").rsplit("/", 1)[-1]
                    or form.get("RoleName", "")
                )
                try:
                    cred = srv.sts_service.assume_role(
                        f"ldap:{username}",
                        None,  # LDAP callers carry no IAM policies
                        role_name,
                        int(form.get("DurationSeconds", "3600") or "3600"),
                    )
                except PermissionError as e:
                    return self._error(403, "AccessDenied", str(e))
                except ValueError:
                    return self._error(
                        400, "InvalidParameterValue", "duration"
                    )
                root = ET.Element(
                    "AssumeRoleWithLdapIdentityResponse",
                    xmlns="https://sts.amazonaws.com/doc/2011-06-15/",
                )
                res = _el(root, "AssumeRoleWithLdapIdentityResult")
                c = _el(res, "Credentials")
                _el(c, "AccessKeyId", cred.access_key)
                _el(c, "SecretAccessKey", cred.secret_key)
                _el(c, "SessionToken", cred.session_token)
                _el(
                    c,
                    "Expiration",
                    time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ",
                        time.gmtime(cred.expires_at),
                    ),
                )
                return self._respond(200, _xml(root))

            def _sts_assume_role(self, ident, form: dict):
                if srv.sts_service is None:
                    return self._error(400, "InvalidAction", "STS not configured")
                if ident is None and not srv.identities.empty:
                    return self._error(
                        403, "AccessDenied", "anonymous cannot assume roles"
                    )
                role_name = (
                    form.get("RoleArn", "").rsplit("/", 1)[-1]
                    or form.get("RoleName", "")
                )
                caller_key = ident.access_key if ident else "anonymous"
                caller_policies = (
                    list(ident.policies) if ident and ident.policies else None
                )
                if (
                    ident is not None
                    and not ident.policies
                    and not ident.allows("Admin")
                ):
                    return self._error(
                        403, "AccessDenied", "identity cannot assume roles"
                    )
                try:
                    cred = srv.sts_service.assume_role(
                        caller_key,
                        caller_policies,
                        role_name,
                        int(form.get("DurationSeconds", "3600") or "3600"),
                    )
                except PermissionError as e:
                    return self._error(403, "AccessDenied", str(e))
                except ValueError:
                    return self._error(400, "InvalidParameterValue", "duration")
                root = ET.Element(
                    "AssumeRoleResponse",
                    xmlns="https://sts.amazonaws.com/doc/2011-06-15/",
                )
                res = _el(root, "AssumeRoleResult")
                c = _el(res, "Credentials")
                _el(c, "AccessKeyId", cred.access_key)
                _el(c, "SecretAccessKey", cred.secret_key)
                _el(c, "SessionToken", cred.session_token)
                _el(
                    c,
                    "Expiration",
                    time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(cred.expires_at)
                    ),
                )
                u = _el(res, "AssumedRoleUser")
                _el(u, "Arn", cred.role.arn)
                _el(u, "AssumedRoleId", f"{cred.access_key}:{role_name}")
                self._respond(200, _xml(root))

            # ---- cors ----

            def _cors_rules(self, bucket: str) -> list[dict]:
                raw = srv.filer.store.kv_get(f"cors-rules/{bucket}".encode())
                if raw is None:
                    return []
                try:
                    return json.loads(raw)
                except ValueError:
                    return []

            def _match_cors(self, bucket: str, origin: str, method: str):
                for rule in self._cors_rules(bucket):
                    if method not in rule["methods"]:
                        continue
                    for o in rule["origins"]:
                        if o == "*" or o == origin:
                            return rule, o
                return None, None

            def _preflight(self, bucket: str):
                origin = self.headers.get("Origin", "")
                method = self.headers.get("Access-Control-Request-Method", "")
                rule, matched = self._match_cors(bucket, origin, method)
                if rule is None:
                    return self._error(403, "AccessForbidden", "CORSResponse")
                self._respond(
                    200,
                    extra={
                        "Access-Control-Allow-Origin": "*" if matched == "*" else origin,
                        "Access-Control-Allow-Methods": ", ".join(rule["methods"]),
                        "Access-Control-Allow-Headers": ", ".join(
                            rule["headers"]
                        )
                        or "*",
                        "Access-Control-Max-Age": "3600",
                    },
                )

            def _cors_response_headers(self, bucket: str) -> dict:
                origin = self.headers.get("Origin", "")
                if not origin:
                    return {}
                rule, matched = self._match_cors(bucket, origin, self.command)
                if rule is None:
                    return {}
                return {
                    "Access-Control-Allow-Origin": "*" if matched == "*" else origin,
                    "Vary": "Origin",
                }

            # ---- service ----

            def _list_buckets(self):
                root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
                owner = _el(root, "Owner")
                _el(owner, "ID", "seaweedfs_tpu")
                buckets = _el(root, "Buckets")
                try:
                    for e in srv.filer.list_entries(BUCKETS_ROOT, limit=10_000):
                        if not e.is_directory or e.name == UPLOADS_DIR:
                            continue
                        b = _el(buckets, "Bucket")
                        _el(b, "Name", e.name)
                        _el(b, "CreationDate", _iso(e.attr.crtime))
                except NotFound:
                    pass
                self._respond(200, _xml(root))

            # ---- bucket ----

            def _bucket_op(self, bucket: str, q: dict):
                path = f"{BUCKETS_ROOT}/{bucket}"
                m = self.command
                if m == "PUT" and "cors" in q:
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    body = self._read_body()
                    try:
                        doc = ET.fromstring(body)
                    except ET.ParseError:
                        return self._error(400, "MalformedXML", "cors config")
                    ns = _xml_ns(doc)
                    rules = []
                    for rule in doc.iter(f"{ns}CORSRule"):
                        rules.append(
                            {
                                "origins": [
                                    e.text or ""
                                    for e in rule.findall(f"{ns}AllowedOrigin")
                                ],
                                "methods": [
                                    e.text or ""
                                    for e in rule.findall(f"{ns}AllowedMethod")
                                ],
                                "headers": [
                                    e.text or ""
                                    for e in rule.findall(f"{ns}AllowedHeader")
                                ],
                            }
                        )
                    if not rules:
                        return self._error(400, "MalformedXML", "no CORSRule")
                    # parsed ONCE here; the hot read path loads JSON
                    srv.filer.store.kv_put(f"cors/{bucket}".encode(), body)
                    srv.filer.store.kv_put(
                        f"cors-rules/{bucket}".encode(),
                        json.dumps(rules).encode(),
                    )
                    return self._respond(200)
                if m == "DELETE" and "cors" in q:
                    srv.filer.store.kv_delete(f"cors/{bucket}".encode())
                    srv.filer.store.kv_delete(f"cors-rules/{bucket}".encode())
                    return self._respond(204)
                if m == "PUT" and "versioning" in q:
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    doc = ET.fromstring(self._read_body())
                    ns = _xml_ns(doc)
                    status = doc.findtext(f"{ns}Status") or ""
                    if status not in ("Enabled", "Suspended"):
                        return self._error(
                            400, "MalformedXML", f"bad Status {status!r}"
                        )
                    if status == "Suspended" and srv.lock_conf(bucket):
                        # AWS: object-lock buckets cannot suspend versioning
                        return self._error(
                            409,
                            "InvalidBucketState",
                            "object lock requires versioning",
                        )
                    srv.filer.store.kv_put(
                        f"versioning/{bucket}".encode(), status.encode()
                    )
                    return self._respond(200)
                if m == "PUT" and "object-lock" in q:
                    return self._put_object_lock_conf(bucket, path)
                if m == "PUT" and "lifecycle" in q:
                    return self._put_lifecycle(bucket, path)
                if "policy" in q or "policyStatus" in q:
                    return self._bucket_policy_op(bucket, path, q)
                if "encryption" in q:
                    return self._bucket_encryption_op(bucket, path)
                if "acl" in q:
                    return self._bucket_acl_op(bucket, path)
                if m == "DELETE" and "lifecycle" in q:
                    srv.filer.store.kv_delete(f"lifecycle/{bucket}".encode())
                    srv.filer.store.kv_delete(
                        f"lifecycle-rules/{bucket}".encode()
                    )
                    return self._respond(204)
                if m == "PUT":
                    # bucket names double as volume collections: enforce
                    # S3 naming up front so object uploads can't fail on
                    # the master's collection validation later
                    if not _BUCKET_RE.match(bucket):
                        return self._error(400, "InvalidBucketName", bucket)
                    if srv.filer.exists(path):
                        return self._error(
                            409, "BucketAlreadyExists", bucket
                        )
                    srv.filer.create_entry(
                        new_entry(path, is_directory=True, mode=0o755)
                    )
                    if (
                        self.headers.get(
                            "x-amz-bucket-object-lock-enabled", ""
                        ).lower()
                        == "true"
                    ):
                        # lock implies versioning (AWS invariant)
                        srv.filer.store.kv_put(
                            f"object-lock/{bucket}".encode(),
                            json.dumps({"Enabled": True}).encode(),
                        )
                        srv.filer.store.kv_put(
                            f"versioning/{bucket}".encode(), b"Enabled"
                        )
                    return self._respond(200, extra={"Location": "/" + bucket})
                if m == "HEAD":
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    return self._respond(200)
                if m == "DELETE":
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    children = list(srv.filer.list_entries(path, limit=2))
                    if children:
                        return self._error(409, "BucketNotEmpty", bucket)
                    srv.filer.delete_entry(path, recursive=True)
                    # a future bucket of the same name must not inherit
                    # this one's CORS/policy/ACL/encryption grants
                    srv.filer.store.kv_delete(f"cors/{bucket}".encode())
                    srv.filer.store.kv_delete(f"cors-rules/{bucket}".encode())
                    srv.filer.store.kv_delete(f"policy/{bucket}".encode())
                    srv.filer.store.kv_delete(f"acl/{bucket}".encode())
                    srv.filer.store.kv_delete(f"encryption/{bucket}".encode())
                    srv.filer.store.kv_delete(f"quota/{bucket}".encode())
                    srv.filer.store.kv_delete(
                        f"quota-exceeded/{bucket}".encode()
                    )
                    # fast space reclaim: drop the bucket's collection
                    # volumes cluster-wide (reference bucket=collection)
                    try:
                        srv.filer.ops.master.collection_delete(bucket)
                    except Exception:
                        pass
                    return self._respond(204)
                if m == "POST" and "delete" in q:
                    return self._delete_objects(bucket)
                if m == "GET":
                    if not srv.filer.exists(path):
                        return self._error(404, "NoSuchBucket", bucket)
                    if "location" in q:
                        root = ET.Element("LocationConstraint", xmlns=XMLNS)
                        root.text = srv.region
                        return self._respond(200, _xml(root))
                    if "cors" in q:
                        raw = srv.filer.store.kv_get(f"cors/{bucket}".encode())
                        if raw is None:
                            return self._error(
                                404, "NoSuchCORSConfiguration", bucket
                            )
                        return self._respond(200, raw)
                    if "versioning" in q:
                        root = ET.Element("VersioningConfiguration", xmlns=XMLNS)
                        state = srv.bucket_versioning(bucket)
                        if state:
                            _el(root, "Status", state)
                        return self._respond(200, _xml(root))
                    if "object-lock" in q:
                        return self._get_object_lock_conf(bucket)
                    if "lifecycle" in q:
                        raw = srv.filer.store.kv_get(
                            f"lifecycle/{bucket}".encode()
                        )
                        if raw is None:
                            return self._error(
                                404,
                                "NoSuchLifecycleConfiguration",
                                bucket,
                            )
                        return self._respond(200, raw)
                    if "versions" in q:
                        return self._list_object_versions(bucket, q)
                    if "uploads" in q:
                        return self._list_uploads(bucket)
                    return self._list_objects(bucket, q)
                return self._error(405, "MethodNotAllowed", m)

            def _list_objects(self, bucket: str, q: dict):
                prefix = q.get("prefix", "")
                delimiter = q.get("delimiter", "")
                v2 = q.get("list-type") == "2"
                max_keys = min(int(q.get("max-keys", "1000") or "1000"), 1000)
                token = (
                    q.get("continuation-token") or q.get("start-after") or ""
                    if v2
                    else q.get("marker", "")
                )
                if v2 and q.get("continuation-token"):
                    token = base64.urlsafe_b64decode(
                        q["continuation-token"].encode()
                    ).decode()

                contents, common, truncated, next_token = srv._walk_keys(
                    bucket, prefix, delimiter, token, max_keys
                )
                root = ET.Element("ListBucketResult", xmlns=XMLNS)
                _el(root, "Name", bucket)
                _el(root, "Prefix", prefix)
                if delimiter:
                    _el(root, "Delimiter", delimiter)
                _el(root, "MaxKeys", max_keys)
                _el(root, "KeyCount", len(contents) + len(common))
                _el(root, "IsTruncated", "true" if truncated else "false")
                if v2 and truncated:
                    _el(
                        root,
                        "NextContinuationToken",
                        base64.urlsafe_b64encode(next_token.encode()).decode(),
                    )
                elif not v2:
                    _el(root, "Marker", q.get("marker", ""))
                    if truncated:
                        _el(root, "NextMarker", next_token)
                for key, entry in contents:
                    c = _el(root, "Contents")
                    _el(c, "Key", key)
                    _el(c, "LastModified", _iso(entry.attr.mtime))
                    _el(c, "ETag", f'"{_entry_etag(entry)}"')
                    _el(c, "Size", entry.file_size)
                    _el(c, "StorageClass", "STANDARD")
                for p in sorted(common):
                    cp = _el(root, "CommonPrefixes")
                    _el(cp, "Prefix", p)
                self._respond(200, _xml(root))

            def _delete_objects(self, bucket: str):
                body = self._read_body()
                doc = ET.fromstring(body)
                ns = _xml_ns(doc)
                quiet = (doc.findtext(f"{ns}Quiet") or "").lower() == "true"
                root = ET.Element("DeleteResult", xmlns=XMLNS)
                state = srv.bucket_versioning(bucket)
                bypass = (
                    self.headers.get(
                        "x-amz-bypass-governance-retention", ""
                    ).lower()
                    == "true"
                )
                for obj in doc.findall(f"{ns}Object"):
                    key = obj.findtext(f"{ns}Key") or ""
                    vid_param = obj.findtext(f"{ns}VersionId") or ""
                    path = normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}")
                    try:
                        marker_vid = ""
                        if vid_param:
                            try:
                                cur = srv.filer.find_entry(path)
                            except NotFound:
                                cur = None
                            if (
                                cur is not None
                                and not cur.is_directory
                                and entry_vid(cur) == vid_param
                            ):
                                check_deletable(cur, bypass)
                                srv.filer.delete_entry(path, gc_chunks=True)
                                promote_latest(
                                    srv.filer, BUCKETS_ROOT, bucket, key
                                )
                            else:
                                vpath = f"{versions_dir(BUCKETS_ROOT, bucket, key)}/{vid_param}"
                                try:
                                    ve = srv.filer.find_entry(vpath)
                                    check_deletable(ve, bypass)
                                    srv.filer.delete_entry(
                                        vpath, gc_chunks=True
                                    )
                                except NotFound:
                                    pass
                        elif state:
                            marker_vid = vtag.write_delete_marker(
                                srv.filer, BUCKETS_ROOT, bucket, key, state
                            )
                        else:
                            srv.filer.delete_entry(path, recursive=True)
                        if not quiet:
                            d = _el(root, "Deleted")
                            _el(d, "Key", key)
                            if vid_param:
                                _el(d, "VersionId", vid_param)
                            if marker_vid:
                                _el(d, "DeleteMarker", "true")
                                _el(d, "DeleteMarkerVersionId", marker_vid)
                    except LockViolation as e:
                        er = _el(root, "Error")
                        _el(er, "Key", key)
                        _el(er, "Code", "AccessDenied")
                        _el(er, "Message", str(e))
                    except FilerError as e:
                        er = _el(root, "Error")
                        _el(er, "Key", key)
                        _el(er, "Code", "InternalError")
                        _el(er, "Message", str(e))
                self._respond(200, _xml(root))

            # ---- object lock / lifecycle / versions (bucket level) ----

            def _put_object_lock_conf(self, bucket: str, path: str):
                if not srv.filer.exists(path):
                    return self._error(404, "NoSuchBucket", bucket)
                doc = ET.fromstring(self._read_body())
                ns = _xml_ns(doc)
                if (doc.findtext(f"{ns}ObjectLockEnabled") or "") != "Enabled":
                    return self._error(
                        400, "MalformedXML", "ObjectLockEnabled must be Enabled"
                    )
                conf: dict = {"Enabled": True}
                dr = doc.find(f"{ns}Rule/{ns}DefaultRetention")
                if dr is not None:
                    conf["DefaultRetention"] = {
                        "Mode": dr.findtext(f"{ns}Mode") or "GOVERNANCE",
                        "Days": int(dr.findtext(f"{ns}Days") or "0"),
                        "Years": int(dr.findtext(f"{ns}Years") or "0"),
                    }
                srv.filer.store.kv_put(
                    f"object-lock/{bucket}".encode(), json.dumps(conf).encode()
                )
                # lock requires versioning on
                srv.filer.store.kv_put(
                    f"versioning/{bucket}".encode(), b"Enabled"
                )
                return self._respond(200)

            def _get_object_lock_conf(self, bucket: str):
                conf = srv.lock_conf(bucket)
                if conf is None:
                    return self._error(
                        404,
                        "ObjectLockConfigurationNotFoundError",
                        bucket,
                    )
                root = ET.Element("ObjectLockConfiguration", xmlns=XMLNS)
                _el(root, "ObjectLockEnabled", "Enabled")
                dr = conf.get("DefaultRetention")
                if dr:
                    rule = _el(root, "Rule")
                    drel = _el(rule, "DefaultRetention")
                    _el(drel, "Mode", dr.get("Mode", "GOVERNANCE"))
                    if dr.get("Days"):
                        _el(drel, "Days", dr["Days"])
                    if dr.get("Years"):
                        _el(drel, "Years", dr["Years"])
                return self._respond(200, _xml(root))

            def _put_lifecycle(self, bucket: str, path: str):
                from .lifecycle import parse_lifecycle_xml

                if not srv.filer.exists(path):
                    return self._error(404, "NoSuchBucket", bucket)
                body = self._read_body()
                try:
                    rules = parse_lifecycle_xml(body)
                except ValueError as e:
                    return self._error(400, "MalformedXML", str(e))
                if not rules:
                    return self._error(400, "MalformedXML", "no Rule")
                srv.filer.store.kv_put(f"lifecycle/{bucket}".encode(), body)
                srv.filer.store.kv_put(
                    f"lifecycle-rules/{bucket}".encode(),
                    json.dumps(rules).encode(),
                )
                return self._respond(200)

            def _select_object(self, bucket: str, key: str, path: str):
                """SelectObjectContent (?select&select-type=2): SQL over
                one object via the framework's own query engine, with
                the AWS event-stream response framing (reference: the
                volume-server Query RPC / s3api select route)."""
                from ..query.engine import QueryError
                from . import select as s3sel

                try:
                    entry = srv.filer.find_entry(path)
                except NotFound:
                    return self._error(404, "NoSuchKey", key)
                try:
                    doc = ET.fromstring(self._read_body())
                except ET.ParseError:
                    return self._error(400, "MalformedXML", "select request")
                ns = _xml_ns(doc)

                def section(tag: str) -> dict:
                    el = doc.find(f"{ns}{tag}")
                    out: dict = {}
                    if el is None:
                        return out
                    for child in el:
                        cname = child.tag.split("}")[-1]
                        if len(child):
                            out[cname] = {
                                g.tag.split("}")[-1]: (g.text or "")
                                for g in child
                            }
                        elif child.text and child.text.strip():
                            out[cname] = child.text.strip()
                        else:
                            out[cname] = {}  # empty section like <JSON/>
                    return out

                expression = doc.findtext(f"{ns}Expression") or ""
                if (
                    doc.findtext(f"{ns}ExpressionType") or "SQL"
                ).upper() != "SQL":
                    return self._error(
                        400, "InvalidArgument", "ExpressionType must be SQL"
                    )
                input_ser = section("InputSerialization")
                output_ser = section("OutputSerialization")
                # SSE: decrypt before querying (fail closed like GET)
                data = srv.filer.read_entry(entry)
                data_key = sse.decrypt_key_for_entry(
                    entry,
                    sse.parse_customer_headers(self.headers),
                    srv.sse_keyring,
                )
                if data_key is not None:
                    data = sse.read_decrypted(
                        lambda o, n: data[o:] if n < 0 else data[o : o + n],
                        entry,
                        data_key,
                        0,
                        -1,
                    )
                try:
                    body = s3sel.select_object_content(
                        data, expression, input_ser, output_ser
                    )
                except QueryError as e:
                    return self._error(400, "InvalidQuery", str(e))
                except (
                    ValueError,
                    json.JSONDecodeError,
                    OSError,
                    EOFError,  # gzip truncated-stream signal
                    zlib.error,  # corrupt deflate payload
                ) as e:
                    return self._error(
                        400, "InvalidTextEncoding", repr(e)[:200]
                    )
                return self._respond(
                    200, body, ctype="application/octet-stream"
                )

            # ---- bucket policy / encryption / acl subresources ----

            def _bucket_policy_op(self, bucket: str, path: str, q: dict):
                if not srv.filer.exists(path):
                    return self._error(404, "NoSuchBucket", bucket)
                m = self.command
                kv_key = f"policy/{bucket}".encode()
                from ..iam.policy import (
                    PolicyError,
                    bucket_policy_is_public,
                    validate_bucket_policy,
                )

                if m == "GET" and "policyStatus" in q:
                    doc = srv.bucket_policy(bucket)
                    if doc is None:
                        return self._error(
                            404, "NoSuchBucketPolicy", bucket
                        )
                    root = ET.Element("PolicyStatus", xmlns=XMLNS)
                    _el(
                        root,
                        "IsPublic",
                        "true" if bucket_policy_is_public(doc) else "false",
                    )
                    return self._respond(200, _xml(root))
                if m == "GET":
                    raw = srv.filer.store.kv_get(kv_key)
                    if raw is None:
                        return self._error(404, "NoSuchBucketPolicy", bucket)
                    return self._respond(200, raw, ctype="application/json")
                if m == "PUT":
                    body = self._read_body()
                    try:
                        doc = json.loads(body)
                        validate_bucket_policy(doc, bucket)
                    except json.JSONDecodeError:
                        return self._error(
                            400, "MalformedPolicy", "policy is not JSON"
                        )
                    except PolicyError as e:
                        return self._error(400, "MalformedPolicy", str(e))
                    srv.filer.store.kv_put(kv_key, body)
                    return self._respond(204)
                if m == "DELETE":
                    srv.filer.store.kv_delete(kv_key)
                    return self._respond(204)
                return self._error(405, "MethodNotAllowed", m)

            def _bucket_encryption_op(self, bucket: str, path: str):
                if not srv.filer.exists(path):
                    return self._error(404, "NoSuchBucket", bucket)
                m = self.command
                kv_key = f"encryption/{bucket}".encode()
                if m == "GET":
                    algo = srv.bucket_default_encryption(bucket)
                    if not algo:
                        return self._error(
                            404,
                            "ServerSideEncryptionConfigurationNotFoundError",
                            bucket,
                        )
                    root = ET.Element(
                        "ServerSideEncryptionConfiguration", xmlns=XMLNS
                    )
                    rule = ET.SubElement(root, "Rule")
                    dflt = ET.SubElement(
                        rule, "ApplyServerSideEncryptionByDefault"
                    )
                    _el(dflt, "SSEAlgorithm", algo)
                    return self._respond(200, _xml(root))
                if m == "PUT":
                    try:
                        doc = ET.fromstring(self._read_body())
                    except ET.ParseError:
                        return self._error(400, "MalformedXML", "encryption config")
                    ns = _xml_ns(doc)
                    algo = doc.findtext(
                        f".//{ns}ApplyServerSideEncryptionByDefault/{ns}SSEAlgorithm"
                    ) or doc.findtext(f".//{ns}SSEAlgorithm")
                    if algo == "aws:kms":
                        return self._error(
                            501,
                            "NotImplemented",
                            "aws:kms requires an external KMS provider",
                        )
                    if algo != "AES256":
                        return self._error(
                            400, "MalformedXML", f"bad SSEAlgorithm {algo!r}"
                        )
                    srv.filer.store.kv_put(kv_key, b"AES256")
                    return self._respond(200)
                if m == "DELETE":
                    srv.filer.store.kv_delete(kv_key)
                    return self._respond(204)
                return self._error(405, "MethodNotAllowed", m)

            _CANNED_ACLS = (
                "private",
                "public-read",
                "public-read-write",
                "authenticated-read",
                "bucket-owner-read",
                "bucket-owner-full-control",
            )

            def _validate_canned_acl(self, acl: str) -> str:
                if acl not in self._CANNED_ACLS:
                    raise S3AuthError(
                        "InvalidArgument", f"unknown canned acl {acl!r}"
                    )
                return acl

            def _canned_acl_header(self) -> str | None:
                """Validated x-amz-acl request header (None if absent)."""
                acl = self.headers.get("x-amz-acl", "")
                return self._validate_canned_acl(acl) if acl else None

            def _acl_xml(self, acl: str) -> bytes:
                root = ET.Element("AccessControlPolicy", xmlns=XMLNS)
                owner = ET.SubElement(root, "Owner")
                _el(owner, "ID", "seaweedfs")
                grants = ET.SubElement(root, "AccessControlList")

                def grant(grantee_uri: str | None, perm: str):
                    g = ET.SubElement(grants, "Grant")
                    ge = ET.SubElement(g, "Grantee")
                    ge.set(
                        "{http://www.w3.org/2001/XMLSchema-instance}type",
                        "Group" if grantee_uri else "CanonicalUser",
                    )
                    if grantee_uri:
                        _el(ge, "URI", grantee_uri)
                    else:
                        _el(ge, "ID", "seaweedfs")
                    _el(g, "Permission", perm)

                grant(None, "FULL_CONTROL")
                AU = "http://acs.amazonaws.com/groups/global/AllUsers"
                if acl in ("public-read", "public-read-write"):
                    grant(AU, "READ")
                if acl == "public-read-write":
                    grant(AU, "WRITE")
                if acl == "authenticated-read":
                    grant(
                        "http://acs.amazonaws.com/groups/global/AuthenticatedUsers",
                        "READ",
                    )
                return _xml(root)

            def _bucket_acl_op(self, bucket: str, path: str):
                if not srv.filer.exists(path):
                    return self._error(404, "NoSuchBucket", bucket)
                m = self.command
                if m == "GET":
                    return self._respond(200, self._acl_xml(srv.bucket_acl(bucket)))
                if m == "PUT":
                    acl = self._canned_acl_header() or "private"
                    srv.filer.store.kv_put(f"acl/{bucket}".encode(), acl.encode())
                    return self._respond(200)
                return self._error(405, "MethodNotAllowed", m)

            def _object_acl_op(self, bucket: str, key: str, path: str):
                try:
                    entry = srv.filer.find_entry(path)
                except NotFound:
                    return self._error(404, "NoSuchKey", key)
                m = self.command
                if m == "GET":
                    acl = (entry.extended.get("s3-acl") or b"private").decode()
                    return self._respond(200, self._acl_xml(acl))
                if m == "PUT":
                    acl = self._canned_acl_header() or "private"
                    srv.filer.mutate_entry(
                        path,
                        lambda e: e.extended.update({"s3-acl": acl.encode()}),
                    )
                    return self._respond(200)
                return self._error(405, "MethodNotAllowed", m)

            # ---- POST-policy browser uploads ----

            def _post_policy_upload(self, bucket: str):
                if not srv.filer.exists(f"{BUCKETS_ROOT}/{bucket}"):
                    return self._error(404, "NoSuchBucket", bucket)
                body = self._read_body()
                ident = None
                try:
                    fields, file_bytes, filename = ppol.parse_multipart_form(
                        body, self.headers.get("Content-Type", "")
                    )
                    key = fields.get("key", "")
                    if not key:
                        return self._error(
                            400, "InvalidArgument", "POST form missing key"
                        )
                    key = key.replace("${filename}", filename)
                    if not srv.identities.empty:
                        ident = ppol.verify_post_signature(
                            srv.identities, fields, srv.region
                        )
                        ppol.check_policy_document(
                            fields, len(file_bytes), bucket, key
                        )
                    elif srv.oidc is not None:
                        # Mirror _auth: an OIDC-only deployment (empty
                        # SigV4 store) must NOT mean open mode — an
                        # unsigned POST-policy form is ANONYMOUS, so
                        # only a bucket-policy/ACL grant can allow it.
                        self._anonymous = True
                except S3AuthError as e:
                    code = 403 if e.code in (
                        "AccessDenied",
                        "SignatureDoesNotMatch",
                        "InvalidAccessKeyId",
                    ) else 400
                    return self._error(code, e.code, str(e))
                # Authentication is not authorization: the signer must
                # also be ALLOWED to put this object (identity policies
                # + bucket policy; a self-signed form from a read-only
                # credential must not write).
                err = self._authorize(ident, "PUT", bucket, key, {})
                if err is not None:
                    return self._error(403, "AccessDenied", err)
                if srv.quota_exceeded(bucket):
                    return self._error(
                        403,
                        "QuotaExceeded",
                        f"bucket {bucket} is over its storage quota",
                    )
                # SSE: explicit form header fields are not standard;
                # bucket default encryption still applies
                sse_algo = srv.bucket_default_encryption(bucket)
                data, sse_ext, sse_hdrs = sse.encrypt_for_put(
                    file_bytes, None, sse_algo, srv.sse_keyring
                )
                ext = dict(sse_ext)
                acl = fields.get("acl", "")
                if acl:
                    self._validate_canned_acl(acl)
                    ext["s3-acl"] = acl.encode()
                entry, vid = srv.put_object(
                    bucket,
                    key,
                    data,
                    mime=fields.get("content-type", "")
                    or "application/octet-stream",
                    extra_extended=ext,
                )
                status = int(fields.get("success_action_status", "204") or 204)
                if status not in (200, 201, 204):
                    status = 204
                extra = {"ETag": f'"{entry.attr.md5.hex()}"', **sse_hdrs}
                if vid:
                    extra["x-amz-version-id"] = vid
                if status == 201:
                    root = ET.Element("PostResponse")
                    _el(root, "Bucket", bucket)
                    _el(root, "Key", key)
                    _el(root, "ETag", f'"{entry.attr.md5.hex()}"')
                    return self._respond(201, _xml(root), extra=extra)
                return self._respond(status, extra=extra)

            def _list_object_versions(self, bucket: str, q: dict):
                prefix = q.get("prefix", "")
                max_keys = min(int(q.get("max-keys", "1000") or "1000"), 1000)
                contents, _, key_truncated, _ = srv._walk_keys(
                    bucket, prefix, "", q.get("key-marker", ""), max_keys,
                    include_markers=True,
                )
                root = ET.Element("ListVersionsResult", xmlns=XMLNS)
                _el(root, "Name", bucket)
                _el(root, "Prefix", prefix)
                _el(root, "MaxKeys", max_keys)

                elements: list = []

                def emit(key, entry, latest: bool):
                    tag = (
                        "DeleteMarker" if is_delete_marker(entry) else "Version"
                    )
                    el = ET.Element(tag)
                    _el(el, "Key", key)
                    _el(el, "VersionId", entry_vid(entry))
                    _el(el, "IsLatest", "true" if latest else "false")
                    _el(el, "LastModified", _iso(entry.attr.mtime))
                    if tag == "Version":
                        _el(el, "ETag", f'"{_entry_etag(entry)}"')
                        _el(el, "Size", entry.file_size)
                        _el(el, "StorageClass", "STANDARD")
                    elements.append(el)

                # resume granularity is the key: emit whole keys until
                # the version budget is spent, then signal truncation
                truncated = key_truncated
                next_marker = ""
                for key, entry in contents:
                    if len(elements) >= max_keys:
                        truncated = True
                        break
                    emit(key, entry, True)
                    for v in iter_versions(
                        srv.filer, BUCKETS_ROOT, bucket, key
                    ):
                        emit(key, v, False)
                    next_marker = key
                _el(root, "IsTruncated", "true" if truncated else "false")
                if truncated and next_marker:
                    _el(root, "NextKeyMarker", next_marker)
                root.extend(elements)
                self._respond(200, _xml(root))

            # ---- object ----

            def _put_object_body(self, bucket: str, key: str):
                """The shared plain-PUT body (copy, SSE, ACL, store);
                callers have already evaluated quotas/preconditions."""
                src = self.headers.get("x-amz-copy-source", "")
                if src:
                    return self._copy_object(bucket, key, src)
                data = self._read_body()
                ext = self._lock_headers_extended(bucket)
                # server-side encryption: explicit SSE-C / SSE-S3
                # headers, else the bucket's default configuration
                ssec_key, sse_algo = sse.resolve_put_encryption(
                    self.headers, srv.bucket_default_encryption(bucket)
                )
                data, sse_ext, sse_hdrs = sse.encrypt_for_put(
                    data, ssec_key, sse_algo, srv.sse_keyring
                )
                ext.update(sse_ext)
                acl = self._canned_acl_header()
                if acl:
                    ext["s3-acl"] = acl.encode()
                entry, vid = srv.put_object(
                    bucket,
                    key,
                    data,
                    mime=self.headers.get("Content-Type", "")
                    or "application/octet-stream",
                    extra_extended=ext,
                )
                etag = entry.attr.md5.hex()
                extra = {"ETag": f'"{etag}"', **sse_hdrs}
                if vid:
                    extra["x-amz-version-id"] = vid
                return self._respond(200, extra=extra)

            def _object_op(self, bucket: str, key: str, q: dict):
                bpath = f"{BUCKETS_ROOT}/{bucket}"
                if not srv.filer.exists(bpath):
                    return self._error(404, "NoSuchBucket", bucket)
                path = normalize_path(f"{bpath}/{key}")
                m = self.command
                if m == "POST" and "uploads" in q:
                    return self._initiate_multipart(bucket, key)
                if m == "PUT" and "partNumber" in q and "uploadId" in q:
                    return self._upload_part(bucket, key, q)
                if m == "POST" and "uploadId" in q:
                    return self._complete_multipart(bucket, key, q)
                if m == "DELETE" and "uploadId" in q:
                    return self._abort_multipart(bucket, key, q)
                if m == "GET" and "uploadId" in q:
                    return self._list_parts(bucket, key, q)

                if m == "POST" and "select" in q:
                    return self._select_object(bucket, key, path)
                if "tagging" in q:
                    return self._object_tagging(bucket, key, path)
                if "retention" in q:
                    return self._object_retention(bucket, key, path, q)
                if "legal-hold" in q:
                    return self._object_legal_hold(bucket, key, path, q)
                if "acl" in q:
                    return self._object_acl_op(bucket, key, path)

                if m == "PUT":
                    if srv.quota_exceeded(bucket):
                        return self._error(
                            403,
                            "QuotaExceeded",
                            f"bucket {bucket} is over its storage quota",
                        )
                    # AWS conditional writes: If-None-Match: * =
                    # create-only; If-Match: <etag> = compare-and-swap.
                    # The precondition and the write hold one lock so
                    # two racing CAS PUTs can never both pass the check
                    # (check-then-act would lose an update silently).
                    inm = self.headers.get("If-None-Match", "")
                    im = self.headers.get("If-Match", "")
                    if inm and inm != "*":
                        # AWS: conditional writes only support '*'
                        return self._error(
                            501,
                            "NotImplemented",
                            "If-None-Match only supports *",
                        )
                    with srv.put_lock(path):
                        if inm or im:
                            try:
                                cur = srv.filer.find_entry(path)
                            except NotFound:
                                cur = None
                            if cur is not None and (
                                cur.is_directory
                                or vtag.is_delete_marker(cur)
                            ):
                                # logically absent: a delete marker or
                                # a directory placeholder is NOT an
                                # object (AWS create-only PUT succeeds
                                # over a deleted key)
                                cur = None
                            if inm == "*" and cur is not None:
                                return self._error(
                                    412,
                                    "PreconditionFailed",
                                    "object already exists "
                                    "(If-None-Match: *)",
                                )
                            if im:
                                cur_etag = (
                                    _entry_etag(cur)
                                    if cur is not None
                                    else ""
                                )
                                if not cur_etag or not _etag_cond_match(
                                    im, cur_etag
                                ):
                                    return self._error(
                                        412,
                                        "PreconditionFailed",
                                        "ETag mismatch (If-Match)",
                                    )
                        return self._put_object_body(bucket, key)
                if m in ("GET", "HEAD"):
                    vid_param = q.get("versionId", "")
                    entry = self._resolve_version(bucket, key, path, vid_param)
                    if entry is None:
                        return  # _resolve_version responded
                    # SSE: resolve the data key BEFORE emitting any
                    # bytes (fail closed — never serve ciphertext), and
                    # advertise the object's encryption in the response.
                    sse_data_key = sse.decrypt_key_for_entry(
                        entry,
                        sse.parse_customer_headers(self.headers),
                        srv.sse_keyring,
                    )
                    total = entry.file_size
                    headers = {
                        **sse.response_headers_for_entry(entry),
                        **self._cors_response_headers(bucket),
                        "ETag": f'"{_entry_etag(entry)}"',
                        "Last-Modified": time.strftime(
                            "%a, %d %b %Y %H:%M:%S GMT",
                            time.gmtime(entry.attr.mtime),
                        ),
                        "Accept-Ranges": "bytes",
                    }
                    if srv.bucket_versioning(bucket):
                        headers["x-amz-version-id"] = entry_vid(entry)
                    mode, until = vtag.get_retention(entry)
                    if mode:
                        headers["x-amz-object-lock-mode"] = mode
                        headers["x-amz-object-lock-retain-until-date"] = (
                            until.isoformat()
                        )
                    ctype = entry.attr.mime or "application/octet-stream"
                    # conditional reads (RFC 9110 semantics, the subset
                    # S3 documents): If-(None-)Match on the ETag,
                    # If-(Un)Modified-Since on Last-Modified
                    etag_now = _entry_etag(entry)
                    inm = self.headers.get("If-None-Match", "")
                    ims_ts = _http_date(
                        self.headers.get("If-Modified-Since", "")
                    )
                    if (inm and _etag_cond_match(inm, etag_now)) or (
                        not inm
                        and ims_ts is not None
                        and entry.attr.mtime <= ims_ts
                    ):
                        self.send_response(304)
                        for hk, hv in headers.items():
                            self.send_header(hk, hv)
                        self.end_headers()
                        return
                    imatch = self.headers.get("If-Match", "")
                    ius_ts = _http_date(
                        self.headers.get("If-Unmodified-Since", "")
                    )
                    if (
                        imatch and not _etag_cond_match(imatch, etag_now)
                    ) or (
                        not imatch
                        and ius_ts is not None
                        and entry.attr.mtime > ius_ts
                    ):
                        return self._error(
                            412, "PreconditionFailed", "precondition failed"
                        )
                    if m == "HEAD":
                        self.send_response(200)
                        for k, v in headers.items():
                            self.send_header(k, v)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(total))
                        self.end_headers()
                        return
                    rng = self.headers.get("Range", "")
                    offset, size, status = 0, -1, 200
                    if rng.startswith("bytes="):
                        try:
                            lo_s, _, hi_s = rng[6:].split(",")[0].partition("-")
                            lo = int(lo_s) if lo_s else max(total - int(hi_s), 0)
                            hi = int(hi_s) if hi_s and lo_s else total - 1
                            if lo > hi or lo >= max(total, 1):
                                return self._respond(
                                    416,
                                    extra={"Content-Range": f"bytes */{total}"},
                                )
                            offset, size, status = lo, hi - lo + 1, 206
                            headers["Content-Range"] = (
                                f"bytes {lo}-{min(hi, total - 1)}/{total}"
                            )
                        except ValueError:
                            pass
                    if sse_data_key is None:
                        data = srv.filer.read_entry(entry, offset, size)
                    else:
                        # unified CTR seek: single-IV objects and
                        # multipart part-maps (per-part streams)
                        data = sse.read_decrypted(
                            lambda o, n: srv.filer.read_entry(entry, o, n),
                            entry,
                            sse_data_key,
                            offset,
                            size,
                        )
                    return self._respond(status, data, ctype, headers)
                if m == "DELETE":
                    # same stripe as writes: a DELETE racing an
                    # If-Match PUT must not resurrect/lose either side
                    with srv.put_lock(path):
                        return self._delete_object(bucket, key, path, q)
                return self._error(405, "MethodNotAllowed", m)

            def _lock_headers_extended(self, bucket: str) -> dict:
                """x-amz-object-lock-* request headers → extended attrs.

                AWS rejects lock headers on buckets without an object
                lock configuration (otherwise a bogus COMPLIANCE lock
                could be stored with no API path to ever clear it)."""
                mode = self.headers.get("x-amz-object-lock-mode", "")
                until = self.headers.get(
                    "x-amz-object-lock-retain-until-date", ""
                )
                hold = self.headers.get("x-amz-object-lock-legal-hold", "")
                if not (mode or until or hold):
                    return {}
                if srv.lock_conf(bucket) is None:
                    raise S3AuthError(
                        "InvalidRequest",
                        "bucket has no object lock configuration",
                    )
                ext: dict = {}
                if mode or until:
                    if mode not in ("GOVERNANCE", "COMPLIANCE") or not until:
                        raise S3AuthError(
                            "InvalidRequest", "malformed object-lock headers"
                        )
                    from datetime import datetime as _dt

                    try:
                        _dt.fromisoformat(until.replace("Z", "+00:00"))
                    except ValueError:
                        raise S3AuthError(
                            "InvalidRequest", "bad retain-until date"
                        ) from None
                    ext[vtag.RETENTION_KEY] = json.dumps(
                        {"Mode": mode, "RetainUntilDate": until}
                    ).encode()
                if hold:
                    if hold not in ("ON", "OFF"):
                        raise S3AuthError(
                            "InvalidRequest", "bad legal hold status"
                        )
                    ext[vtag.LEGAL_HOLD_KEY] = hold.encode()
                return ext

            def _resolve_version(
                self, bucket: str, key: str, path: str, vid_param: str
            ):
                """Entry for GET/HEAD honoring ?versionId; responds with
                the right error itself and returns None on failure."""
                if not vid_param:
                    entry = srv.filer.find_entry(path)
                    if entry.is_directory:
                        self._error(404, "NoSuchKey", key)
                        return None
                    if is_delete_marker(entry):
                        self._respond_marker_error(404, "NoSuchKey", key, entry)
                        return None
                    return entry
                try:
                    cur = srv.filer.find_entry(path)
                    if not cur.is_directory and entry_vid(cur) == vid_param:
                        entry = cur
                    else:
                        raise NotFound(key)
                except NotFound:
                    try:
                        entry = srv.filer.find_entry(
                            f"{versions_dir(BUCKETS_ROOT, bucket, key)}/{vid_param}"
                        )
                    except NotFound:
                        self._error(404, "NoSuchVersion", vid_param)
                        return None
                if is_delete_marker(entry):
                    # AWS: GET on a delete-marker version is 405
                    self._respond_marker_error(
                        405, "MethodNotAllowed", key, entry
                    )
                    return None
                return entry

            def _respond_marker_error(self, code, s3code, key, entry):
                root = ET.Element("Error")
                _el(root, "Code", s3code)
                _el(root, "Message", "delete marker")
                _el(root, "Resource", key)
                self._respond(
                    code,
                    _xml(root),
                    extra={
                        "x-amz-delete-marker": "true",
                        "x-amz-version-id": entry_vid(entry),
                    },
                )

            def _delete_object(self, bucket: str, key: str, path: str, q: dict):
                state = srv.bucket_versioning(bucket)
                vid_param = q.get("versionId", "")
                bypass = (
                    self.headers.get(
                        "x-amz-bypass-governance-retention", ""
                    ).lower()
                    == "true"
                )
                if vid_param:
                    # permanent deletion of one version — lock-checked
                    try:
                        cur = srv.filer.find_entry(path)
                    except NotFound:
                        cur = None
                    try:
                        if (
                            cur is not None
                            and not cur.is_directory
                            and entry_vid(cur) == vid_param
                        ):
                            check_deletable(cur, bypass)
                            srv.filer.delete_entry(path, gc_chunks=True)
                            promote_latest(srv.filer, BUCKETS_ROOT, bucket, key)
                        else:
                            vpath = f"{versions_dir(BUCKETS_ROOT, bucket, key)}/{vid_param}"
                            ve = srv.filer.find_entry(vpath)
                            check_deletable(ve, bypass)
                            srv.filer.delete_entry(vpath, gc_chunks=True)
                    except LockViolation as e:
                        return self._error(403, "AccessDenied", str(e))
                    except NotFound:
                        pass  # deleting a missing version succeeds (AWS)
                    return self._respond(
                        204, extra={"x-amz-version-id": vid_param}
                    )
                if state:
                    # versioned simple DELETE: add a delete marker
                    vid = vtag.write_delete_marker(
                        srv.filer, BUCKETS_ROOT, bucket, key, state
                    )
                    return self._respond(
                        204,
                        extra={
                            "x-amz-delete-marker": "true",
                            "x-amz-version-id": vid,
                        },
                    )
                srv.filer.delete_entry(path, recursive=False, gc_chunks=True)
                return self._respond(204)

            def _object_retention(self, bucket, key, path, q: dict):
                target = self._resolve_version(
                    bucket, key, path, q.get("versionId", "")
                )
                if target is None:
                    return
                m = self.command
                if m == "GET":
                    mode, until = vtag.get_retention(target)
                    if not mode:
                        return self._error(
                            404,
                            "NoSuchObjectLockConfiguration",
                            key,
                        )
                    root = ET.Element("Retention", xmlns=XMLNS)
                    _el(root, "Mode", mode)
                    _el(root, "RetainUntilDate", until.isoformat())
                    return self._respond(200, _xml(root))
                if m == "PUT":
                    if srv.lock_conf(bucket) is None:
                        return self._error(
                            400,
                            "InvalidRequest",
                            "bucket has no object lock configuration",
                        )
                    doc = ET.fromstring(self._read_body())
                    ns = _xml_ns(doc)
                    mode = doc.findtext(f"{ns}Mode") or ""
                    until_s = doc.findtext(f"{ns}RetainUntilDate") or ""
                    if mode not in ("GOVERNANCE", "COMPLIANCE") or not until_s:
                        return self._error(400, "MalformedXML", "retention")
                    from datetime import datetime as _dt

                    new_until = _dt.fromisoformat(
                        until_s.replace("Z", "+00:00")
                    )
                    old_mode, old_until = vtag.get_retention(target)
                    bypass = (
                        self.headers.get(
                            "x-amz-bypass-governance-retention", ""
                        ).lower()
                        == "true"
                    )
                    # weakening an active lock needs bypass (GOVERNANCE)
                    # and is never allowed for COMPLIANCE
                    if old_mode and old_until and new_until < old_until:
                        if old_mode == "COMPLIANCE" or not bypass:
                            return self._error(
                                403,
                                "AccessDenied",
                                "cannot shorten active retention",
                            )
                    srv.filer.mutate_entry(
                        target.full_path,
                        lambda e: e.extended.__setitem__(
                            vtag.RETENTION_KEY,
                            json.dumps(
                                {
                                    "Mode": mode,
                                    "RetainUntilDate": new_until.isoformat(),
                                }
                            ).encode(),
                        ),
                    )
                    return self._respond(200)
                return self._error(405, "MethodNotAllowed", m)

            def _object_legal_hold(self, bucket, key, path, q: dict):
                target = self._resolve_version(
                    bucket, key, path, q.get("versionId", "")
                )
                if target is None:
                    return
                m = self.command
                if m == "GET":
                    root = ET.Element("LegalHold", xmlns=XMLNS)
                    _el(root, "Status", vtag.legal_hold(target))
                    return self._respond(200, _xml(root))
                if m == "PUT":
                    if srv.lock_conf(bucket) is None:
                        return self._error(
                            400,
                            "InvalidRequest",
                            "bucket has no object lock configuration",
                        )
                    doc = ET.fromstring(self._read_body())
                    ns = _xml_ns(doc)
                    status = doc.findtext(f"{ns}Status") or ""
                    if status not in ("ON", "OFF"):
                        return self._error(400, "MalformedXML", "legal hold")
                    srv.filer.mutate_entry(
                        target.full_path,
                        lambda e: e.extended.__setitem__(
                            vtag.LEGAL_HOLD_KEY, status.encode()
                        ),
                    )
                    return self._respond(200)
                return self._error(405, "MethodNotAllowed", m)

            def _object_tagging(self, bucket: str, key: str, path: str):
                """Get/Put/DeleteObjectTagging: tags ride the entry's
                extended attributes (reference s3api tagging handlers)."""
                entry = srv.filer.find_entry(path)
                if entry.is_directory:
                    return self._error(404, "NoSuchKey", key)
                m = self.command
                if m == "GET":
                    root = ET.Element("Tagging", xmlns=XMLNS)
                    tagset = _el(root, "TagSet")
                    raw = entry.extended.get("s3-tags", b"{}")
                    for k2, v2 in sorted(json.loads(raw).items()):
                        t = _el(tagset, "Tag")
                        _el(t, "Key", k2)
                        _el(t, "Value", v2)
                    return self._respond(200, _xml(root))
                if m == "PUT":
                    doc = ET.fromstring(self._read_body())
                    ns = _xml_ns(doc)
                    tags = {}
                    for t in doc.iter(f"{ns}Tag"):
                        k2 = t.findtext(f"{ns}Key") or ""
                        # AWS rejects bad tag sets rather than storing a subset
                        if not k2 or k2 in tags:
                            return self._error(
                                400, "InvalidTag", f"empty or duplicate key {k2!r}"
                            )
                        tags[k2] = t.findtext(f"{ns}Value") or ""
                    if len(tags) > 10:
                        return self._error(
                            400, "BadRequest", "object tag set exceeds 10 tags"
                        )
                    srv.filer.mutate_entry(
                        path,
                        lambda e: e.extended.__setitem__(
                            "s3-tags", json.dumps(tags, sort_keys=True).encode()
                        ),
                    )
                    return self._respond(200)
                if m == "DELETE":
                    srv.filer.mutate_entry(
                        path, lambda e: e.extended.pop("s3-tags", None)
                    )
                    return self._respond(204)
                return self._error(405, "MethodNotAllowed", m)

            def _copy_object(self, bucket: str, key: str, src: str):
                src = urllib.parse.unquote(src)
                src_vid = ""
                if "?versionId=" in src:
                    src, _, src_vid = src.partition("?versionId=")
                if not src.startswith("/"):
                    src = "/" + src
                src_path = normalize_path(f"{BUCKETS_ROOT}{src}")
                if src_vid:
                    sb, _, sk = src.lstrip("/").partition("/")
                    entry = self._resolve_version(sb, sk, src_path, src_vid)
                    if entry is None:
                        return
                else:
                    entry = srv.filer.find_entry(src_path)
                    if entry.is_directory or is_delete_marker(entry):
                        # a versioned key behind a delete marker reads
                        # as absent — copy must 404 like GET does
                        return self._error(404, "NoSuchKey", src)
                # x-amz-copy-source-if-* preconditions (AWS CopyObject):
                # same RFC 9110 matching as GET, evaluated against the
                # SOURCE entry before any bytes move
                src_etag = _entry_etag(entry)
                cim = self.headers.get("x-amz-copy-source-if-match", "")
                cinm = self.headers.get(
                    "x-amz-copy-source-if-none-match", ""
                )
                cims = _http_date(
                    self.headers.get(
                        "x-amz-copy-source-if-modified-since", ""
                    )
                )
                cius = _http_date(
                    self.headers.get(
                        "x-amz-copy-source-if-unmodified-since", ""
                    )
                )
                # RFC 9110 precedence, same as the GET path: an ETag
                # condition overrides its date counterpart
                if (
                    (cim and not _etag_cond_match(cim, src_etag))
                    or (
                        not cim
                        and cius is not None
                        and entry.attr.mtime > cius
                    )
                    or (cinm and _etag_cond_match(cinm, src_etag))
                    or (
                        not cinm
                        and cims is not None
                        and entry.attr.mtime <= cims
                    )
                ):
                    return self._error(
                        412,
                        "PreconditionFailed",
                        "copy source precondition failed",
                    )
                data = srv.filer.read_entry(entry)
                # decrypt the source (SSE-C via the x-amz-copy-source-*
                # key headers; SSE-S3 via the keyring), then apply the
                # destination's own encryption
                src_key = sse.decrypt_key_for_entry(
                    entry,
                    sse.parse_customer_headers(
                        self.headers, prefix=sse.COPY_CUSTOMER_PREFIX
                    ),
                    srv.sse_keyring,
                )
                if src_key is not None:
                    data = sse.read_decrypted(
                        lambda o, n: data[o:] if n < 0 else data[o : o + n],
                        entry,
                        src_key,
                        0,
                        -1,
                    )
                dst_ssec, dst_algo = sse.resolve_put_encryption(
                    self.headers, srv.bucket_default_encryption(bucket)
                )
                data, sse_ext, sse_hdrs = sse.encrypt_for_put(
                    data, dst_ssec, dst_algo, srv.sse_keyring
                )
                copy_ext = dict(sse_ext)
                acl = self._canned_acl_header()
                if acl:
                    copy_ext["s3-acl"] = acl.encode()
                dst, vid = srv.put_object(
                    bucket,
                    key,
                    data,
                    mime=entry.attr.mime,
                    extra_extended=copy_ext,
                )
                root = ET.Element("CopyObjectResult", xmlns=XMLNS)
                _el(root, "ETag", f'"{dst.attr.md5.hex()}"')
                _el(root, "LastModified", _iso(dst.attr.mtime))
                extra = {**sse_hdrs}
                if vid:
                    extra["x-amz-version-id"] = vid
                self._respond(200, _xml(root), extra=extra)

            # ---- multipart ----

            def _initiate_multipart(self, bucket: str, key: str):
                if srv.quota_exceeded(bucket):
                    return self._error(
                        403,
                        "QuotaExceeded",
                        f"bucket {bucket} is over its storage quota",
                    )
                # SSE context for the whole upload (reference
                # SerializeSSECMetadata-per-chunk model): parts become
                # independent CTR streams under one data key; the
                # part map lands on the completed object.
                sse_meta: dict = {}
                ssec_key, sse_algo = sse.resolve_put_encryption(
                    self.headers, srv.bucket_default_encryption(bucket)
                )
                if ssec_key is not None:
                    # the key itself is NEVER stored; every UploadPart
                    # must present it again (AWS SSE-C semantics)
                    sse_meta = {
                        "algo": "SSE-C",
                        "key_md5": sse.key_md5_b64(ssec_key),
                    }
                elif sse_algo:
                    if srv.sse_keyring is None:
                        return self._error(
                            501, "NotImplemented", "SSE keyring unavailable"
                        )
                    key_id, _dk, wrapped = srv.sse_keyring.generate_data_key()
                    sse_meta = {
                        "algo": "AES256",
                        "key_id": key_id,
                        "wrapped": wrapped.hex(),
                    }
                upload_id = uuid.uuid4().hex
                meta_path = srv._upload_dir(bucket, upload_id)
                e = new_entry(meta_path, is_directory=True, mode=0o755)
                srv.filer.create_entry(e)
                # x-amz-object-lock-* headers arrive on the initiate
                # request; they must stick to the completed object
                lock_ext = {
                    k2: v2.decode()
                    for k2, v2 in self._lock_headers_extended(bucket).items()
                }
                srv.filer.store.kv_put(
                    f"upload/{upload_id}".encode(),
                    json.dumps(
                        {
                            "bucket": bucket,
                            "key": key,
                            "mime": self.headers.get("Content-Type", ""),
                            "lock_ext": lock_ext,
                            "sse": sse_meta,
                        }
                    ).encode(),
                )
                root = ET.Element("InitiateMultipartUploadResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "UploadId", upload_id)
                self._respond(200, _xml(root))

            def _upload_part(self, bucket: str, key: str, q: dict):
                if srv.quota_exceeded(bucket):
                    # parts consume storage immediately — an over-quota
                    # bucket must not grow unbounded via multipart
                    return self._error(
                        403,
                        "QuotaExceeded",
                        f"bucket {bucket} is over its storage quota",
                    )
                upload_id = q["uploadId"]
                part = int(q["partNumber"])
                meta_raw = srv.filer.store.kv_get(f"upload/{upload_id}".encode())
                if meta_raw is None:
                    return self._error(404, "NoSuchUpload", upload_id)
                data = self._read_body()
                part_ext: dict = {}
                sse_meta = (json.loads(meta_raw) or {}).get("sse") or {}
                if sse_meta:
                    dk = self._upload_data_key(sse_meta)
                    if isinstance(dk, bytes):
                        iv, data = sse.encrypt(dk, data)
                        part_ext["s3-sse-part-iv"] = iv
                    else:
                        return dk  # an error response was sent
                entry = srv.filer.write_file(
                    f"{srv._upload_dir(bucket, upload_id)}/{part:05d}.part",
                    data,
                    collection=bucket,
                    inline=False,  # completion splices chunk lists
                    extended=part_ext,
                )
                self._respond(200, extra={"ETag": f'"{entry.attr.md5.hex()}"'})

            def _upload_data_key(self, sse_meta: dict):
                """Resolve the upload's data key: SSE-C re-presents the
                customer key on every part request (MD5-bound to the
                initiate); SSE-S3 unwraps the stored envelope key.
                Returns bytes, or None after sending an error."""
                if sse_meta.get("algo") == "SSE-C":
                    ck = sse.parse_customer_headers(self.headers)
                    if ck is None:
                        self._error(
                            400,
                            "InvalidRequest",
                            "upload uses SSE-C; part requests need the key",
                        )
                        return None
                    if sse.key_md5_b64(ck) != sse_meta.get("key_md5"):
                        self._error(
                            403, "AccessDenied", "SSE-C key does not match upload"
                        )
                        return None
                    return ck
                return srv.sse_keyring.decrypt_data_key(
                    sse_meta.get("key_id", ""),
                    bytes.fromhex(sse_meta.get("wrapped", "")),
                )

            def _complete_multipart(self, bucket: str, key: str, q: dict):
                if srv.quota_exceeded(bucket):
                    return self._error(
                        403,
                        "QuotaExceeded",
                        f"bucket {bucket} is over its storage quota",
                    )
                upload_id = q["uploadId"]
                meta_raw = srv.filer.store.kv_get(f"upload/{upload_id}".encode())
                if meta_raw is None:
                    return self._error(404, "NoSuchUpload", upload_id)
                meta = json.loads(meta_raw)
                updir = srv._upload_dir(bucket, upload_id)
                parts = sorted(
                    (
                        e
                        for e in srv.filer.list_entries(updir, limit=10_000)
                        if e.name.endswith(".part")
                    ),
                    key=lambda e: e.name,
                )
                # honor the client's part list when provided
                body = self._read_body()
                if body.strip():
                    doc = ET.fromstring(body)
                    ns = _xml_ns(doc)
                    wanted = {
                        int(p.findtext(f"{ns}PartNumber") or "0")
                        for p in doc.findall(f"{ns}Part")
                    }
                    if wanted:
                        chosen = [
                            e for e in parts if int(e.name.split(".")[0]) in wanted
                        ]
                        if len(chosen) != len(wanted):
                            return self._error(
                                400, "InvalidPart", "listed part missing"
                            )
                        parts = chosen
                # splice chunk lists: no data copy (filer_multipart.go)
                chunks, offset, md5s = [], 0, []
                for p in parts:
                    if p.content and not p.chunks:
                        # a part stored inline (e.g. pre-inline=False
                        # uploads) must become a chunk or its bytes
                        # would vanish from the spliced object
                        fid = srv.filer.ops.upload(
                            p.content, collection=bucket
                        )
                        c0 = fpb.FileChunk(
                            fid=fid,
                            offset=0,
                            size=len(p.content),
                            modified_ts_ns=time.time_ns(),
                        )
                        p.chunks.append(c0)
                    for c in p.chunks:
                        nc = fpb.FileChunk()
                        nc.CopyFrom(c)
                        nc.offset = offset + c.offset
                        chunks.append(nc)
                    offset += p.file_size
                    md5s.append(p.attr.md5)
                final_path = normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}")
                final = new_entry(final_path, mime=meta.get("mime", ""))
                final.chunks = chunks
                final.attr.file_size = offset
                etag = hashlib.md5(b"".join(md5s)).hexdigest() + f"-{len(parts)}"
                final.extended["s3-etag"] = etag.encode()
                sse_meta = meta.get("sse") or {}
                if sse_meta:
                    # assemble the per-part CTR map (length + IV per
                    # part, in splice order); key material mirrors the
                    # single-PUT layout so the read path is uniform
                    part_map = []
                    for p in parts:
                        iv = p.extended.get("s3-sse-part-iv")
                        if not iv:
                            return self._error(
                                400,
                                "InvalidPart",
                                f"part {p.name} missing SSE metadata",
                            )
                        part_map.append([p.file_size, iv.hex()])
                    final.extended[sse.SSE_PART_MAP_KEY] = json.dumps(
                        part_map
                    ).encode()
                    if sse_meta["algo"] == "SSE-C":
                        final.extended[sse.SSE_ALGO_KEY] = b"SSE-C"
                        final.extended[sse.SSE_KEY_MD5_KEY] = sse_meta[
                            "key_md5"
                        ].encode()
                    else:
                        final.extended[sse.SSE_ALGO_KEY] = b"AES256"
                        final.extended[sse.SSE_KEY_ID_KEY] = sse_meta[
                            "key_id"
                        ].encode()
                        final.extended[sse.SSE_WRAPPED_KEY] = bytes.fromhex(
                            sse_meta["wrapped"]
                        )
                # bucket default retention applies to multipart objects
                # too — large SDK uploads must not escape WORM
                for k2, v2 in vtag.default_retention_extended(
                    srv.lock_conf(bucket)
                ).items():
                    final.extended[k2] = v2
                for k2, v2 in (meta.get("lock_ext") or {}).items():
                    final.extended[k2] = v2.encode()
                # versioning-aware finalize (mirrors srv.put_object);
                # the key's write stripe makes it atomic vs CAS PUTs
                # and deletes on the same key
                final_lock = srv.put_lock(final_path)
                final_lock.acquire()
                state = srv.bucket_versioning(bucket)
                vid = ""
                old = None
                if state == "Enabled":
                    vid = new_version_id()
                    final.extended[vtag.VID_KEY] = vid.encode()
                    archive_current(srv.filer, BUCKETS_ROOT, bucket, key)
                elif state == "Suspended":
                    vid = vtag.NULL_VID
                    try:
                        cur = srv.filer.find_entry(final_path)
                        if not cur.is_directory and entry_vid(cur) != vtag.NULL_VID:
                            archive_current(srv.filer, BUCKETS_ROOT, bucket, key)
                        elif not cur.is_directory:
                            old = cur
                    except NotFound:
                        pass
                else:
                    # an overwritten object's chunks must be GC'd
                    # (write_file does this for the simple-PUT path)
                    try:
                        old = srv.filer.find_entry(final_path)
                    except NotFound:
                        old = None
                try:
                    srv.filer.create_entry(final)
                finally:
                    final_lock.release()
                if old is not None and not old.is_directory:
                    srv.filer.gc_chunks(old.chunks)
                # drop part entries WITHOUT GC'ing chunks (now referenced
                # by the final entry)
                for p in parts:
                    srv.filer.delete_entry(p.full_path, gc_chunks=False)
                srv.filer.delete_entry(updir, recursive=True, gc_chunks=False)
                srv.filer.store.kv_delete(f"upload/{upload_id}".encode())
                root = ET.Element("CompleteMultipartUploadResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "ETag", f'"{etag}"')
                self._respond(
                    200,
                    _xml(root),
                    extra={"x-amz-version-id": vid} if vid else None,
                )

            def _abort_multipart(self, bucket: str, key: str, q: dict):
                upload_id = q["uploadId"]
                srv.filer.delete_entry(
                    srv._upload_dir(bucket, upload_id), recursive=True
                )
                srv.filer.store.kv_delete(f"upload/{upload_id}".encode())
                self._respond(204)

            def _list_parts(self, bucket: str, key: str, q: dict):
                upload_id = q["uploadId"]
                updir = srv._upload_dir(bucket, upload_id)
                if srv.filer.store.kv_get(
                    f"upload/{upload_id}".encode()
                ) is None or not srv.filer.exists(updir):
                    return self._error(404, "NoSuchUpload", upload_id)
                root = ET.Element("ListPartsResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                _el(root, "Key", key)
                _el(root, "UploadId", upload_id)
                try:
                    for e in srv.filer.list_entries(updir, limit=10_000):
                        if not e.name.endswith(".part"):
                            continue
                        p = _el(root, "Part")
                        _el(p, "PartNumber", int(e.name.split(".")[0]))
                        _el(p, "ETag", f'"{e.attr.md5.hex()}"')
                        _el(p, "Size", e.file_size)
                except NotFound:
                    return self._error(404, "NoSuchUpload", upload_id)
                self._respond(200, _xml(root))

            def _list_uploads(self, bucket: str):
                root = ET.Element("ListMultipartUploadsResult", xmlns=XMLNS)
                _el(root, "Bucket", bucket)
                updir = f"{BUCKETS_ROOT}/{UPLOADS_DIR}/{bucket}"
                try:
                    for e in srv.filer.list_entries(updir, limit=10_000):
                        meta_raw = srv.filer.store.kv_get(
                            f"upload/{e.name}".encode()
                        )
                        if meta_raw is None:
                            continue
                        meta = json.loads(meta_raw)
                        u = _el(root, "Upload")
                        _el(u, "Key", meta["key"])
                        _el(u, "UploadId", e.name)
                except NotFound:
                    pass
                self._respond(200, _xml(root))

        return Handler

    # -------------------------------------------------------- versioning

    def quota_exceeded(self, bucket: str) -> bool:
        """Set by the s3.bucket.quota.enforce sweep (reference
        command_s3_bucketquota.go): over-quota buckets reject writes
        until usage drops below the quota and a sweep clears the flag."""
        v = self.filer.store.kv_get(f"quota-exceeded/{bucket}".encode())
        return bool(v)

    def bucket_policy(self, bucket: str) -> dict | None:
        raw = self.filer.store.kv_get(f"policy/{bucket}".encode())
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return None

    def bucket_acl(self, bucket: str) -> str:
        raw = self.filer.store.kv_get(f"acl/{bucket}".encode())
        return raw.decode() if raw else "private"

    def bucket_default_encryption(self, bucket: str) -> str:
        """'' | 'AES256': bucket default applied to unencrypted PUTs."""
        raw = self.filer.store.kv_get(f"encryption/{bucket}".encode())
        return raw.decode() if raw else ""

    # Canned ACLs grant DATA-PLANE actions only: never control-plane
    # operations (policy/acl/encryption/lifecycle/bucket delete), which
    # would let an anonymous caller escalate on a public-read-write
    # bucket.
    _ACL_READ_ACTIONS = frozenset(
        {"s3:GetObject", "s3:GetObjectVersion", "s3:ListBucket"}
    )
    _ACL_WRITE_ACTIONS = frozenset({"s3:PutObject", "s3:DeleteObject"})

    def put_lock(self, path: str) -> threading.Lock:
        return self._put_locks[zlib.crc32(path.encode()) % len(self._put_locks)]

    def acl_allows_anonymous(self, bucket: str, key: str, action: str) -> bool:
        """Canned-ACL grant check for unauthenticated requests:
        public-read(-write) on the bucket, or public-read on the object
        itself (object ACL stored in entry.extended at PUT)."""
        acl = self.bucket_acl(bucket)
        if action in self._ACL_READ_ACTIONS:
            if acl in ("public-read", "public-read-write"):
                return True
            if key:
                try:
                    entry = self.filer.find_entry(
                        normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}")
                    )
                except NotFound:
                    return False
                oacl = (entry.extended.get("s3-acl") or b"").decode()
                return oacl in ("public-read", "public-read-write")
            return False
        if action in self._ACL_WRITE_ACTIONS:
            return acl == "public-read-write"
        return False

    def bucket_versioning(self, bucket: str) -> str:
        """"" (never enabled) | "Enabled" | "Suspended"."""
        raw = self.filer.store.kv_get(f"versioning/{bucket}".encode())
        return raw.decode() if raw else ""

    def lock_conf(self, bucket: str) -> dict | None:
        raw = self.filer.store.kv_get(f"object-lock/{bucket}".encode())
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def put_object(
        self,
        bucket: str,
        key: str,
        data: bytes,
        mime: str = "",
        extra_extended: dict | None = None,
    ):
        """Versioning-aware object write (reference
        s3api_object_versioning.go putVersionedObject). Returns
        (entry, version_id-or-None)."""
        path = normalize_path(f"{BUCKETS_ROOT}/{bucket}/{key}")
        with self.put_lock(path):
            return self._put_object_locked(
                bucket, key, path, data, mime, extra_extended
            )

    def _put_object_locked(
        self,
        bucket: str,
        key: str,
        path: str,
        data: bytes,
        mime: str,
        extra_extended: dict | None,
    ):
        state = self.bucket_versioning(bucket)
        ext = dict(extra_extended or {})
        ext.update(vtag.default_retention_extended(self.lock_conf(bucket)))
        if state == "Enabled":
            vid = new_version_id()
            ext[vtag.VID_KEY] = vid.encode()
            archive_current(self.filer, BUCKETS_ROOT, bucket, key)
            entry = self.filer.write_file(
                path, data, mime=mime, collection=bucket, extended=ext
            )
            return entry, vid
        if state == "Suspended":
            # the new object becomes the "null" version; an existing
            # non-null current version is retained, a null one replaced
            try:
                cur = self.filer.find_entry(path)
                if not cur.is_directory and entry_vid(cur) != vtag.NULL_VID:
                    archive_current(self.filer, BUCKETS_ROOT, bucket, key)
            except NotFound:
                pass
            entry = self.filer.write_file(
                path, data, mime=mime, collection=bucket, extended=ext
            )
            return entry, vtag.NULL_VID
        entry = self.filer.write_file(
            path, data, mime=mime, collection=bucket, extended=ext or None
        )
        return entry, None

    # -------------------------------------------------------------- walk

    def _upload_dir(self, bucket: str, upload_id: str) -> str:
        return f"{BUCKETS_ROOT}/{UPLOADS_DIR}/{bucket}/{upload_id}"

    def _walk_keys(
        self,
        bucket: str,
        prefix: str,
        delimiter: str,
        after: str,
        max_keys: int,
        include_markers: bool = False,
    ):
        """Flat key listing with prefix/delimiter grouping.

        DFS over the filer tree in sorted order (the namespace IS the
        key space, reference s3api list semantics over the filer)."""
        bpath = f"{BUCKETS_ROOT}/{bucket}"
        contents: list = []
        common: set[str] = set()
        truncated = False
        last_emitted = ""

        def cap_reached() -> bool:
            nonlocal truncated
            if len(contents) + len(common) >= max_keys:
                truncated = True
                return True
            return False

        def dfs(dir_path: str, key_prefix: str) -> bool:
            nonlocal last_emitted
            for e in self.filer.list_entries(dir_path, limit=100_000):
                key = key_prefix + e.name
                if dir_path == bpath and e.name == vtag.VERSIONS_DIR:
                    continue  # hidden noncurrent-version tree
                if not include_markers and is_delete_marker(e):
                    continue
                if e.is_directory:
                    sub = key + "/"
                    # prune subtrees that cannot contain matching keys
                    if prefix and not (
                        sub.startswith(prefix) or prefix.startswith(sub)
                    ):
                        continue
                    if delimiter == "/" and sub.startswith(prefix) and sub != prefix:
                        cut = prefix + sub[len(prefix) :].split("/")[0] + "/"
                        if after.startswith(cut):
                            continue  # group already emitted on a prior page
                        if cut <= after:
                            continue
                        if cut in common:
                            continue
                        if cap_reached():
                            return False
                        common.add(cut)
                        last_emitted = cut
                        continue
                    if not dfs(e.full_path, sub):
                        return False
                else:
                    if prefix and not key.startswith(prefix):
                        continue
                    if after and key <= after:
                        continue
                    if cap_reached():
                        return False
                    contents.append((key, e))
                    last_emitted = key
            return True

        try:
            dfs(bpath, "")
        except NotFound:
            pass
        return contents, common, truncated, last_emitted


def _required_action(method: str, bucket: str, key: str) -> str:
    """Map a request to the coarse action model (reference
    auth_credentials.go identity actions: Admin/Read/Write/List)."""
    if key == "":
        if method in ("GET", "HEAD"):
            return "List"
        if method == "POST":  # batch delete
            return "Write"
        return "Admin"  # bucket create/delete
    return "Read" if method in ("GET", "HEAD") else "Write"


def _http_date(header: str):
    """RFC 7231 date -> epoch seconds, or None for malformed input
    (RFC 9110: an unparseable validator date IGNORES the condition)."""
    try:
        import email.utils as _eu

        return _eu.parsedate_to_datetime(header).timestamp()
    except (TypeError, ValueError):
        return None


def _etag_cond_match(header: str, etag: str) -> bool:
    """RFC 9110 If-(None-)Match list semantics: '*' matches any
    existing representation; otherwise EXACT entity-tag comparison per
    comma-separated member (substring matching would confuse
    'deadbeef-2' with 'deadbeef-25')."""
    header = header.strip()
    if header == "*":
        return True
    for member in header.split(","):
        tag = member.strip()
        if tag.startswith("W/"):
            tag = tag[2:]
        if tag.strip('"') == etag:
            return True
    return False


def _entry_etag(entry) -> str:
    s3etag = entry.extended.get("s3-etag")
    if s3etag:
        return s3etag.decode()
    return entry.attr.md5.hex() if entry.attr.md5 else ""


