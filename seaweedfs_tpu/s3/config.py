"""S3 gateway identity/role configuration.

Reference: weed/s3api auth_credentials.go loading identities.json (the
`-s3.config` flag) — names, key pairs, coarse actions, and IAM policy
documents; plus STS roles.

    {"identities": [
        {"name": "admin", "accessKey": "AK", "secretKey": "SK",
         "actions": ["Admin"]},
        {"name": "ro", "accessKey": "AK2", "secretKey": "SK2",
         "policies": [{"Version": "2012-10-17", "Statement": [...]}]}],
     "roles": [
        {"name": "uploader", "trusted": ["AK"],
         "policies": [{"Statement": [...]}]}]}
"""

from __future__ import annotations

import json

from ..iam.sts import Role, StsService
from .auth import Identity, IdentityStore


def load_s3_config(path: str) -> tuple[IdentityStore, StsService | None]:
    with open(path) as f:
        conf = json.load(f)
    store = IdentityStore()
    for ident in conf.get("identities", []):
        store.add(
            Identity(
                name=ident.get("name", ident["accessKey"]),
                access_key=ident["accessKey"],
                secret_key=ident["secretKey"],
                actions=tuple(ident.get("actions", ())) or (),
                policies=tuple(ident.get("policies", ())),
            )
        )
    sts = None
    roles = conf.get("roles", [])
    if roles and store.empty:
        # roles without identities would leave the gateway in open mode
        # (anonymous = admin) with STS credentials never verified —
        # refuse the misconfiguration instead of silently ignoring it
        raise ValueError(
            f"{path}: 'roles' configured but no 'identities'; "
            "an empty identity store runs the gateway in open mode"
        )
    if roles:
        sts = StsService()
        for r in roles:
            sts.put_role(
                Role(
                    name=r["name"],
                    policies=list(r.get("policies", [])),
                    trusted=list(r.get("trusted", ["*"])),
                )
            )
        store.sts = sts
    return store, sts
