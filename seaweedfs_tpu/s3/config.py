"""S3 gateway identity/role configuration.

Reference: weed/s3api auth_credentials.go loading identities.json (the
`-s3.config` flag) — names, key pairs, coarse actions, and IAM policy
documents; plus STS roles.

    {"identities": [
        {"name": "admin", "accessKey": "AK", "secretKey": "SK",
         "actions": ["Admin"]},
        {"name": "ro", "accessKey": "AK2", "secretKey": "SK2",
         "policies": [{"Version": "2012-10-17", "Statement": [...]}]}],
     "roles": [
        {"name": "uploader", "trusted": ["AK"],
         "policies": [{"Statement": [...]}]}]}
"""

from __future__ import annotations

import json
import threading
import time

from ..iam.sts import Role, StsService
from .auth import Identity, IdentityStore

# Filer KV key holding the dynamic identity config (written by the
# shell's s3.* command family, read by every gateway over the filer —
# the reference keeps the same file at /etc/iam/identity.json).
S3_IDENTITY_KV = b"s3/identity.json"


def mint_key_pair() -> tuple[str, str]:
    """One credential format for every minting surface (shell
    s3.accesskey.create AND the embedded IAM API)."""
    import secrets

    return "SW" + secrets.token_hex(9).upper(), secrets.token_urlsafe(30)


def identity_from_conf(ident: dict) -> Identity:
    return Identity(
        name=ident.get("name", ident["accessKey"]),
        access_key=ident["accessKey"],
        secret_key=ident["secretKey"],
        actions=tuple(ident.get("actions", ())) or (),
        policies=tuple(ident.get("policies", ())),
    )


class FilerIdentityStore:
    """IdentityStore facade layering dynamic, filer-persisted
    credentials (s3/identity.json in the filer KV, maintained by the
    shell `s3.*` commands) over an optional static base store (CLI
    flags / config file). The KV is re-read at most every `ttl`
    seconds, so a key created in the shell authenticates against every
    gateway within seconds — and creating the FIRST identity flips an
    open-mode gateway to authenticated mode."""

    def __init__(self, filer, base: IdentityStore | None = None, ttl: float = 2.0):
        self.base = base or IdentityStore()
        self._filer = filer
        self._ttl = ttl
        self._next = 0.0
        self._blob: bytes | None = None
        self._dynamic: dict[str, Identity] = {}
        self._lock = threading.Lock()

    # --- IdentityStore surface ---

    @property
    def sts(self):
        return self.base.sts

    @sts.setter
    def sts(self, value):
        self.base.sts = value

    def add(self, ident: Identity) -> None:
        self.base.add(ident)

    def lookup(self, access_key: str) -> Identity | None:
        found = self.base.lookup(access_key)
        if found is not None:
            return found
        self._refresh()
        return self._dynamic.get(access_key)

    @property
    def empty(self) -> bool:
        if not self.base.empty or self._dynamic:
            return False
        self._refresh()
        return not self._dynamic

    # --- dynamic reload ---

    def _refresh(self) -> None:
        now = time.monotonic()
        with self._lock:
            if now < self._next:
                return
            self._next = now + self._ttl
            try:
                raw = self._filer.store.kv_get(S3_IDENTITY_KV)
            except Exception:  # noqa: BLE001 — keep serving the last view
                return
            if raw == self._blob:
                return
            self._blob = raw
            dyn: dict[str, Identity] = {}
            if raw:
                try:
                    conf = json.loads(raw)
                except json.JSONDecodeError:
                    return  # malformed config: keep the previous view
                for ident in conf.get("identities", []):
                    try:
                        i = identity_from_conf(ident)
                    except KeyError:
                        continue
                    if not i.access_key:
                        # keyless placeholder (IAM CreateUser before
                        # CreateAccessKey): a user, not a credential
                        continue
                    dyn[i.access_key] = i
            self._dynamic = dyn


def load_s3_config(path: str):
    """-> (IdentityStore, StsService | None, OidcProvider | None,
    LdapProvider | None)."""
    with open(path) as f:
        conf = json.load(f)
    store = IdentityStore()
    for ident in conf.get("identities", []):
        store.add(identity_from_conf(ident))
    oidc = None
    if conf.get("oidc"):
        from ..iam.oidc import OidcProvider

        oidc = OidcProvider(**conf["oidc"])
    ldap = None
    if conf.get("ldap"):
        from ..iam.ldap import LdapProvider

        ldap = LdapProvider(**conf["ldap"])
    sts = None
    roles = conf.get("roles", [])
    if roles and store.empty and oidc is None:
        # roles without identities would leave the gateway in open mode
        # (anonymous = admin) with STS credentials never verified —
        # refuse the misconfiguration instead of silently ignoring it
        raise ValueError(
            f"{path}: 'roles' configured but no 'identities'; "
            "an empty identity store runs the gateway in open mode"
        )
    if roles:
        sts = StsService()
        for r in roles:
            sts.put_role(
                Role(
                    name=r["name"],
                    policies=list(r.get("policies", [])),
                    trusted=list(r.get("trusted", ["*"])),
                )
            )
        store.sts = sts
    if ldap is not None and sts is None:
        raise ValueError(
            f"{path}: 'ldap' requires 'roles' (LDAP identities assume a "
            "role for their credentials)"
        )
    return store, sts, oidc, ldap
