"""S3 object versioning + object-lock helpers.

Reference: weed/s3api/s3api_object_versioning.go (version directory per
object, latest materialized), s3api_object_retention.go (retention /
legal hold / governance bypass).

Layout (redesigned for this filer): the latest version of a key lives
at its normal path ``/buckets/<b>/<key>`` with the version id in
extended["s3-version-id"]; noncurrent versions are renamed (metadata
move, chunks by reference) into the hidden per-bucket tree
``/buckets/<b>/.versions/<key>/<version-id>``. Delete markers are
zero-length entries with extended["s3-delete-marker"]=b"1". Version ids
are inverse-timestamp hex, so ascending name order = newest first.
"""

from __future__ import annotations

import json
import os
import time
from datetime import datetime, timezone

from ..filer.entry import normalize_path
from ..filer.filer_store import NotFound

VERSIONS_DIR = ".versions"
NULL_VID = "null"

VID_KEY = "s3-version-id"
MARKER_KEY = "s3-delete-marker"
RETENTION_KEY = "s3-retention"
LEGAL_HOLD_KEY = "s3-legal-hold"


def new_version_id() -> str:
    """Inverse-timestamp so lexicographic ascending = newest first."""
    return f"{(1 << 63) - time.time_ns():016x}{os.urandom(4).hex()}"


def entry_vid(entry) -> str:
    raw = entry.extended.get(VID_KEY)
    return raw.decode() if raw else NULL_VID


def is_delete_marker(entry) -> bool:
    return entry.extended.get(MARKER_KEY) == b"1"


def versions_dir(buckets_root: str, bucket: str, key: str) -> str:
    return normalize_path(f"{buckets_root}/{bucket}/{VERSIONS_DIR}/{key}")


class LockViolation(Exception):
    """Deleting/overwriting a version protected by retention or hold."""


def get_retention(entry) -> tuple[str, datetime | None]:
    raw = entry.extended.get(RETENTION_KEY)
    if not raw:
        return "", None
    try:
        d = json.loads(raw)
        until = datetime.fromisoformat(d["RetainUntilDate"])
        if until.tzinfo is None:
            until = until.replace(tzinfo=timezone.utc)
        return d.get("Mode", ""), until
    except (ValueError, KeyError):
        return "", None


def set_retention(entry, mode: str, until: datetime) -> None:
    entry.extended[RETENTION_KEY] = json.dumps(
        {"Mode": mode, "RetainUntilDate": until.isoformat()}
    ).encode()


def legal_hold(entry) -> str:
    raw = entry.extended.get(LEGAL_HOLD_KEY)
    return raw.decode() if raw else "OFF"


def check_deletable(entry, bypass_governance: bool = False) -> None:
    """Raise LockViolation if the version is protected (reference
    s3api_object_retention.go enforcement)."""
    if legal_hold(entry) == "ON":
        raise LockViolation("object version is under legal hold")
    mode, until = get_retention(entry)
    if mode and until and until > datetime.now(timezone.utc):
        if mode == "COMPLIANCE" or not bypass_governance:
            raise LockViolation(
                f"object version is locked ({mode}) until {until.isoformat()}"
            )


def default_retention_extended(lock_conf: dict | None) -> dict:
    """Extended attrs implementing the bucket's DefaultRetention on a
    freshly written version."""
    if not lock_conf:
        return {}
    dr = lock_conf.get("DefaultRetention")
    if not dr:
        return {}
    days = int(dr.get("Days", 0)) + 365 * int(dr.get("Years", 0))
    if days <= 0:
        return {}
    until = datetime.fromtimestamp(
        time.time() + days * 86400, tz=timezone.utc
    )
    return {
        RETENTION_KEY: json.dumps(
            {"Mode": dr.get("Mode", "GOVERNANCE"), "RetainUntilDate": until.isoformat()}
        ).encode()
    }


def archive_current(filer, buckets_root: str, bucket: str, key: str) -> None:
    """Move the current version (if any) into the versions tree under
    its version id. Metadata-only: chunks move by reference."""
    path = normalize_path(f"{buckets_root}/{bucket}/{key}")
    try:
        cur = filer.find_entry(path)
    except NotFound:
        return
    if cur.is_directory:
        return
    vid = entry_vid(cur)
    dst = f"{versions_dir(buckets_root, bucket, key)}/{vid}"
    if filer.exists(dst):
        # re-archiving the null version overwrites the previous null
        filer.delete_entry(dst, gc_chunks=True)
    filer.rename(path, dst)


def iter_versions(filer, buckets_root: str, bucket: str, key: str):
    """Noncurrent versions of one key, newest first."""
    vdir = versions_dir(buckets_root, bucket, key)
    try:
        entries = list(filer.list_entries(vdir, limit=100_000))
    except NotFound:
        return
    for e in sorted(entries, key=lambda e: e.name):
        if not e.is_directory:
            yield e


def write_delete_marker(
    filer, buckets_root: str, bucket: str, key: str, state: str
) -> str:
    """Archive the current version and leave a delete marker at the
    normal path. Suspended buckets get the null version id (AWS
    semantics); Enabled buckets a fresh one. Returns the marker vid."""
    from ..filer.entry import new_entry

    archive_current(filer, buckets_root, bucket, key)
    vid = new_version_id() if state == "Enabled" else NULL_VID
    path = normalize_path(f"{buckets_root}/{bucket}/{key}")
    marker = new_entry(path)
    marker.extended[MARKER_KEY] = b"1"
    marker.extended[VID_KEY] = vid.encode()
    filer.create_entry(marker)
    return vid


def promote_latest(filer, buckets_root: str, bucket: str, key: str) -> bool:
    """After the current version is removed, materialize the newest
    remaining version back at the normal path. Returns True if one was
    promoted."""
    for e in iter_versions(filer, buckets_root, bucket, key):
        path = normalize_path(f"{buckets_root}/{bucket}/{key}")
        filer.rename(e.full_path, path)
        return True
    return False
