"""Embedded IAM API: AWS IAM query protocol on the S3 service endpoint.

Reference: weed/iamapi (CreateUser/ListUsers/DeleteUser,
Create/Delete/ListAccessKeys, Put/Get/DeleteUserPolicy over the
2010-05-08 query protocol). Backed by the SAME filer-persisted identity
config the shell's s3.* commands maintain (s3/identity.json in the
filer KV), so keys minted here authenticate on every gateway within
the identity store's reload TTL.

Model mapping: one config entry per (user, accessKey); a user created
before any key is a keyless placeholder entry the credential loader
skips. PutUserPolicy attaches the document to every entry of the user
(replacing coarse actions, exactly like the shell's s3.policy.put).
"""

from __future__ import annotations

import json
import threading
import uuid
import xml.etree.ElementTree as ET

from .config import S3_IDENTITY_KV, mint_key_pair

# ThreadingHTTPServer serves IAM calls concurrently; every action is a
# whole-document read-modify-write of the identity KV, so a lost update
# would hand a caller a 200 + credentials that were never persisted
_MUTATE_LOCK = threading.Lock()

IAM_XMLNS = "https://iam.amazonaws.com/doc/2010-05-08/"

ACTIONS = {
    "CreateUser",
    "DeleteUser",
    "ListUsers",
    "CreateAccessKey",
    "DeleteAccessKey",
    "ListAccessKeys",
    "PutUserPolicy",
    "GetUserPolicy",
    "DeleteUserPolicy",
}


class IamError(Exception):
    def __init__(self, code: int, typ: str, message: str):
        super().__init__(message)
        self.code = code
        self.typ = typ


def _load(store) -> dict:
    raw = store.kv_get(S3_IDENTITY_KV)
    if not raw:
        return {"identities": []}
    try:
        return json.loads(raw)
    except ValueError:
        return {"identities": []}


def _save(store, conf: dict) -> None:
    store.kv_put(S3_IDENTITY_KV, json.dumps(conf).encode())


def _entries(conf: dict, user: str) -> list[dict]:
    return [i for i in conf.get("identities", []) if i.get("name") == user]


def _require_user(conf: dict, user: str) -> list[dict]:
    got = _entries(conf, user)
    if not got:
        raise IamError(404, "NoSuchEntity", f"user {user} not found")
    return got


def _response(action: str, fill) -> bytes:
    root = ET.Element(f"{action}Response", xmlns=IAM_XMLNS)
    result = ET.SubElement(root, f"{action}Result")
    fill(result)
    meta = ET.SubElement(root, "ResponseMetadata")
    ET.SubElement(meta, "RequestId").text = uuid.uuid4().hex
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)


def _user_el(parent, name: str):
    u = ET.SubElement(parent, "User")
    ET.SubElement(u, "UserName").text = name
    ET.SubElement(u, "UserId").text = name
    ET.SubElement(u, "Arn").text = f"arn:aws:iam:::user/{name}"
    ET.SubElement(u, "Path").text = "/"
    return u


def execute(store, form: dict) -> bytes:
    """Run one IAM action against the identity config; returns the XML
    response body or raises IamError."""
    with _MUTATE_LOCK:
        return _execute_locked(store, form)


def _execute_locked(store, form: dict) -> bytes:
    action = form.get("Action", "")
    user = form.get("UserName", "")
    conf = _load(store)
    idents = conf.setdefault("identities", [])

    if action == "CreateUser":
        if not user:
            raise IamError(400, "InvalidInput", "UserName required")
        if _entries(conf, user):
            raise IamError(409, "EntityAlreadyExists", f"user {user} exists")
        idents.append(
            {"name": user, "accessKey": "", "secretKey": "", "actions": []}
        )
        _save(store, conf)
        return _response("CreateUser", lambda r: _user_el(r, user))

    if action == "ListUsers":
        names = sorted({i.get("name", "") for i in idents if i.get("name")})

        def fill(r):
            ET.SubElement(r, "IsTruncated").text = "false"
            users = ET.SubElement(r, "Users")
            for n in names:
                m = ET.SubElement(users, "member")
                ET.SubElement(m, "UserName").text = n
                ET.SubElement(m, "UserId").text = n
                ET.SubElement(m, "Arn").text = f"arn:aws:iam:::user/{n}"

        return _response("ListUsers", fill)

    if action == "DeleteUser":
        _require_user(conf, user)
        conf["identities"] = [i for i in idents if i.get("name") != user]
        _save(store, conf)
        return _response("DeleteUser", lambda r: None)

    if action == "CreateAccessKey":
        existing = _require_user(conf, user)
        ak, sk = mint_key_pair()
        policies = next(
            (i.get("policies") for i in existing if i.get("policies")), []
        )
        # the ["Admin"] default applies ONLY to a user with neither
        # actions nor policies: a PutUserPolicy-restricted user (whose
        # actions were deliberately emptied) must NEVER regain Admin
        # through a key mint — that would be privilege escalation
        actions = next(
            (i.get("actions") for i in existing if i.get("actions")),
            [] if policies else ["Admin"],
        )
        entry = {
            "name": user,
            "accessKey": ak,
            "secretKey": sk,
            "actions": list(actions),
        }
        if policies:
            entry["policies"] = list(policies)
            pn = next(
                (i.get("policyName") for i in existing if i.get("policyName")),
                "",
            )
            if pn:
                entry["policyName"] = pn
        # replace a keyless placeholder if one exists
        placeholders = [
            i for i in existing if not i.get("accessKey")
        ]
        if placeholders:
            idents.remove(placeholders[0])
        idents.append(entry)
        _save(store, conf)

        def fill(r):
            k = ET.SubElement(r, "AccessKey")
            ET.SubElement(k, "UserName").text = user
            ET.SubElement(k, "AccessKeyId").text = ak
            ET.SubElement(k, "SecretAccessKey").text = sk
            ET.SubElement(k, "Status").text = "Active"

        return _response("CreateAccessKey", fill)

    if action == "DeleteAccessKey":
        ak = form.get("AccessKeyId", "")
        victim = next(
            (i for i in idents if i.get("accessKey") == ak), None
        )
        if victim is None:
            raise IamError(404, "NoSuchEntity", f"access key {ak} not found")
        idents.remove(victim)
        owner = victim.get("name", "")
        if owner and not _entries(conf, owner):
            # the USER outlives its last key (AWS semantics: keys and
            # users are separate entities) — keep a keyless placeholder
            # carrying BOTH actions and policies, or delete+recreate of
            # a key would silently shed the user's restrictions
            placeholder = {
                "name": owner,
                "accessKey": "",
                "secretKey": "",
                "actions": victim.get("actions", []),
            }
            if victim.get("policies"):
                placeholder["policies"] = victim["policies"]
                if victim.get("policyName"):
                    placeholder["policyName"] = victim["policyName"]
            idents.append(placeholder)
        _save(store, conf)
        return _response("DeleteAccessKey", lambda r: None)

    if action == "ListAccessKeys":
        existing = _require_user(conf, user)

        def fill(r):
            ET.SubElement(r, "IsTruncated").text = "false"
            keys = ET.SubElement(r, "AccessKeyMetadata")
            for i in existing:
                if not i.get("accessKey"):
                    continue
                m = ET.SubElement(keys, "member")
                ET.SubElement(m, "UserName").text = user
                ET.SubElement(m, "AccessKeyId").text = i["accessKey"]
                ET.SubElement(m, "Status").text = "Active"

        return _response("ListAccessKeys", fill)

    if action == "PutUserPolicy":
        existing = _require_user(conf, user)
        try:
            doc = json.loads(form.get("PolicyDocument", ""))
        except ValueError:
            raise IamError(
                400, "MalformedPolicyDocument", "PolicyDocument is not JSON"
            ) from None
        for i in existing:
            i["policies"] = [doc]
            i["actions"] = []  # policies REPLACE coarse actions
            i["policyName"] = form.get("PolicyName", "default")
        _save(store, conf)
        return _response("PutUserPolicy", lambda r: None)

    if action == "GetUserPolicy":
        existing = _require_user(conf, user)
        pol = next((i.get("policies") for i in existing if i.get("policies")), None)
        if not pol:
            raise IamError(404, "NoSuchEntity", f"user {user} has no policy")

        def fill(r):
            ET.SubElement(r, "UserName").text = user
            ET.SubElement(r, "PolicyName").text = next(
                (i.get("policyName", "default") for i in existing), "default"
            )
            ET.SubElement(r, "PolicyDocument").text = json.dumps(pol[0])

        return _response("GetUserPolicy", fill)

    if action == "DeleteUserPolicy":
        existing = _require_user(conf, user)
        for i in existing:
            i.pop("policies", None)
            i.pop("policyName", None)
        _save(store, conf)
        return _response("DeleteUserPolicy", lambda r: None)

    raise IamError(400, "InvalidAction", f"unsupported action {action!r}")


def error_xml(e: IamError) -> bytes:
    root = ET.Element("ErrorResponse", xmlns=IAM_XMLNS)
    err = ET.SubElement(root, "Error")
    ET.SubElement(err, "Code").text = e.typ
    ET.SubElement(err, "Message").text = str(e)
    ET.SubElement(
        ET.SubElement(root, "ResponseMetadata"), "RequestId"
    ).text = uuid.uuid4().hex
    return b'<?xml version="1.0" encoding="UTF-8"?>' + ET.tostring(root)
