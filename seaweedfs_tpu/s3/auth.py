"""AWS Signature V4 verification.

Reference: weed/s3api/auth_signature_v4.go — header-based AUTH
(Authorization: AWS4-HMAC-SHA256 ...) and presigned-URL query auth.
Streaming chunked uploads (STREAMING-AWS4-HMAC-SHA256-PAYLOAD, per
weed/s3api/chunked_reader_v4.go) are verified chunk-by-chunk using the
SigningContext returned by verify_v4_ex.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import threading
import urllib.parse
from collections import OrderedDict
from dataclasses import dataclass
from datetime import datetime, timedelta, timezone


class S3AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: tuple[str, ...] = ("Admin",)  # Admin|Read|Write|List|Tagging
    # IAM policy documents (AWS JSON); when present they REPLACE the
    # coarse action model for authorization (reference
    # auth_credentials.go identity -> policy binding)
    policies: tuple = ()
    # STS temporary credentials carry a session token the request must
    # echo in x-amz-security-token
    session_token: str = ""

    def allows(self, action: str) -> bool:
        return "Admin" in self.actions or action in self.actions


class IdentityStore:
    def __init__(self, sts=None):
        self._by_access_key: dict[str, Identity] = {}
        self.allow_anonymous = True
        self.sts = sts  # iam.StsService for temp-credential lookup

    def add(self, ident: Identity) -> None:
        self._by_access_key[ident.access_key] = ident
        self.allow_anonymous = False

    def lookup(self, access_key: str) -> Identity | None:
        ident = self._by_access_key.get(access_key)
        if ident is not None:
            return ident
        if self.sts is not None:
            cred = self.sts.lookup(access_key)
            if cred is not None:
                return Identity(
                    name=f"sts:{cred.role.name}",
                    access_key=cred.access_key,
                    secret_key=cred.secret_key,
                    actions=(),
                    policies=tuple(cred.role.policies),
                    session_token=cred.session_token,
                )
        return None

    @property
    def empty(self) -> bool:
        return not self._by_access_key


def _sha256(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _parse_amz_date(s: str) -> datetime:
    """``YYYYMMDD'T'HHMMSS'Z'`` -> aware datetime. The strptime this
    replaces cost ~6us per request on the warm path (format-string
    re-interpretation); the fixed-layout slice parse is ~10x cheaper
    with the same refusal behavior (ValueError on anything malformed —
    the datetime constructor still range-checks every field). The
    digit checks are strict — int() alone would admit forms strptime
    refused (signs, padding, non-ASCII digits)."""
    if (
        len(s) != 16
        or s[8] != "T"
        or s[15] != "Z"
        or not s.isascii()
        or not s[0:8].isdigit()
        or not s[9:15].isdigit()
    ):
        raise ValueError(f"malformed amz date {s!r}")
    return datetime(
        int(s[0:4]), int(s[4:6]), int(s[6:8]),
        int(s[9:11]), int(s[11:13]), int(s[13:15]),
        tzinfo=timezone.utc,
    )


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


# --------------------------------------------------------------- fast path
# The warm-GET ceiling after the byte planes went native (ISSUE 13) is
# this module: every request re-ran the 4-step HMAC key derivation AND
# the full canonical-request reconstruction. Two memoizations close it:
#
# - the DERIVED SIGNING KEY is a pure function of (secret, date, region,
#   service) — one derivation per key/day instead of per request;
# - a bounded VERDICT MEMO over header-auth verifications: the memo key
#   is a digest of EVERY input the verification reads (secret included,
#   so key rotation changes the digest and can never serve a stale
#   verdict), and only SUCCESSFUL verdicts are stored — a 403 is always
#   recomputed. Freshness (the 15-minute skew window), identity
#   existence, and the session-token compare are re-checked on every
#   hit, so a memo hit is bit-identical to a full verification in both
#   result and refusal behavior. Presigned-URL auth and streaming/
#   chunked payloads bypass the memo entirely.
#
# ``SEAWEED_S3_AUTH_MEMO`` sizes the verdict memo (entries; 0 disables).

_SKEY_MAX = 256
_skey_lock = threading.Lock()
_skey_cache: "OrderedDict[tuple, bytes]" = OrderedDict()

_memo_lock = threading.Lock()
_memo: "OrderedDict[bytes, tuple]" = OrderedDict()


def _memo_capacity() -> int:
    try:
        return int(os.environ.get("SEAWEED_S3_AUTH_MEMO", "2048"))
    except ValueError:
        return 2048


def _memo_count(result: str) -> None:
    from ..utils import metrics

    metrics.s3_auth_memo_total.inc(result=result)


def auth_cache_stats() -> dict:
    """Signing-key / verdict-memo occupancy for status surfaces and the
    bench's counter evidence."""
    with _skey_lock:
        skeys = len(_skey_cache)
    with _memo_lock:
        verdicts = len(_memo)
    return {"signing_keys": skeys, "verdicts": verdicts}


def auth_cache_clear() -> None:
    """Drop both caches (tests; never required for correctness — the
    memo digest covers every verification input including the secret)."""
    with _skey_lock:
        _skey_cache.clear()
    with _memo_lock:
        _memo.clear()


def _derive_signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    """Derived SigV4 signing key, cached per (secret, date, region,
    service) — a pure function, so the cache can never go stale; a
    rotated secret is simply a different key.
    ``SEAWEED_S3_AUTH_MEMO=0`` disables this cache too (it is the
    master off-switch for the whole SigV4 fast path, giving benches a
    true per-request-derivation baseline)."""
    if _memo_capacity() <= 0:
        return _derive_signing_key(secret, date, region, service)
    ck = (secret, date, region, service)
    with _skey_lock:
        k = _skey_cache.get(ck)
        if k is not None:
            _skey_cache.move_to_end(ck)
            return k
    k = _derive_signing_key(secret, date, region, service)
    with _skey_lock:
        _skey_cache[ck] = k
        while len(_skey_cache) > _SKEY_MAX:
            _skey_cache.popitem(last=False)
    return k


def sign_v4(
    method: str,
    path: str,
    query: str = "",
    *,
    access_key: str,
    secret_key: str,
    headers: dict | None = None,
    payload_hash: str,
    region: str = "us-east-1",
    service: str = "s3",
    amz_date: str | None = None,
) -> dict:
    """Client-side header-auth SigV4 signer — the mirror image of
    :func:`verify_v4_ex`, built on the SAME canonicalization helpers so
    a canonical-request change lands in one place for both directions.
    Signs `headers` (plus x-amz-date / x-amz-content-sha256, which are
    always added and signed) and returns a new dict with the
    Authorization header merged in. Used by the bench's warm-GET
    phases and the warm-path tests; tests/test_s3.py keeps its own
    independent signer as the cross-implementation check."""
    h = {k.lower(): v for k, v in (headers or {}).items()}
    if amz_date is None:
        amz_date = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    h["x-amz-date"] = amz_date
    h["x-amz-content-sha256"] = payload_hash
    date = amz_date[:8]
    signed = ";".join(sorted(h))
    canonical_headers = "".join(
        f"{k}:{' '.join((h[k] or '').split())}\n" for k in sorted(h)
    )
    creq = "\n".join(
        [
            method,
            canonical_uri(path),
            canonical_query(query),
            canonical_headers,
            signed,
            payload_hash,
        ]
    )
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope, _sha256(creq.encode())]
    )
    sig = hmac.new(
        signing_key(secret_key, date, region, service),
        sts.encode(),
        hashlib.sha256,
    ).hexdigest()
    h["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}"
    )
    return h


def canonical_query(query: str, drop: str | None = None) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    if drop:
        pairs = [(k, v) for k, v in pairs if k != drop]
    enc = [
        (
            urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~"),
        )
        for k, v in pairs
    ]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def canonical_uri(path: str) -> str:
    # S3 canonical URI: each path segment URI-encoded (but "/" kept)
    return urllib.parse.quote(urllib.parse.unquote(path), safe="/-_.~")


@dataclass
class SigningContext:
    """Everything needed to verify a chunk-signature chain (reference
    chunked_reader_v4.go: seed signature + derived signing key)."""

    signing_key: bytes
    amz_date: str
    scope: str  # date/region/service/aws4_request
    seed_signature: str


def verify_v4(
    store: IdentityStore,
    method: str,
    path: str,
    query: str,
    headers,
    payload_hash: str,
) -> Identity:
    return verify_v4_ex(store, method, path, query, headers, payload_hash)[0]


def verify_v4_ex(
    store: IdentityStore,
    method: str,
    path: str,
    query: str,
    headers,
    payload_hash: str,
) -> tuple[Identity, SigningContext | None]:
    """Validate the Authorization header; returns the caller identity
    plus the signing context (None for presigned-URL auth)."""
    auth = headers.get("Authorization", "")
    if not auth:
        # presigned query auth
        q = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
        if "X-Amz-Signature" in q:
            return _verify_presigned(store, method, path, query, headers, q), None
        raise S3AuthError("AccessDenied", "no credentials")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        raise S3AuthError("AccessDenied", "unsupported auth scheme")
    fields = {}
    for part in auth[len("AWS4-HMAC-SHA256 ") :].split(","):
        k, _, v = part.strip().partition("=")
        fields[k] = v
    try:
        cred = fields["Credential"]
        signed_headers = fields["SignedHeaders"].split(";")
        signature = fields["Signature"]
        access_key, date, region, service, _ = cred.split("/")
    except (KeyError, ValueError):
        raise S3AuthError("AuthorizationHeaderMalformed", "bad Authorization") from None
    ident = store.lookup(access_key)
    if ident is None:
        raise S3AuthError("InvalidAccessKeyId", f"unknown access key {access_key}")

    amz_date = headers.get("x-amz-date", "") or headers.get("Date", "")
    # freshness window (AWS allows 15 min of skew); without it a sniffed
    # signed request replays forever. Re-checked on EVERY request —
    # memo hits included — so a memoized verdict can never outlive the
    # skew window.
    try:
        t0 = _parse_amz_date(amz_date)
    except ValueError:
        raise S3AuthError("AccessDenied", "malformed x-amz-date") from None
    if abs((datetime.now(timezone.utc) - t0).total_seconds()) > 900:
        raise S3AuthError("RequestTimeTooSkewed", "request time too skewed")
    canonical_headers = "".join(
        f"{h}:{' '.join((headers.get(h) or '').split())}\n" for h in signed_headers
    )
    # Verdict memo (fast path): the digest covers EVERY verification
    # input — any changed byte (tampered request, rotated secret) is a
    # different key, so a hit can only replay a verification that would
    # succeed identically. The skew window was already re-checked above;
    # identity existence was re-looked-up; the session token is
    # re-compared below (it may ride an unsigned header, outside the
    # digest). Streaming/chunked payloads bypass (their seed context
    # feeds a chunk chain — keep that path byte-for-byte untouched).
    memo_cap = _memo_capacity()
    mkey = None
    cached = None
    if memo_cap > 0 and not payload_hash.startswith("STREAMING-"):
        mkey = hashlib.sha256(
            "\x00".join(
                [
                    ident.secret_key,
                    access_key,
                    method,
                    path,
                    query,
                    canonical_headers,
                    ";".join(signed_headers),
                    payload_hash,
                    signature,
                    amz_date,
                    f"{date}/{region}/{service}",
                ]
            ).encode()
        ).digest()
        with _memo_lock:
            cached = _memo.get(mkey)
            if cached is not None:
                _memo.move_to_end(mkey)
        _memo_count("hit" if cached is not None else "miss")
    else:
        _memo_count("bypass")
    if cached is not None:
        skey, scope = cached
        if ident.session_token and not hmac.compare_digest(
            headers.get("x-amz-security-token", "") or "", ident.session_token
        ):
            raise S3AuthError("InvalidToken", "missing or wrong session token")
        return ident, SigningContext(
            signing_key=skey,
            amz_date=amz_date,
            scope=scope,
            seed_signature=signature,
        )
    creq = "\n".join(
        [
            method,
            canonical_uri(path),
            canonical_query(query),
            canonical_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            f"{date}/{region}/{service}/aws4_request",
            _sha256(creq.encode()),
        ]
    )
    skey = signing_key(ident.secret_key, date, region, service)
    want = hmac.new(skey, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
    if ident.session_token and not hmac.compare_digest(
        headers.get("x-amz-security-token", "") or "", ident.session_token
    ):
        raise S3AuthError("InvalidToken", "missing or wrong session token")
    ctx = SigningContext(
        signing_key=skey,
        amz_date=amz_date,
        scope=f"{date}/{region}/{service}/aws4_request",
        seed_signature=signature,
    )
    if mkey is not None:
        # success-only admission: a mismatch raised above, so refusals
        # (bad signature, rotated key, revoked token) are recomputed on
        # every attempt and can never be served from the memo
        with _memo_lock:
            _memo[mkey] = (skey, ctx.scope)
            while len(_memo) > memo_cap:
                _memo.popitem(last=False)
    return ident, ctx


def verify_chunk_signature(
    ctx: SigningContext, prev_signature: str, chunk: bytes
) -> str:
    """Expected signature of one aws-chunked frame (reference
    chunked_reader_v4.go getChunkSignature)."""
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256-PAYLOAD",
            ctx.amz_date,
            ctx.scope,
            prev_signature,
            _sha256(b""),
            _sha256(chunk),
        ]
    )
    return hmac.new(ctx.signing_key, sts.encode(), hashlib.sha256).hexdigest()


def verify_trailer_signature(
    ctx: SigningContext, prev_signature: str, trailer: bytes
) -> str:
    """Expected x-amz-trailer-signature over the canonical trailer
    block (STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER)."""
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256-TRAILER",
            ctx.amz_date,
            ctx.scope,
            prev_signature,
            _sha256(trailer),
        ]
    )
    return hmac.new(ctx.signing_key, sts.encode(), hashlib.sha256).hexdigest()


def _verify_presigned(store, method, path, query, headers, q) -> Identity:
    try:
        cred = q["X-Amz-Credential"]
        access_key, date, region, service, _ = cred.split("/")
        signed_headers = q["X-Amz-SignedHeaders"].split(";")
        signature = q["X-Amz-Signature"]
        amz_date = q["X-Amz-Date"]
        expires = int(q["X-Amz-Expires"])
    except (KeyError, ValueError):
        raise S3AuthError("AuthorizationQueryParametersError", "bad presign") from None
    # AWS rejects out-of-range expiries rather than clamping: a URL
    # signed with a huge X-Amz-Expires must not be honored indefinitely.
    if expires < 1 or expires > 604800:
        raise S3AuthError(
            "AuthorizationQueryParametersError",
            "X-Amz-Expires must be between 1 and 604800 seconds",
        )
    ident = store.lookup(access_key)
    if ident is None:
        raise S3AuthError("InvalidAccessKeyId", f"unknown access key {access_key}")
    try:
        t0 = _parse_amz_date(amz_date)
    except ValueError:
        raise S3AuthError(
            "AuthorizationQueryParametersError", "malformed X-Amz-Date"
        ) from None
    if datetime.now(timezone.utc) > t0 + timedelta(seconds=expires):
        raise S3AuthError("AccessDenied", "request expired")
    canonical_headers = "".join(
        f"{h}:{' '.join((headers.get(h) or '').split())}\n" for h in signed_headers
    )
    creq = "\n".join(
        [
            method,
            canonical_uri(path),
            canonical_query(query, drop="X-Amz-Signature"),
            canonical_headers,
            ";".join(signed_headers),
            "UNSIGNED-PAYLOAD",
        ]
    )
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            f"{date}/{region}/{service}/aws4_request",
            _sha256(creq.encode()),
        ]
    )
    want = hmac.new(
        signing_key(ident.secret_key, date, region, service),
        sts.encode(),
        hashlib.sha256,
    ).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
    if ident.session_token and not hmac.compare_digest(
        q.get("X-Amz-Security-Token", ""), ident.session_token
    ):
        raise S3AuthError("InvalidToken", "missing or wrong session token")
    return ident
