"""AWS Signature V4 verification.

Reference: weed/s3api/auth_signature_v4.go — header-based AUTH
(Authorization: AWS4-HMAC-SHA256 ...) and presigned-URL query auth.
Streaming chunked uploads (STREAMING-AWS4-HMAC-SHA256-PAYLOAD, per
weed/s3api/chunked_reader_v4.go) are verified chunk-by-chunk using the
SigningContext returned by verify_v4_ex.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass
from datetime import datetime, timedelta, timezone


class S3AuthError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: tuple[str, ...] = ("Admin",)  # Admin|Read|Write|List|Tagging
    # IAM policy documents (AWS JSON); when present they REPLACE the
    # coarse action model for authorization (reference
    # auth_credentials.go identity -> policy binding)
    policies: tuple = ()
    # STS temporary credentials carry a session token the request must
    # echo in x-amz-security-token
    session_token: str = ""

    def allows(self, action: str) -> bool:
        return "Admin" in self.actions or action in self.actions


class IdentityStore:
    def __init__(self, sts=None):
        self._by_access_key: dict[str, Identity] = {}
        self.allow_anonymous = True
        self.sts = sts  # iam.StsService for temp-credential lookup

    def add(self, ident: Identity) -> None:
        self._by_access_key[ident.access_key] = ident
        self.allow_anonymous = False

    def lookup(self, access_key: str) -> Identity | None:
        ident = self._by_access_key.get(access_key)
        if ident is not None:
            return ident
        if self.sts is not None:
            cred = self.sts.lookup(access_key)
            if cred is not None:
                return Identity(
                    name=f"sts:{cred.role.name}",
                    access_key=cred.access_key,
                    secret_key=cred.secret_key,
                    actions=(),
                    policies=tuple(cred.role.policies),
                    session_token=cred.session_token,
                )
        return None

    @property
    def empty(self) -> bool:
        return not self._by_access_key


def _sha256(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(query: str, drop: str | None = None) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    if drop:
        pairs = [(k, v) for k, v in pairs if k != drop]
    enc = [
        (
            urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~"),
        )
        for k, v in pairs
    ]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def canonical_uri(path: str) -> str:
    # S3 canonical URI: each path segment URI-encoded (but "/" kept)
    return urllib.parse.quote(urllib.parse.unquote(path), safe="/-_.~")


@dataclass
class SigningContext:
    """Everything needed to verify a chunk-signature chain (reference
    chunked_reader_v4.go: seed signature + derived signing key)."""

    signing_key: bytes
    amz_date: str
    scope: str  # date/region/service/aws4_request
    seed_signature: str


def verify_v4(
    store: IdentityStore,
    method: str,
    path: str,
    query: str,
    headers,
    payload_hash: str,
) -> Identity:
    return verify_v4_ex(store, method, path, query, headers, payload_hash)[0]


def verify_v4_ex(
    store: IdentityStore,
    method: str,
    path: str,
    query: str,
    headers,
    payload_hash: str,
) -> tuple[Identity, SigningContext | None]:
    """Validate the Authorization header; returns the caller identity
    plus the signing context (None for presigned-URL auth)."""
    auth = headers.get("Authorization", "")
    if not auth:
        # presigned query auth
        q = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
        if "X-Amz-Signature" in q:
            return _verify_presigned(store, method, path, query, headers, q), None
        raise S3AuthError("AccessDenied", "no credentials")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        raise S3AuthError("AccessDenied", "unsupported auth scheme")
    fields = {}
    for part in auth[len("AWS4-HMAC-SHA256 ") :].split(","):
        k, _, v = part.strip().partition("=")
        fields[k] = v
    try:
        cred = fields["Credential"]
        signed_headers = fields["SignedHeaders"].split(";")
        signature = fields["Signature"]
        access_key, date, region, service, _ = cred.split("/")
    except (KeyError, ValueError):
        raise S3AuthError("AuthorizationHeaderMalformed", "bad Authorization") from None
    ident = store.lookup(access_key)
    if ident is None:
        raise S3AuthError("InvalidAccessKeyId", f"unknown access key {access_key}")

    amz_date = headers.get("x-amz-date", "") or headers.get("Date", "")
    # freshness window (AWS allows 15 min of skew); without it a sniffed
    # signed request replays forever
    try:
        t0 = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=timezone.utc
        )
    except ValueError:
        raise S3AuthError("AccessDenied", "malformed x-amz-date") from None
    if abs((datetime.now(timezone.utc) - t0).total_seconds()) > 900:
        raise S3AuthError("RequestTimeTooSkewed", "request time too skewed")
    canonical_headers = "".join(
        f"{h}:{' '.join((headers.get(h) or '').split())}\n" for h in signed_headers
    )
    creq = "\n".join(
        [
            method,
            canonical_uri(path),
            canonical_query(query),
            canonical_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            f"{date}/{region}/{service}/aws4_request",
            _sha256(creq.encode()),
        ]
    )
    skey = signing_key(ident.secret_key, date, region, service)
    want = hmac.new(skey, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
    if ident.session_token and not hmac.compare_digest(
        headers.get("x-amz-security-token", "") or "", ident.session_token
    ):
        raise S3AuthError("InvalidToken", "missing or wrong session token")
    ctx = SigningContext(
        signing_key=skey,
        amz_date=amz_date,
        scope=f"{date}/{region}/{service}/aws4_request",
        seed_signature=signature,
    )
    return ident, ctx


def verify_chunk_signature(
    ctx: SigningContext, prev_signature: str, chunk: bytes
) -> str:
    """Expected signature of one aws-chunked frame (reference
    chunked_reader_v4.go getChunkSignature)."""
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256-PAYLOAD",
            ctx.amz_date,
            ctx.scope,
            prev_signature,
            _sha256(b""),
            _sha256(chunk),
        ]
    )
    return hmac.new(ctx.signing_key, sts.encode(), hashlib.sha256).hexdigest()


def verify_trailer_signature(
    ctx: SigningContext, prev_signature: str, trailer: bytes
) -> str:
    """Expected x-amz-trailer-signature over the canonical trailer
    block (STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER)."""
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256-TRAILER",
            ctx.amz_date,
            ctx.scope,
            prev_signature,
            _sha256(trailer),
        ]
    )
    return hmac.new(ctx.signing_key, sts.encode(), hashlib.sha256).hexdigest()


def _verify_presigned(store, method, path, query, headers, q) -> Identity:
    try:
        cred = q["X-Amz-Credential"]
        access_key, date, region, service, _ = cred.split("/")
        signed_headers = q["X-Amz-SignedHeaders"].split(";")
        signature = q["X-Amz-Signature"]
        amz_date = q["X-Amz-Date"]
        expires = int(q["X-Amz-Expires"])
    except (KeyError, ValueError):
        raise S3AuthError("AuthorizationQueryParametersError", "bad presign") from None
    # AWS rejects out-of-range expiries rather than clamping: a URL
    # signed with a huge X-Amz-Expires must not be honored indefinitely.
    if expires < 1 or expires > 604800:
        raise S3AuthError(
            "AuthorizationQueryParametersError",
            "X-Amz-Expires must be between 1 and 604800 seconds",
        )
    ident = store.lookup(access_key)
    if ident is None:
        raise S3AuthError("InvalidAccessKeyId", f"unknown access key {access_key}")
    try:
        t0 = datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
            tzinfo=timezone.utc
        )
    except ValueError:
        raise S3AuthError(
            "AuthorizationQueryParametersError", "malformed X-Amz-Date"
        ) from None
    if datetime.now(timezone.utc) > t0 + timedelta(seconds=expires):
        raise S3AuthError("AccessDenied", "request expired")
    canonical_headers = "".join(
        f"{h}:{' '.join((headers.get(h) or '').split())}\n" for h in signed_headers
    )
    creq = "\n".join(
        [
            method,
            canonical_uri(path),
            canonical_query(query, drop="X-Amz-Signature"),
            canonical_headers,
            ";".join(signed_headers),
            "UNSIGNED-PAYLOAD",
        ]
    )
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            f"{date}/{region}/{service}/aws4_request",
            _sha256(creq.encode()),
        ]
    )
    want = hmac.new(
        signing_key(ident.secret_key, date, region, service),
        sts.encode(),
        hashlib.sha256,
    ).hexdigest()
    if not hmac.compare_digest(want, signature):
        raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
    if ident.session_token and not hmac.compare_digest(
        q.get("X-Amz-Security-Token", ""), ident.session_token
    ):
        raise S3AuthError("InvalidToken", "missing or wrong session token")
    return ident
