"""Needle: one stored blob inside a volume file.

Byte-precise v2/v3 record layout (SURVEY.md Appendix E; reference:
weed/storage/needle/needle.go:26, needle_write_v2.go, needle_write_v3.go):

  header  [cookie(4) | needleId(8) | size(4)]            (big-endian)
  body    when size > 0:
          [dataSize(4) | data | flags(1)
           | nameSize(1)+name      if FLAG_HAS_NAME
           | mimeSize(1)+mime      if FLAG_HAS_MIME
           | lastModified(5)       if FLAG_HAS_LAST_MODIFIED
           | ttl(2)                if FLAG_HAS_TTL
           | pairsSize(2)+pairs    if FLAG_HAS_PAIRS]
  footer  v2: [crc32c(4)]   v3: [crc32c(4) | appendAtNs(8)]
  padding zero bytes to an 8-byte boundary

`size` counts dataSize..pairs (the body). Max needle size is 4GB.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from ..utils.crc import crc32c
from .types import (
    MAX_NEEDLE_BODY_SIZE,
    NEEDLE_CHECKSUM_SIZE,
    NEEDLE_HEADER_SIZE,
    TIMESTAMP_SIZE,
    padded_record_size,
)

VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
# 0x40: deletion tombstone record (this framework's own marker; the
# reference leaves the bit unused). Disambiguates a delete from a
# legitimate empty-body put on the tail/replica-sync path.
FLAG_IS_TOMBSTONE = 0x40
FLAG_IS_CHUNK_MANIFEST = 0x80

MAX_NEEDLE_SIZE = MAX_NEEDLE_BODY_SIZE
LAST_MODIFIED_BYTES = 5


def footer_size(version: int) -> int:
    """Footer bytes after the body: crc32c(4), plus appendAtNs(8) in v3."""
    return NEEDLE_CHECKSUM_SIZE + (TIMESTAMP_SIZE if version == VERSION3 else 0)


class NeedleError(Exception):
    pass


class CrcError(NeedleError):
    pass


@dataclass
class Needle:
    cookie: int = 0
    needle_id: int = 0
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    last_modified: int = 0  # unix seconds, 5 bytes on disk
    ttl: bytes = b"\x00\x00"  # 2-byte TTL encoding (count + unit)
    pairs: bytes = b""  # serialized extended attributes
    append_at_ns: int = 0  # v3 footer
    checksum: int = 0

    # ---- flag helpers ----
    def _has(self, f: int) -> bool:
        return bool(self.flags & f)

    @property
    def is_compressed(self) -> bool:
        return self._has(FLAG_IS_COMPRESSED)

    @property
    def is_chunk_manifest(self) -> bool:
        return self._has(FLAG_IS_CHUNK_MANIFEST)

    @property
    def is_tombstone(self) -> bool:
        return self._has(FLAG_IS_TOMBSTONE)

    def set_name(self, name: bytes) -> None:
        self.name = name[:255]
        self.flags |= FLAG_HAS_NAME

    def set_mime(self, mime: bytes) -> None:
        self.mime = mime[:255]
        self.flags |= FLAG_HAS_MIME

    def set_last_modified(self, ts: int | None = None) -> None:
        self.last_modified = int(ts if ts is not None else time.time())
        self.flags |= FLAG_HAS_LAST_MODIFIED

    def set_ttl(self, ttl2: bytes) -> None:
        if len(ttl2) != 2:
            raise ValueError("ttl encoding is 2 bytes")
        self.ttl = ttl2
        if ttl2 != b"\x00\x00":
            self.flags |= FLAG_HAS_TTL

    def set_pairs(self, pairs: bytes) -> None:
        if len(pairs) > 0xFFFF:
            raise ValueError("pairs too large")
        self.pairs = pairs
        self.flags |= FLAG_HAS_PAIRS

    # ---- encode ----

    def _body(self) -> bytes:
        parts = [struct.pack(">I", len(self.data)), self.data, bytes([self.flags])]
        if self._has(FLAG_HAS_NAME):
            parts.append(bytes([len(self.name)]))
            parts.append(self.name)
        if self._has(FLAG_HAS_MIME):
            parts.append(bytes([len(self.mime)]))
            parts.append(self.mime)
        if self._has(FLAG_HAS_LAST_MODIFIED):
            parts.append(self.last_modified.to_bytes(LAST_MODIFIED_BYTES, "big"))
        if self._has(FLAG_HAS_TTL):
            parts.append(self.ttl)
        if self._has(FLAG_HAS_PAIRS):
            parts.append(struct.pack(">H", len(self.pairs)))
            parts.append(self.pairs)
        return b"".join(parts)

    def to_bytes(self, version: int = CURRENT_VERSION) -> bytes:
        """Full on-disk record including padding."""
        if version not in (VERSION2, VERSION3):
            raise NeedleError(f"unsupported needle version {version}")
        body = self._body() if (self.data or self.flags) else b""
        size = len(body)
        if size > MAX_NEEDLE_SIZE:
            raise NeedleError(f"needle body {size} exceeds {MAX_NEEDLE_SIZE} limit")
        header = struct.pack(">IQI", self.cookie, self.needle_id, size)
        self.checksum = crc32c(self.data)
        footer = struct.pack(">I", self.checksum)
        if version == VERSION3:
            if not self.append_at_ns:
                self.append_at_ns = time.time_ns()
            footer += struct.pack(">Q", self.append_at_ns)
        raw = header + body + footer
        return raw + b"\x00" * (padded_record_size(len(raw)) - len(raw))

    def disk_size(self, version: int = CURRENT_VERSION) -> int:
        body = len(self._body()) if (self.data or self.flags) else 0
        return padded_record_size(NEEDLE_HEADER_SIZE + body + footer_size(version))

    # ---- decode ----

    @classmethod
    def parse_header(cls, raw: bytes) -> tuple[int, int, int]:
        """-> (cookie, needle_id, size)."""
        if len(raw) < NEEDLE_HEADER_SIZE:
            raise NeedleError("short header")
        return struct.unpack(">IQI", raw[:NEEDLE_HEADER_SIZE])

    @classmethod
    def from_bytes(
        cls, raw: bytes, version: int = CURRENT_VERSION, verify: bool = True
    ) -> "Needle":
        """Parse a full record (header+body+footer, padding optional)."""
        cookie, nid, size = cls.parse_header(raw)
        n = cls(cookie=cookie, needle_id=nid)
        p = NEEDLE_HEADER_SIZE
        if size > 0:
            if len(raw) < p + size:
                raise NeedleError("truncated body")
            body_end = NEEDLE_HEADER_SIZE + size
            (data_size,) = struct.unpack(">I", raw[p : p + 4])
            p += 4
            # dataSize must leave room for at least the flags byte; a bad
            # length field is corruption and must surface as CrcError, not
            # IndexError from an out-of-range slice.
            if p + data_size + 1 > body_end:
                raise CrcError(
                    f"needle {nid:x} corrupt dataSize {data_size} (body size {size})"
                )
            n.data = raw[p : p + data_size]
            p += data_size
            n.flags = raw[p]
            p += 1
            try:
                if n._has(FLAG_HAS_NAME):
                    ln = raw[p]
                    n.name = raw[p + 1 : p + 1 + ln]
                    p += 1 + ln
                if n._has(FLAG_HAS_MIME):
                    lm = raw[p]
                    n.mime = raw[p + 1 : p + 1 + lm]
                    p += 1 + lm
                if n._has(FLAG_HAS_LAST_MODIFIED):
                    n.last_modified = int.from_bytes(
                        raw[p : p + LAST_MODIFIED_BYTES], "big"
                    )
                    p += LAST_MODIFIED_BYTES
                if n._has(FLAG_HAS_TTL):
                    n.ttl = raw[p : p + 2]
                    p += 2
                if n._has(FLAG_HAS_PAIRS):
                    (lp,) = struct.unpack(">H", raw[p : p + 2])
                    n.pairs = raw[p + 2 : p + 2 + lp]
                    p += 2 + lp
            except (IndexError, struct.error):
                raise CrcError(f"needle {nid:x} corrupt optional fields") from None
            if p != NEEDLE_HEADER_SIZE + size:
                raise NeedleError(
                    f"body length mismatch: parsed {p - NEEDLE_HEADER_SIZE}, size {size}"
                )
        if len(raw) < p + NEEDLE_CHECKSUM_SIZE:
            raise NeedleError("truncated footer")
        (n.checksum,) = struct.unpack(">I", raw[p : p + 4])
        p += 4
        if version == VERSION3 and len(raw) >= p + TIMESTAMP_SIZE:
            (n.append_at_ns,) = struct.unpack(">Q", raw[p : p + TIMESTAMP_SIZE])
            p += TIMESTAMP_SIZE
        if verify and crc32c(n.data) != n.checksum:
            raise CrcError(
                f"needle {nid:x} crc mismatch: stored {n.checksum:08x}"
            )
        return n
