"""File id: "<volumeId>,<needleIdHex><cookieHex>" e.g. "3,01637037d6".

Reference: weed/storage/needle/file_id.go — needle id rendered as hex
without leading zeros (minimum one digit), cookie always 8 hex chars.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass


class FileIdError(ValueError):
    pass


@dataclass(frozen=True)
class FileId:
    volume_id: int
    needle_id: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{self.needle_id:x}{self.cookie:08x}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        try:
            vid_str, rest = fid.split(",", 1)
            volume_id = int(vid_str)
        except ValueError:
            raise FileIdError(f"malformed fid {fid!r}") from None
        # Allow the url-path form "<vid>/<fid>" to have stripped slashes already.
        if len(rest) <= 8:
            raise FileIdError(f"fid {fid!r} too short for cookie")
        try:
            needle_id = int(rest[:-8], 16)
            cookie = int(rest[-8:], 16)
        except ValueError:
            raise FileIdError(f"malformed fid {fid!r}") from None
        return cls(volume_id, needle_id, cookie)


def new_cookie() -> int:
    return secrets.randbits(32)
