"""Store: all volumes + EC volumes on one server, across disk locations.

Reference: weed/storage/store.go:60 (Store), disk_location.go /
disk_location_ec.go (per-directory volume discovery, EC siblings),
heartbeat assembly (CollectHeartbeat, store_ec.go:137).
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..ec.context import ECError
from ..ec.device_queue import QueueScope, default_scope
from ..ec.ec_volume import EcVolume
from ..utils.chunk_cache import ChunkCache
from .needle import Needle
from .volume import NotFoundError, Volume, VolumeError

# Default byte budget for the STORE-LEVEL reconstructed-interval cache
# shared by every EC volume on this server (one budget, not one slice
# per volume): a degraded hot volume can claim the whole allowance
# while cold volumes cost nothing. 4x the old per-volume default.
DEFAULT_EC_INTERVAL_CACHE_BYTES = 64 << 20

def durable_writes_default() -> bool:
    """SEAWEED_VOLUME_FSYNC=1 makes every needle append power-loss
    durable before it is acked (fsync — per needle, or amortized over a
    group-commit window when SEAWEED_VOLUME_GROUP_COMMIT_MS > 0).
    Default 0 keeps the historical contract: an acked write survives
    SIGKILL (kernel flush) but not power loss. Read live per write so
    the bench's phases flip it without restarting servers."""
    return os.environ.get("SEAWEED_VOLUME_FSYNC", "0") == "1"


_DAT_RE = re.compile(r"^(?:(?P<col>[^_]+)_)?(?P<vid>\d+)\.dat$")
_ECX_RE = re.compile(r"^(?:(?P<col>[^_]+)_)?(?P<vid>\d+)\.ecx$")
_VIF_RE = re.compile(r"^(?:(?P<col>[^_]+)_)?(?P<vid>\d+)\.vif$")


@dataclass
class DiskLocation:
    """One storage directory, tagged with a disk type (reference
    per-disk-type hdd/ssd DiskLocations, weed/storage/store.go)."""

    directory: str
    max_volume_count: int = 0  # 0 = unlimited
    needle_map_kind: str = "memory"
    disk_type: str = "hdd"
    volumes: dict[int, Volume] = field(default_factory=dict)
    ec_volumes: dict[int, EcVolume] = field(default_factory=dict)

    def load_existing(
        self,
        ec_backend: str = "auto",
        remote_reader_factory=None,
        ec_interval_cache: "ChunkCache | None | str" = "default",
        ec_scheduler: "QueueScope | None" = None,
    ) -> None:
        """`ec_interval_cache`: a ChunkCache = the Store-level shared
        budget; None = cache disabled (Store budget 0); "default"
        (direct callers) = each EcVolume keeps its own private default
        cache, the pre-store-cache behavior. `ec_scheduler` is the
        Store's device-queue scope (placement + admission config) for
        the mounted volumes' degraded reads."""
        if ec_interval_cache == "default":
            cache_kwargs = {}
        else:
            # store-managed: share the one budget, or (None) no cache
            # at all — never a private per-volume slice
            cache_kwargs = {
                "interval_cache": ec_interval_cache,
                "interval_cache_bytes": 0,
            }
        if ec_scheduler is not None:
            cache_kwargs["scheduler"] = ec_scheduler
        for name in sorted(os.listdir(self.directory)):
            m = _DAT_RE.match(name) or _VIF_RE.match(name)
            # a .vif with no local .dat is a cold-tiered volume: it must
            # still mount (Volume opens it in remote mode)
            if m and int(m.group("vid")) not in self.volumes:
                vid = int(m.group("vid"))
                col = m.group("col") or ""
                try:
                    self.volumes[vid] = Volume(
                        self.directory, vid, collection=col, create=False,
                        needle_map_kind=self.needle_map_kind,
                    )
                except VolumeError:
                    continue
            m = _ECX_RE.match(name)
            if m:
                vid = int(m.group("vid"))
                col = m.group("col") or ""
                base = Volume.base_file_name(self.directory, col, vid)
                # only mount when at least one shard is local
                if any(
                    os.path.exists(base + f".ec{i:02d}") for i in range(32)
                ):
                    try:
                        self.ec_volumes[vid] = EcVolume(
                            self.directory, vid, collection=col,
                            backend_name=ec_backend,
                            remote_reader=remote_reader_factory(vid, col)
                            if remote_reader_factory
                            else None,
                            **cache_kwargs,
                        )
                    except ECError:
                        continue


class Store:
    def __init__(
        self,
        directories: list[str],
        ip: str = "localhost",
        port: int = 0,
        public_url: str = "",
        ec_backend: str = "auto",
        ec_remote_reader_factory=None,
        needle_map_kind: str = "memory",
        ec_interval_cache_bytes: int | None = None,
        ec_device_queue: bool | None = None,
        ec_queue_window: int | None = None,
        ec_queue_shares: dict | None = None,
        ec_placement: str | None = None,
        ec_scheduler: "QueueScope | None" = None,
        ec_tenant: str | None = None,
    ):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.ec_backend = ec_backend
        self.ec_remote_reader_factory = ec_remote_reader_factory
        self.needle_map_kind = needle_map_kind
        # Per-STORE device-queue scheduler/placement scope, threaded to
        # every EC producer touching this store's volumes exactly like
        # the interval cache is: a multi-tenant process embedding two
        # Stores no longer has configure() last-caller-wins — each
        # tenant's knobs live in its own scope. All knobs None (and no
        # explicit scope) = the process-wide default scope, so a bare
        # Store keeps today's behavior. `ec_tenant` names the scope's
        # fairness/shed accounting domain on the shared residency
        # ledger: config isolation stays per scope, while the PHYSICAL
        # per-chip budget spans every tenant (ec/device_queue.py
        # ResidencyLedger).
        if ec_scheduler is not None:
            self.ec_scheduler = ec_scheduler
        elif any(
            v is not None
            for v in (
                ec_device_queue, ec_queue_window, ec_queue_shares,
                ec_placement, ec_tenant,
            )
        ):
            from ..ec.device_queue import DEFAULT_WINDOW

            self.ec_scheduler = QueueScope(
                enabled=True if ec_device_queue is None else ec_device_queue,
                window=(
                    DEFAULT_WINDOW if ec_queue_window is None
                    else ec_queue_window
                ),
                shares=ec_queue_shares,
                placement=ec_placement or "auto",
                tenant=ec_tenant,
            )
        else:
            self.ec_scheduler = default_scope()
        # ONE reconstructed-interval cache budget for the whole store,
        # shared by every EC volume (keys are volume-namespaced; see
        # EcVolume). None = the store default; 0 disables the
        # degraded-read cache entirely.
        if ec_interval_cache_bytes is None:
            ec_interval_cache_bytes = DEFAULT_EC_INTERVAL_CACHE_BYTES
        self.ec_interval_cache_bytes = ec_interval_cache_bytes
        self.ec_interval_cache: ChunkCache | None = (
            ChunkCache(ec_interval_cache_bytes, tier="ec_interval")
            if ec_interval_cache_bytes > 0
            else None
        )
        self._lock = threading.RLock()
        # a directory spec may carry a type tag: "/data1:ssd"
        # (reference -dir=/d1 -disk=ssd); bare paths default to hdd
        self.locations = []
        for d in directories:
            dtype = "hdd"
            if ":" in d:
                path, _, tag = d.rpartition(":")
                if tag and "/" not in tag:
                    d, dtype = path, tag
            self.locations.append(
                DiskLocation(
                    d, needle_map_kind=needle_map_kind, disk_type=dtype
                )
            )
        for loc in self.locations:
            os.makedirs(loc.directory, exist_ok=True)
            loc.load_existing(
                ec_backend, ec_remote_reader_factory, self.ec_interval_cache,
                ec_scheduler=self.ec_scheduler,
            )

    # ----------------------------------------------------------- lookup

    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev
        return None

    def location_of(self, vid: int) -> Optional[DiskLocation]:
        for loc in self.locations:
            if vid in loc.volumes:
                return loc
        return None

    def volume_ids(self) -> list[int]:
        return sorted(vid for loc in self.locations for vid in loc.volumes)

    def ec_volume_ids(self) -> list[int]:
        return sorted(vid for loc in self.locations for vid in loc.ec_volumes)

    # ----------------------------------------------------------- manage

    def _pick_location(self, disk_type: str = "") -> DiskLocation:
        if disk_type:
            typed = [l for l in self.locations if l.disk_type == disk_type]
            if not typed:
                raise VolumeError(f"no {disk_type!r} disk location here")
            return min(
                typed, key=lambda l: len(l.volumes) + len(l.ec_volumes)
            )
        return self._pick_any_location()

    def _pick_any_location(self) -> DiskLocation:
        # fewest volumes first (the reference scores free slots per disk)
        return min(self.locations, key=lambda l: len(l.volumes) + len(l.ec_volumes))

    def allocate_volume(
        self,
        vid: int,
        collection: str = "",
        replica_placement: str = "000",
        ttl: str = "",
        disk_type: str = "",
    ) -> Volume:
        with self._lock:
            if self.find_volume(vid) is not None:
                raise VolumeError(f"volume {vid} already exists")
            loc = self._pick_location(disk_type)
            v = Volume(
                loc.directory,
                vid,
                collection=collection,
                replica_placement=replica_placement,
                ttl=ttl,
                needle_map_kind=self.needle_map_kind,
            )
            loc.volumes[vid] = v
            return v

    def reap_expired_volumes(self) -> list[int]:
        """Delete TTL'd volumes idle past their window (reference
        periodic expired-volume reaping)."""
        with self._lock:
            expired = [
                vid
                for loc in self.locations
                for vid, v in loc.volumes.items()
                if v.is_expired()
            ]
        for vid in expired:
            try:
                self.delete_volume(vid)
            except NotFoundError:
                pass
        return expired

    def delete_volume(self, vid: int) -> None:
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    v.close()
                    base = v.dat_path[:-4]
                    exts = [
                        ".dat", ".idx", ".cpd", ".cpx",
                        ".idx.ldb", ".idx.ldb-wal", ".idx.ldb-shm",
                    ]
                    # .vif/.ecsum describe the EC artifacts too: keep them
                    # while EC files coexist (reference Destroy behavior,
                    # volume_destroy_ec_vif_test.go).
                    has_ec = os.path.exists(base + ".ecx") or any(
                        os.path.exists(base + f".ec{i:02d}") for i in range(32)
                    )
                    if not has_ec:
                        exts += [".vif", ".ecsum"]
                    for ext in exts:
                        if os.path.exists(base + ext):
                            os.unlink(base + ext)
                    return
        raise NotFoundError(f"volume {vid} not found")

    def unmount_volume(self, vid: int) -> None:
        """Release a volume WITHOUT touching its files (reference
        volume.unmount): the inverse of mount_volume, for moving a
        volume's files or taking them offline for repair."""
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    v.close()
                    return
        raise NotFoundError(f"volume {vid} not found")

    def mount_volume(self, vid: int, collection: str = "") -> Volume:
        """Load an existing .dat/.idx pair from disk (post-copy/restart)."""
        with self._lock:
            v = self.find_volume(vid)
            if v is not None:
                return v
            for loc in self.locations:
                base = Volume.base_file_name(loc.directory, collection, vid)
                if os.path.exists(base + ".dat"):
                    v = Volume(
                        loc.directory, vid, collection=collection,
                        create=False, needle_map_kind=self.needle_map_kind,
                    )
                    loc.volumes[vid] = v
                    return v
        raise NotFoundError(f"no volume files for {vid} in any location")

    def mount_ec_volume(self, vid: int, collection: str = "") -> EcVolume:
        with self._lock:
            ev = self.find_ec_volume(vid)
            if ev is not None:
                ev.refresh_shards()  # pick up freshly copied shard files
                return ev
            for loc in self.locations:
                base = Volume.base_file_name(loc.directory, collection, vid)
                if os.path.exists(base + ".ecx"):
                    ev = EcVolume(
                        loc.directory,
                        vid,
                        collection,
                        backend_name=self.ec_backend,
                        remote_reader=self.ec_remote_reader_factory(vid, collection)
                        if self.ec_remote_reader_factory
                        else None,
                        interval_cache=self.ec_interval_cache,
                        interval_cache_bytes=0,
                        scheduler=self.ec_scheduler,
                    )
                    loc.ec_volumes[vid] = ev
                    return ev
        raise NotFoundError(f"ec volume {vid} not found in any location")

    def unmount_ec_volume(self, vid: int) -> None:
        with self._lock:
            for loc in self.locations:
                ev = loc.ec_volumes.pop(vid, None)
                if ev is not None:
                    ev.close()
                    return

    def unmount_ec_shards(self, vid: int, shard_ids: list[int]) -> None:
        """Partial unmount: stop serving just these shards; the volume
        stays mounted while any shard remains."""
        if not shard_ids:
            return self.unmount_ec_volume(vid)
        with self._lock:
            for loc in self.locations:
                ev = loc.ec_volumes.get(vid)
                if ev is None:
                    continue
                if ev.unmount_shards(shard_ids) == 0:
                    loc.ec_volumes.pop(vid, None)
                    ev.close()
                return

    # --------------------------------------------------------------- io

    def write_needle(
        self, vid: int, n: Needle, fsync: bool | None = None
    ) -> int:
        """Append `n` to volume `vid`. `fsync=None` (the transports'
        default — neither the gRPC proto nor the HTTP upload carries a
        per-write durability flag) resolves to the store-wide
        :func:`durable_writes_default`; an explicit bool wins."""
        v = self.find_volume(vid)
        if v is None:
            raise NotFoundError(f"volume {vid} not found")
        if fsync is None:
            fsync = durable_writes_default()
        _, size = v.write_needle(n, fsync=fsync)
        return size

    def read_needle(
        self, vid: int, needle_id: int, cookie: Optional[int] = None
    ) -> Needle:
        v = self.find_volume(vid)
        if v is not None:
            return v.read_needle(needle_id, cookie)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return ev.read_needle(needle_id, cookie)
        raise NotFoundError(f"volume {vid} not found")

    def delete_needle(self, vid: int, needle_id: int) -> int:
        v = self.find_volume(vid)
        if v is not None:
            return v.delete_needle(needle_id)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return ev.delete_needle(needle_id)
        raise NotFoundError(f"volume {vid} not found")

    # ---------------------------------------------------------- status

    def status(self) -> dict:
        vols = []
        for loc in self.locations:
            for vid, v in sorted(loc.volumes.items()):
                st = v.stat()
                vols.append(
                    {
                        "id": vid,
                        "collection": st.collection,
                        "size": st.size,
                        "file_count": st.file_count,
                        "deleted_count": st.deleted_count,
                        "deleted_bytes": st.deleted_bytes,
                        "read_only": st.read_only,
                        "replica_placement": st.replica_placement,
                        "version": st.version,
                        "ttl": str(v.ttl),
                        "disk_type": loc.disk_type,
                    }
                )
        ecs = []
        for loc in self.locations:
            for vid, ev in sorted(loc.ec_volumes.items()):
                ecs.append(
                    {
                        "id": vid,
                        "collection": ev.collection,
                        "shards": ev.shard_ids,
                        "shard_size": ev.shard_size(),
                        "data_shards": ev.ctx.data_shards,
                        "parity_shards": ev.ctx.parity_shards,
                        "generation": ev.encode_ts_ns,
                    }
                )
        return {"volumes": vols, "ec_volumes": ecs}

    def close(self) -> None:
        with self._lock:
            for loc in self.locations:
                for v in loc.volumes.values():
                    v.close()
                for ev in loc.ec_volumes.values():
                    ev.close()
                loc.volumes.clear()
                loc.ec_volumes.clear()
