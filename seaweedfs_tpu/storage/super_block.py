"""Volume superblock: first 8 bytes of every .dat file.

Layout (SURVEY.md Appendix E; reference weed/storage/super_block/
super_block.go:13-23):
  [version(1) | replicaPlacement(1) | TTL(2) | compactionRevision(2) |
   reserved(2)]
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

SUPER_BLOCK_SIZE = 8


@dataclass(frozen=True)
class ReplicaPlacement:
    """Replica placement code XYZ: copies on other DCs / racks / servers
    (reference weed/storage/super_block/replica_placement.go)."""

    diff_data_centers: int = 0
    diff_racks: int = 0
    same_rack: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"replica placement must be 3 digits, got {s!r}")
        return cls(int(s[0]), int(s[1]), int(s[2]))

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls(b // 100, (b // 10) % 10, b % 10)

    def to_byte(self) -> int:
        return self.diff_data_centers * 100 + self.diff_racks * 10 + self.same_rack

    @property
    def copy_count(self) -> int:
        return self.diff_data_centers + self.diff_racks + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_data_centers}{self.diff_racks}{self.same_rack}"


@dataclass
class SuperBlock:
    version: int = 3
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: bytes = b"\x00\x00"
    compaction_revision: int = 0

    def to_bytes(self) -> bytes:
        return struct.pack(
            ">BB2sHxx",
            self.version,
            self.replica_placement.to_byte(),
            self.ttl,
            self.compaction_revision,
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "SuperBlock":
        if len(raw) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock too short")
        version, rp, ttl, rev = struct.unpack(">BB2sHxx", raw[:SUPER_BLOCK_SIZE])
        if version not in (2, 3):
            raise ValueError(f"unsupported volume version {version}")
        return cls(version, ReplicaPlacement.from_byte(rp), ttl, rev)
