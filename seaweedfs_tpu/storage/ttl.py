"""TTL: 2-byte on-disk encoding [count(1) | unit(1)].

Reference: weed/storage/needle/volume_ttl.go — units m/h/d/w/M/y; TTL
lives in the superblock (volume-level bucket) and per-needle; reads of
expired needles 404 and fully-expired volumes get reaped.
"""

from __future__ import annotations

from dataclasses import dataclass

_UNITS = {
    0: ("", 0),
    1: ("m", 60),
    2: ("h", 3600),
    3: ("d", 86400),
    4: ("w", 7 * 86400),
    5: ("M", 30 * 86400),
    6: ("y", 365 * 86400),
}
_BY_SUFFIX = {s: (u, secs) for u, (s, secs) in _UNITS.items() if s}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = 0

    @classmethod
    def parse(cls, s: str) -> "TTL":
        """'3m', '4h', '5d', '6w', '7M', '8y'; '' or '0' = no TTL."""
        s = (s or "").strip()
        if s in ("", "0"):
            return cls()
        suffix = s[-1]
        if suffix.isdigit():  # bare number = minutes (reference behavior)
            count = int(s)
            if not 0 < count < 256:
                raise ValueError(f"TTL count out of range in {s!r}")
            return cls(count, 1)
        if suffix not in _BY_SUFFIX:
            raise ValueError(f"unknown TTL unit {suffix!r} in {s!r}")
        unit, _ = _BY_SUFFIX[suffix]
        count = int(s[:-1])
        if not 0 < count < 256:
            raise ValueError(f"TTL count out of range in {s!r}")
        return cls(count, unit)

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if len(b) != 2:
            raise ValueError("TTL encoding is 2 bytes")
        return cls(b[0], b[1] if b[1] in _UNITS else 0)

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit])

    @property
    def seconds(self) -> int:
        return self.count * _UNITS.get(self.unit, ("", 0))[1]

    def __bool__(self) -> bool:
        return self.seconds > 0

    def __str__(self) -> str:
        if not self:
            return ""
        return f"{self.count}{_UNITS[self.unit][0]}"

    def expired(self, last_modified: int, now: float) -> bool:
        return bool(self) and last_modified + self.seconds < now
