"""Pluggable byte-store backends under a volume + tiering transfers.

Reference: weed/storage/backend/backend.go (BackendStorageFile SPI with
local-disk, mmap, S3 and rclone implementations) and the tiering RPCs
weed/server/volume_grpc_tier_upload.go / tier_download.go: a sealed
volume's .dat moves to an object store while the .idx stays local, and
reads become ranged GETs against the cold tier.

Here the remote backend speaks plain S3-style HTTP (PUT object, ranged
GET) — which the framework's own S3 gateway serves, so a cluster can
cold-tier onto itself or onto any S3-compatible endpoint.
"""

from __future__ import annotations

import os
from typing import BinaryIO

import requests

from .. import faults


class BackendError(Exception):
    pass


class BackendStorageFile:
    """Read-side SPI a tiered Volume consumes (reference
    backend.BackendStorageFile ReadAt/WriteAt/Truncate/Close/Name —
    tiered volumes are sealed, so only the read surface is required)."""

    name: str = ""

    def read_at(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class DiskFile(BackendStorageFile):
    """Local-file backend (the default hot tier)."""

    def __init__(self, path: str):
        self.name = path
        self._f = open(path, "rb")

    def read_at(self, offset: int, size: int) -> bytes:
        # Fault points: raised IOError / latency, then byte corruption
        # (bit-flip, torn read) on the payload itself.
        faults.fire("storage.disk.read_at", path=self.name, offset=offset, size=size)
        self._f.seek(offset)
        data = self._f.read(size)
        return faults.mutate(
            "storage.disk.read_at", data, path=self.name, offset=offset, size=size
        )

    def size(self) -> int:
        return os.fstat(self._f.fileno()).st_size

    def close(self) -> None:
        self._f.close()


class S3RemoteFile(BackendStorageFile):
    """Ranged-GET reader against an S3-style object URL
    (http://host:port/bucket/key)."""

    def __init__(self, url: str, session: requests.Session | None = None):
        self.name = url
        self._http = session or requests.Session()
        self._size: int | None = None

    def read_at(self, offset: int, size: int) -> bytes:
        if size <= 0:
            return b""
        faults.fire("storage.remote.read_at", url=self.name, offset=offset, size=size)
        r = self._http.get(
            self.name,
            headers={"Range": f"bytes={offset}-{offset + size - 1}"},
            timeout=60,
        )
        if r.status_code not in (200, 206):
            raise BackendError(
                f"cold-tier read {self.name} [{offset}:{offset+size}]: "
                f"HTTP {r.status_code}"
            )
        data = r.content
        if r.status_code == 200:
            # endpoint ignored Range: slice locally
            data = data[offset : offset + size]
        data = faults.mutate(
            "storage.remote.read_at", data, url=self.name, offset=offset, size=size
        )
        if len(data) < size:
            raise BackendError(
                f"cold-tier short read {self.name}: {len(data)} < {size}"
            )
        return data

    def size(self) -> int:
        if self._size is None:
            r = self._http.head(self.name, timeout=30)
            if r.status_code != 200:
                raise BackendError(
                    f"cold-tier stat {self.name}: HTTP {r.status_code}"
                )
            self._size = int(r.headers.get("Content-Length", "0"))
        return self._size


def open_backend_file(url: str) -> BackendStorageFile:
    if url.startswith(("http://", "https://")):
        return S3RemoteFile(url)
    return DiskFile(url)


# ------------------------------------------------------------- transfers

_CHUNK = 8 * 1024 * 1024


class _SizedReader:
    """File-like wrapper with a known length: requests sends a plain
    Content-Length body (a bare generator would make it emit
    Transfer-Encoding: chunked ALONGSIDE the manual Content-Length —
    a malformed request strict S3 endpoints reject). Every read is
    clamped to ``_CHUNK`` — a multi-GiB PUT never materializes more
    than one bounded chunk in memory regardless of what the HTTP
    stack asks for — and a source that runs dry before `size` bytes
    raises instead of silently sending a short body the endpoint
    would stall on (Content-Length already promised more)."""

    def __init__(self, f: BinaryIO, size: int):
        self._f = f
        self._remaining = size
        self._size = size

    def __len__(self) -> int:
        return self._size

    def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if n is None or n < 0:
            n = self._remaining
        piece = self._f.read(min(n, self._remaining, _CHUNK))
        if not piece:
            raise BackendError(
                f"upload source truncated: {self._remaining} of "
                f"{self._size} bytes still owed"
            )
        self._remaining -= len(piece)
        return piece


def put_object(url: str, src: BinaryIO, size: int) -> None:
    """Streaming PUT of `size` bytes from `src` to an S3-style URL."""
    # Torn-write model: a fault here kills the upload before any byte
    # moves; mid-stream tears are injected by truncating _SizedReader's
    # remaining budget so the endpoint sees a short body and rejects it.
    faults.fire("storage.put_object", url=url, size=size)
    r = requests.put(url, data=_SizedReader(src, size), timeout=3600)
    if r.status_code >= 300:
        raise BackendError(
            f"cold-tier upload {url}: HTTP {r.status_code} {r.text[:200]}"
        )


def fetch_object(url: str, dest_path: str) -> int:
    """Streaming GET of a cold object into a local file (durable:
    written to a temp, fsynced, renamed)."""
    from ..utils.fs import fsync_dir

    tmp = f"{dest_path}.fetch.{os.getpid()}.{os.urandom(4).hex()}"
    n = 0
    try:
        with requests.get(url, stream=True, timeout=3600) as r:
            if r.status_code != 200:
                raise BackendError(
                    f"cold-tier download {url}: HTTP {r.status_code}"
                )
            with open(tmp, "wb") as f:
                for piece in r.iter_content(_CHUNK):
                    piece = faults.mutate(
                        "storage.fetch_object.chunk", piece, url=url, offset=n
                    )
                    f.write(piece)
                    n += len(piece)
                f.flush()
                faults.fire("storage.fetch_object.before_fsync", url=url, path=dest_path)
                os.fsync(f.fileno())
        faults.fire("storage.fetch_object.before_rename", url=url, path=dest_path)
        os.replace(tmp, dest_path)
    except BaseException:
        # a failed stream must not leak a partial multi-GB temp
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(dest_path)
    return n


def delete_object(url: str) -> None:
    """Best-effort delete of a cold object (after tier.download)."""
    try:
        requests.delete(url, timeout=60)
    except requests.RequestException:
        pass
