"""On-disk scalar types and constants.

Byte-precise per the reference formats (SURVEY.md Appendix E):
- 16-byte idx entries [needleId(8) | offset(4) | size(4)], big-endian
  (reference: weed/storage/types/needle_types.go:59-64)
- offsets stored in units of 8 bytes (NeedlePaddingSize)
- size == 0xFFFFFFFF (int32 -1, TombstoneFileSize) marks a deletion
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

NEEDLE_PADDING_SIZE = 8
# Body size is stored as int32 in the idx entry (reference Size int32,
# needle_types.go), so the hard cap is 2^31-1, not the 4GB the 4-byte
# header field could hold.
MAX_NEEDLE_BODY_SIZE = (1 << 31) - 1
NEEDLE_HEADER_SIZE = 16  # cookie(4) + id(8) + size(4)
NEEDLE_MAP_ENTRY_SIZE = 16
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8  # appendAtNs in v3 footer
TOMBSTONE_FILE_SIZE = -1  # stored as 0xFFFFFFFF
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB (4-byte offset * 8)

_IDX_STRUCT = struct.Struct(">QIi")  # needleId, offset(units of 8), size


class NeedleId(int):
    """64-bit needle id; hex-rendered without leading zeros in fids."""

    def hex(self) -> str:  # type: ignore[override]
        return f"{int(self):x}"


def actual_offset(stored_offset: int) -> int:
    """Stored offset (8-byte units) -> byte offset in the .dat file."""
    return stored_offset * NEEDLE_PADDING_SIZE


def to_stored_offset(byte_offset: int) -> int:
    if byte_offset % NEEDLE_PADDING_SIZE != 0:
        raise ValueError(f"unaligned offset {byte_offset}")
    return byte_offset // NEEDLE_PADDING_SIZE


@dataclass(frozen=True)
class NeedleValue:
    """One index entry: where a needle lives inside a volume."""

    needle_id: int
    offset: int  # stored units (multiply by 8 for bytes)
    size: int  # payload size; TOMBSTONE_FILE_SIZE for deletions

    def to_bytes(self) -> bytes:
        return _IDX_STRUCT.pack(self.needle_id, self.offset, self.size)

    @classmethod
    def from_bytes(cls, b: bytes) -> "NeedleValue":
        nid, off, size = _IDX_STRUCT.unpack(b)
        return cls(nid, off, size)

    @property
    def is_deleted(self) -> bool:
        return self.size == TOMBSTONE_FILE_SIZE


def size_is_deleted(size: int) -> bool:
    return size == TOMBSTONE_FILE_SIZE or size < 0


def padded_record_size(header_and_body: int) -> int:
    """Total bytes a record occupies on disk after 8-byte alignment."""
    rem = header_and_body % NEEDLE_PADDING_SIZE
    return header_and_body if rem == 0 else header_and_body + NEEDLE_PADDING_SIZE - rem
