"""Volume: one .dat (superblock + appended needles) + .idx pair.

Mirrors the reference's behavior (weed/storage/volume.go,
volume_write.go:167 writeNeedle2, volume_read.go readNeedle,
volume_vacuum.go) the TPU-framework way: pure-Python engine with the
CRC/GF hot paths in the C++ native core; EC offload in ec/.

Semantics preserved:
- append-only writes, 8-byte aligned records
- overwrite = new append + index update (old space reclaimed by vacuum)
- delete = tombstone append to .dat (empty needle) + idx tombstone
- cookie check on read
- vacuum: copy live needles to .cpd/.cpx then atomic commit
- readonly/writable state
"""

from __future__ import annotations

import contextlib
import os
import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional

from .needle import CURRENT_VERSION, FLAG_IS_TOMBSTONE, Needle, footer_size
from .ttl import TTL
from .. import faults
from .needle_map import MemoryNeedleMap
from .super_block import SUPER_BLOCK_SIZE, ReplicaPlacement, SuperBlock
from ..utils.fs import fsync_dir
from .types import (
    NEEDLE_CHECKSUM_SIZE,
    NEEDLE_HEADER_SIZE,
    NEEDLE_MAP_ENTRY_SIZE,
    NEEDLE_PADDING_SIZE,
    NeedleValue,
    actual_offset,
    padded_record_size,
    to_stored_offset,
)


def _group_commit_window_s() -> float:
    """SEAWEED_VOLUME_GROUP_COMMIT_MS as seconds (0 = fsync-per-needle,
    the default). Read live per write so the bench's on/off phases flip
    it without reopening volumes."""
    try:
        ms = float(os.environ.get("SEAWEED_VOLUME_GROUP_COMMIT_MS", "0"))
    except ValueError:
        ms = 0.0
    return max(0.0, ms) / 1000.0


class _GroupCommitter:
    """Amortizes fsync over a bounded window of concurrent durable
    appends: writers append + kernel-flush under the volume lock, take
    a WINDOW TICKET, and block until one fsync covering their window
    completes — N writers inside one window cost one .dat fsync plus
    one needle-map flush instead of N of each.

    Ordering argument (why a ticket-w writer's bytes are always covered
    by window w's fsync): the ticket is read under the condition lock
    BEFORE the committer bumps ``_open_window`` (also under it), and the
    bump happens-before the fsync starts — so any append that took
    ticket w was handed to the kernel before window w's fsync began.
    The durability contract is unchanged from fsync-per-needle: an
    acked write has survived power loss; only the LATENCY of the ack is
    traded against fsync amortization (bounded by the window).

    A failed fsync fails every writer waiting on that window (and the
    error names the window, not a single needle — none of the cohort's
    bytes are certified durable)."""

    def __init__(self, volume: "Volume", window_s: float):
        self._volume = volume
        self._window_s = window_s
        self._cv = threading.Condition()
        self._open_window = 0
        self._completed = -1
        self._error_upto = -1
        self._last_error: BaseException | None = None
        self._pending = 0
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"group-commit-{volume.volume_id}",
        )
        self._thread.start()

    @property
    def window_s(self) -> float:
        return self._window_s

    def wait_durable(self) -> None:
        """Block the calling writer (which has already appended and
        kernel-flushed) until an fsync covering its bytes completes;
        raise if that fsync failed."""
        with self._cv:
            w = self._open_window
            self._pending += 1
            self._cv.notify_all()
            while self._completed < w:
                if self._stop and not self._thread.is_alive():
                    raise OSError(
                        f"volume {self._volume.volume_id} group "
                        "committer stopped with writes in flight"
                    )
                self._cv.wait(timeout=0.5)
            failed = self._error_upto >= w
            err = self._last_error if failed else None
        if failed:
            raise OSError(f"group commit fsync failed: {err!r}") from err

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending == 0 and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._pending == 0 and self._stop:
                    return
                stopping = self._stop
            # accumulate the window OUTSIDE any lock: appends keep
            # landing and taking tickets for this window meanwhile
            if not stopping and self._window_s > 0:
                time.sleep(self._window_s)
            with self._cv:
                w = self._open_window
                self._open_window += 1
                self._pending = 0
            err: BaseException | None = None
            try:
                self._volume._fsync_all()
            except OSError as e:
                err = e
            with self._cv:
                self._completed = w
                if err is not None:
                    self._error_upto = w
                    self._last_error = err
                self._cv.notify_all()

    def stop(self) -> None:
        """Drain pending writers with a final commit, then exit."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)


class VolumeError(Exception):
    pass


class NotFoundError(VolumeError):
    pass


class CookieMismatch(VolumeError):
    pass


class ReadOnlyError(VolumeError):
    pass


@dataclass
class VolumeStat:
    volume_id: int
    size: int
    file_count: int
    deleted_count: int
    deleted_bytes: int
    read_only: bool
    version: int
    collection: str
    replica_placement: str
    compaction_revision: int


class Volume:
    def __init__(
        self,
        directory: str,
        volume_id: int,
        collection: str = "",
        replica_placement: str = "000",
        version: int = CURRENT_VERSION,
        create: bool = True,
        ttl: str = "",
        needle_map_kind: str = "memory",
    ):
        """needle_map_kind: "memory" (reference default — replay .idx
        into RAM) or "sqlite" (LevelDB-class durable map: O(delta)
        reopen, bounded RAM; reference needle_map_leveldb.go)."""
        self.volume_id = volume_id
        self.collection = collection
        self.directory = directory
        self.needle_map_kind = needle_map_kind
        self.read_only = False
        # Poisoned by an unfinishable vacuum commit (half-swapped pair
        # on disk): all IO refuses until the volume is reopened, at
        # which point _reconcile_vacuum_marker heals from the durable
        # marker + temps.
        self.broken = False
        self._lock = threading.RLock()
        base = self.base_file_name(directory, collection, volume_id)
        self.dat_path = base + ".dat"
        self.idx_path = base + ".idx"
        self.vif_path = base + ".vif"
        self._remote = None  # BackendStorageFile when cold-tiered
        self._tiering = False  # a tier transfer is in flight
        self._vacuuming = False  # a live vacuum is in flight
        self._vacuum_ro_override = None  # set_read_only during vacuum
        self._reconcile_vacuum_marker(base)
        exists = os.path.exists(self.dat_path)
        if not exists:
            # a .vif with tier info and no local .dat = cold-tiered
            # volume: serve reads from the backend, .idx stays local
            from ..ec.volume_info import VolumeInfo

            vif = VolumeInfo.maybe_load(self.vif_path)
            if vif is not None and vif.tier_url:
                self._open_remote(vif)
                return
        if not exists and not create:
            raise VolumeError(f"volume {volume_id} not found at {self.dat_path}")
        if exists:
            with open(self.dat_path, "rb") as f:
                self.super_block = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))
        else:
            self.super_block = SuperBlock(
                version=version,
                replica_placement=ReplicaPlacement.parse(replica_placement),
                ttl=TTL.parse(ttl).to_bytes(),
            )
            with open(self.dat_path, "wb") as f:
                f.write(self.super_block.to_bytes())
                f.flush()
                os.fsync(f.fileno())
        self.version = self.super_block.version
        self.ttl = TTL.from_bytes(self.super_block.ttl)
        # expiry clock for whole-volume reaping; reopen restarts the
        # window (conservative: never reaps early)
        self._last_write_ts = time.time()
        self.needle_map = self._new_map()
        self._dat = open(self.dat_path, "r+b")
        self._dat.seek(0, os.SEEK_END)
        self._append_at = self._pad_tail()
        self._committer: _GroupCommitter | None = None

    def _open_remote(self, vif) -> None:
        """Cold-tier mode: reads ride ranged GETs against the backend
        (reference volume_tier.go LoadRemoteFile)."""
        from .backend import open_backend_file

        self._remote = open_backend_file(vif.tier_url)
        self.super_block = SuperBlock.from_bytes(
            self._remote.read_at(0, SUPER_BLOCK_SIZE)
        )
        self.version = self.super_block.version
        self.ttl = TTL.from_bytes(self.super_block.ttl)
        self._last_write_ts = time.time()
        self.needle_map = self._new_map()
        self._dat = None
        self._append_at = vif.tier_size
        self._committer = None
        self.read_only = True  # tiered volumes are sealed

    @property
    def is_tiered(self) -> bool:
        return self._remote is not None

    def _new_map(self):
        if self.needle_map_kind == "sqlite":
            from .needle_map import SqliteNeedleMap

            return SqliteNeedleMap(
                self.idx_path,
                generation=self.super_block.compaction_revision,
            )
        return MemoryNeedleMap(self.idx_path)

    @staticmethod
    def base_file_name(directory: str, collection: str, volume_id: int) -> str:
        name = f"{collection}_{volume_id}" if collection else str(volume_id)
        return os.path.join(directory, name)

    @staticmethod
    def _reconcile_vacuum_marker(base: str) -> None:
        """Heal a crashed/failed vacuum commit (volume_vacuum.go:316).

        The commit marker `.cpm` is written (fsynced) after `.cpd`/`.cpx`
        are durable and before the swaps. Marker present => the commit
        point was passed: finish any remaining swap (idempotent; replace
        order in vacuum() is dat-then-idx, so `.cpd` can never be the
        one left behind alone). Marker absent => any temps are from a
        compaction that never reached its commit point: abort them.
        """
        marker, cpd, cpx = base + ".cpm", base + ".cpd", base + ".cpx"
        if os.path.exists(marker):
            if os.path.exists(cpd):
                os.replace(cpd, base + ".dat")
            if os.path.exists(cpx):
                os.replace(cpx, base + ".idx")
            fsync_dir(base + ".dat")
            os.unlink(marker)
            fsync_dir(marker)
        else:
            for p in (cpd, cpx):
                if os.path.exists(p):
                    os.unlink(p)

    def _pad_tail(self) -> int:
        """Ensure the append offset is 8-byte aligned (crash padding)."""
        end = self._dat.tell()
        rem = end % NEEDLE_PADDING_SIZE
        if rem:
            self._dat.write(b"\x00" * (NEEDLE_PADDING_SIZE - rem))
            end += NEEDLE_PADDING_SIZE - rem
        return end

    # ------------------------------------------------------------------ io

    def _group_committer(self) -> "_GroupCommitter | None":
        """The active group committer, (re)built lazily from the live
        SEAWEED_VOLUME_GROUP_COMMIT_MS value — a window change mid-life
        (the bench's on/off phases) swaps the committer instead of
        freezing the open-time value. None when the window is 0
        (fsync-per-needle)."""
        w = _group_commit_window_s()
        c = self._committer
        if c is not None and c.window_s == w:
            return c
        with self._lock:
            c = self._committer
            if w <= 0:
                if c is not None:
                    self._committer = None
                    c.stop()
                return None
            if c is None or c.window_s != w:
                if c is not None:
                    c.stop()
                c = _GroupCommitter(self, w)
                self._committer = c
            return c

    def _fsync_all(self) -> None:
        """One fsync covering every append already handed to the
        kernel, with the needle-map idx flush riding the same window —
        the group committer's commit step."""
        with self._lock:
            if self._dat is not None:
                os.fsync(self._dat.fileno())
            self.needle_map.flush()

    def write_needle(self, n: Needle, fsync: bool = False) -> tuple[int, int]:
        """Append; returns (byte_offset, body_size).

        Reference behavior: volume_write.go:167 writeNeedle2 — dedupe
        identical overwrites is NOT done; every write appends.

        With fsync, the write is power-loss durable before returning:
        either its own fsync (window 0) or a group-commit window fsync
        covering it (SEAWEED_VOLUME_GROUP_COMMIT_MS > 0). The chaos
        kill points volume.write.{before_fsync,after_fsync,before_ack}
        bracket the durability step — a SIGKILL at any of them must
        leave the needle fully-acked-durable or clean-unacked, never
        acked-but-lost (tests/test_group_commit.py)."""
        committer = self._group_committer() if fsync else None
        with self._lock:
            self._check_not_broken()
            if self.read_only:
                raise ReadOnlyError(f"volume {self.volume_id} is read-only")
            if self.ttl and not n.last_modified:
                n.set_last_modified()  # expiry clock for TTL'd volumes
            raw = n.to_bytes(self.version)
            offset = self._append_at
            self._dat.seek(offset)
            self._dat.write(raw)
            faults.fire(
                "volume.write.before_fsync",
                volume=self.volume_id, needle=n.needle_id,
            )
            # ALWAYS hand the bytes to the kernel before acknowledging:
            # an acked write must survive SIGKILL of this process (page
            # cache). fsync additionally survives power loss.
            self._dat.flush()
            if fsync and committer is None:
                os.fsync(self._dat.fileno())
            self._append_at = offset + len(raw)
            self._last_write_ts = time.time()
            _, _, size = Needle.parse_header(raw)
            self.needle_map.put(n.needle_id, to_stored_offset(offset), size)
            if fsync and committer is None:
                # power-loss durability covers the INDEX entry too:
                # recovery replays only the .idx
                self.needle_map.flush()
        if fsync and committer is not None:
            # ticket wait OUTSIDE the volume lock: the window
            # accumulates sibling appends while this writer blocks
            committer.wait_durable()
        faults.fire(
            "volume.write.after_fsync",
            volume=self.volume_id, needle=n.needle_id,
        )
        faults.fire(
            "volume.write.before_ack",
            volume=self.volume_id, needle=n.needle_id,
        )
        return offset, size

    def _check_not_broken(self) -> None:
        if self.broken:
            raise VolumeError(
                f"volume {self.volume_id} has a pending vacuum commit; "
                "reopen to heal"
            )

    def read_needle(self, needle_id: int, cookie: Optional[int] = None) -> Needle:
        with self._lock:
            self._check_not_broken()
            nv = self.needle_map.get(needle_id)
            if nv is None or nv.is_deleted:
                raise NotFoundError(f"needle {needle_id:x} not found")
            remote = self._remote
            if remote is None:
                raw = self._pread_record(actual_offset(nv.offset), nv.size)
        if remote is not None:
            # cold-tier GET outside the lock: a 60s remote read must not
            # serialize every other read of this volume behind it (the
            # tiered volume is sealed, so the record can't move)
            raw = remote.read_at(
                actual_offset(nv.offset), self._record_disk_len(nv.size)
            )
        n = Needle.from_bytes(raw, self.version)
        if cookie is not None and n.cookie != cookie:
            raise CookieMismatch(
                f"needle {needle_id:x} cookie mismatch"
            )
        if self.ttl and n.last_modified:
            if self.ttl.expired(n.last_modified, time.time()):
                raise NotFoundError(f"needle {needle_id:x} expired")
        return n

    def _pread_record(self, byte_offset: int, body_size: int) -> bytes:
        if self._dat is None:
            return self._remote.read_at(
                byte_offset, self._record_disk_len(body_size)
            )
        self._dat.seek(byte_offset)
        return self._dat.read(self._record_disk_len(body_size))

    def delete_needle(self, needle_id: int, tombstone: Needle | None = None) -> int:
        """Tombstone both .dat (empty needle append) and .idx.

        `tombstone` lets a tail follower append the SOURCE's tombstone
        record verbatim (its appendAtNs included) so a resynced replica
        stays bit-identical to the source."""
        with self._lock:
            self._check_not_broken()
            if self.read_only:
                raise ReadOnlyError(f"volume {self.volume_id} is read-only")
            nv = self.needle_map.get(needle_id)
            if nv is None or nv.is_deleted:
                return 0
            tomb = tombstone or Needle(cookie=0, needle_id=needle_id)
            tomb.flags |= FLAG_IS_TOMBSTONE
            raw = tomb.to_bytes(self.version)
            self._dat.seek(self._append_at)
            self._dat.write(raw)
            self._dat.flush()  # acked deletes survive SIGKILL too
            self._append_at += len(raw)
            return self.needle_map.delete(needle_id)

    def locate_payload(
        self, needle_id: int, cookie: Optional[int] = None
    ) -> tuple[str, int, int, int]:
        """(dat_path, absolute_offset, size, crc32c) of a needle's DATA
        bytes — the control-plane half of the bulk-read fast path (the
        RDMA sidecar analog): callers pull the range over the native
        Unix-socket server and MUST verify the crc (the sidecar serves
        raw ranges with no lock, so a vacuum commit between locate and
        read, or a replayed locate against the wrong host, surfaces as
        a checksum mismatch instead of silent wrong bytes). Tiered and
        TTL'd volumes raise — they need the locked, validated path."""
        with self._lock:
            self._check_not_broken()
            if self._remote is not None:
                raise VolumeError(
                    f"volume {self.volume_id} is cold-tiered"
                )
            if self.ttl:
                # per-needle expiry lives in the body's optional fields;
                # the HTTP path enforces it, so TTL volumes stay there
                raise VolumeError(
                    f"volume {self.volume_id} is TTL'd; use the HTTP path"
                )
            nv = self.needle_map.get(needle_id)
            if nv is None or nv.is_deleted:
                raise NotFoundError(f"needle {needle_id:x} not found")
            base = actual_offset(nv.offset)
            # header(16) + dataSize(4) prefix locates the payload
            self._dat.seek(base)
            head = self._dat.read(NEEDLE_HEADER_SIZE + 4)
            n_cookie, _nid, body_size = Needle.parse_header(head)
            crc = 0
            if body_size > 0:
                # the footer's crc32c sits right after the body
                self._dat.seek(base + NEEDLE_HEADER_SIZE + body_size)
                (crc,) = struct.unpack(">I", self._dat.read(4))
        if cookie is not None and n_cookie != cookie:
            raise CookieMismatch(f"needle {needle_id:x} cookie mismatch")
        if body_size == 0:
            return self.dat_path, base + NEEDLE_HEADER_SIZE, 0, 0
        (data_size,) = struct.unpack(
            ">I", head[NEEDLE_HEADER_SIZE : NEEDLE_HEADER_SIZE + 4]
        )
        return self.dat_path, base + NEEDLE_HEADER_SIZE + 4, data_size, crc

    def has_needle(self, needle_id: int) -> bool:
        nv = self.needle_map.get(needle_id)
        return nv is not None and not nv.is_deleted

    # ---------------------------------------------------------------- state

    @property
    def size(self) -> int:
        return self._append_at

    def content_size(self) -> int:
        return self._append_at - SUPER_BLOCK_SIZE

    def set_replica_placement(self, replication: str) -> None:
        """Rewrite the superblock's replica placement in place
        (reference volume_super_block.go MaybeWriteSuperBlock /
        volume.configure.replication)."""
        with self._lock:
            self._check_not_broken()
            rp = ReplicaPlacement.parse(replication)
            self.super_block.replica_placement = rp
            self._dat.seek(0)
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
            os.fsync(self._dat.fileno())
            self._dat.seek(self._append_at)

    def set_read_only(self, ro: bool = True) -> None:
        with self._lock:
            if self._remote is not None and not ro:
                raise VolumeError(
                    f"volume {self.volume_id} is cold-tiered; "
                    "tier.download before making it writable"
                )
            if self._vacuuming:
                # remember the operator's intent: vacuum's finally
                # restores this instead of the pre-vacuum state
                self._vacuum_ro_override = ro
                if not ro:
                    # never un-freeze mid-vacuum: vacuum may be in its
                    # final frozen drain, and a write acked after its
                    # last .idx-tail check would be discarded by the
                    # .cpd/.cpx swap; the override applies on finish
                    return
            self.flush()
            self.read_only = ro

    def stat(self) -> VolumeStat:
        return VolumeStat(
            volume_id=self.volume_id,
            size=self.size,
            file_count=self.needle_map.file_counter,
            deleted_count=self.needle_map.deleted_counter,
            deleted_bytes=self.needle_map.deleted_bytes,
            read_only=self.read_only,
            version=self.version,
            collection=self.collection,
            replica_placement=str(self.super_block.replica_placement),
            compaction_revision=self.super_block.compaction_revision,
        )

    def is_expired(self) -> bool:
        """Whole-volume expiry: TTL'd and idle past the TTL window
        (reference expired() reaping of sealed TTL buckets). Uses the
        in-memory last-write clock — file mtime lags buffered writes."""
        if not self.ttl:
            return False
        return self._last_write_ts + self.ttl.seconds < time.time()

    def garbage_ratio(self) -> float:
        cs = self.content_size()
        if cs <= 0:
            return 0.0
        return self.needle_map.deleted_bytes / cs

    def flush(self) -> None:
        with self._lock:
            if self._dat is not None:
                self._dat.flush()
                os.fsync(self._dat.fileno())
            self.needle_map.flush()

    def close(self) -> None:
        # stop the committer BEFORE taking the volume lock: its commit
        # step takes that lock, and a stop() under it would deadlock
        c = self._committer
        if c is not None:
            self._committer = None
            c.stop()
        with self._lock:
            self.flush()
            if self._dat is not None:
                self._dat.close()
            if self._remote is not None:
                self._remote.close()
            self.needle_map.close()

    # -------------------------------------------------------------- tiering

    def tier_upload(self, dest_url: str, keep_local: bool = False) -> int:
        """Move the sealed .dat to a cold backend; the .idx stays local
        (reference volume_grpc_tier_upload.go). Returns bytes moved.

        The network transfer runs OUTSIDE the volume lock — the volume
        is sealed, so the .dat cannot change underneath it, and reads
        keep flowing during a potentially hour-long upload."""
        from ..ec.volume_info import VolumeInfo
        from .backend import put_object

        with self._lock:
            self._check_not_broken()
            if self._tiering:
                raise VolumeError(
                    f"volume {self.volume_id}: tier transfer in progress"
                )
            if self._vacuuming:
                raise VolumeError(
                    f"volume {self.volume_id}: vacuum in progress"
                )
            if self._remote is not None:
                raise VolumeError(f"volume {self.volume_id} already tiered")
            if not self.read_only:
                raise VolumeError(
                    f"volume {self.volume_id} must be readonly to tier"
                )
            self._tiering = True
            self.flush()
            size = self._append_at
        try:
            with open(self.dat_path, "rb") as f:  # unlocked: sealed volume
                put_object(dest_url, f, size)
            with self._lock:
                if self._remote is not None or not self.read_only:
                    raise VolumeError(
                        f"volume {self.volume_id} changed state during tiering"
                    )
                vif = VolumeInfo.maybe_load(self.vif_path) or VolumeInfo(
                    version=self.version
                )
                vif.tier_url = dest_url
                vif.tier_size = size
                vif.save(self.vif_path)
                if not keep_local:
                    self._dat.close()
                    os.unlink(self.dat_path)
                    fsync_dir(self.dat_path)
                    self.needle_map.close()
                    self._open_remote(vif)
                return size
        finally:
            with self._lock:
                self._tiering = False

    def tier_download(self, delete_remote: bool = False) -> int:
        """Bring a cold-tiered .dat back to local disk (reference
        volume_grpc_tier_download.go). Returns bytes fetched. The fetch
        streams outside the lock (remote reads keep serving); only the
        handle switchover is locked."""
        from ..ec.volume_info import VolumeInfo
        from .backend import delete_object, fetch_object

        with self._lock:
            if self._tiering:
                raise VolumeError(
                    f"volume {self.volume_id}: tier transfer in progress"
                )
            if self._vacuuming:
                raise VolumeError(
                    f"volume {self.volume_id}: vacuum in progress"
                )
            if self._remote is None:
                raise VolumeError(f"volume {self.volume_id} is not tiered")
            self._tiering = True
            vif = VolumeInfo.maybe_load(self.vif_path)
            url = vif.tier_url if vif else self._remote.name
        try:
            n = fetch_object(url, self.dat_path)  # unlocked: cold object sealed
            if vif and vif.tier_size and n != vif.tier_size:
                os.unlink(self.dat_path)
                raise VolumeError(
                    f"cold-tier download size mismatch: {n} != {vif.tier_size}"
                )
            with self._lock:
                # drop the reference without closing: an in-flight
                # unlocked cold read may still be using the session
                self._remote = None
                if vif:
                    vif.tier_url, vif.tier_size = "", 0
                    vif.save(self.vif_path)
                self.needle_map.close()
                self.needle_map = self._new_map()
                self._dat = open(self.dat_path, "r+b")
                self._dat.seek(0, os.SEEK_END)
                self._append_at = self._pad_tail()
        finally:
            with self._lock:
                self._tiering = False
        if delete_remote:
            delete_object(url)
        return n

    # --------------------------------------------------------------- vacuum

    def vacuum(self) -> int:
        """Compact: copy live needles to .cpd/.cpx, then atomically commit.

        Returns bytes reclaimed. Mirrors volume_vacuum.go:74
        CompactByVolumeData + :162 CommitCompact: the volume stays
        WRITABLE during the bulk copy; writes that land meanwhile are
        caught up from the .idx journal tail (makeupDiff), with a brief
        freeze only for the final sliver + the atomic swap.
        """
        with self._lock:
            self._check_not_broken()
            if self._remote is not None:
                raise VolumeError(
                    f"volume {self.volume_id} is cold-tiered; "
                    "tier.download before vacuuming"
                )
            if os.path.exists(self.dat_path[:-4] + ".cpm"):
                # A durable commit marker means an earlier vacuum's swap
                # is pending: truncating .cpd/.cpx now would let a crash
                # reconcile partial garbage over the live pair.
                raise VolumeError(
                    f"volume {self.volume_id} has a pending vacuum "
                    "commit; reopen to heal before vacuuming"
                )
            if self._vacuuming:
                raise VolumeError(
                    f"volume {self.volume_id} vacuum already running"
                )
            if self._tiering:
                # vacuum no longer holds the lock for its duration, so
                # it must exclude tier transfers explicitly (and they
                # check _vacuuming symmetrically)
                raise VolumeError(
                    f"volume {self.volume_id}: tier transfer in progress"
                )
            self._vacuuming = True
            self._vacuum_ro_override = None  # set_read_only during vacuum
            was_ro = self.read_only
            # snapshot the live set + journal watermark while locked;
            # the bulk copy then runs WITHOUT the lock and writes keep
            # flowing (reference CompactByVolumeData : the volume stays
            # writable; CommitCompact catches up from the .idx tail)
            self.flush()
            # sqlite maps offer a memory-bounded paginated scan; the
            # memory map is O(live needles) resident anyway, so a list
            # snapshot adds nothing to its footprint
            snap_fn = getattr(self.needle_map, "snapshot_batches", None)
            snapshot = (
                snap_fn() if snap_fn else list(self.needle_map.ascending_visit())
            )
            idx_watermark = os.path.getsize(self.idx_path)
            old_size = self.size
            new_sb = SuperBlock(
                version=self.super_block.version,
                replica_placement=self.super_block.replica_placement,
                ttl=self.super_block.ttl,
                compaction_revision=self.super_block.compaction_revision + 1,
            )
        cpd = self.dat_path[:-4] + ".cpd"
        cpx = self.idx_path[:-4] + ".cpx"
        marker = self.dat_path[:-4] + ".cpm"
        try:
            rfd = os.open(self.dat_path, os.O_RDONLY)
            frozen = False
            try:
                with open(cpd, "wb") as df, open(cpx, "wb") as xf:
                    df.write(new_sb.to_bytes())
                    pos = df.tell()
                    for nv in snapshot:  # phase 1: unlocked bulk copy
                        rec_len = self._record_disk_len(nv.size)
                        raw = os.pread(rfd, rec_len, actual_offset(nv.offset))
                        df.write(raw)
                        xf.write(
                            NeedleValue(
                                nv.needle_id, to_stored_offset(pos), nv.size
                            ).to_bytes()
                        )
                        pos += rec_len
                    # phase 2: replay the .idx tail written during the
                    # copy (volume_vacuum.go makeupDiff catch-up); the
                    # volume stays writable until the delta is small,
                    # then freezes only for the final sliver
                    rounds = 0
                    while True:
                        idx_end = os.path.getsize(self.idx_path)
                        if idx_end == idx_watermark:
                            if frozen:
                                break
                            with self._lock:
                                self.flush()
                                self.read_only = True
                            frozen = True
                            continue
                        rounds += 1
                        if not frozen and (
                            idx_end - idx_watermark < 4096 or rounds > 16
                        ):
                            # small remaining delta (or a firehose
                            # writer): freeze, drain, finish
                            with self._lock:
                                self.flush()
                                self.read_only = True
                            frozen = True
                            idx_end = os.path.getsize(self.idx_path)
                        pos, idx_watermark = self._replay_idx_tail(
                            rfd, idx_watermark, idx_end, df, xf, pos
                        )
                    df.flush()
                    os.fsync(df.fileno())
                    xf.flush()
                    os.fsync(xf.fileno())
            except BaseException:
                for tmp in (cpd, cpx):
                    with contextlib.suppress(OSError):
                        os.unlink(tmp)
                raise
            finally:
                os.close(rfd)
            with self._lock:
                # Commit point: once the marker is durable, the swap is
                # completable by _reconcile_vacuum_marker (here on
                # failure, or at next open after a crash). The closes
                # are best-effort — the compacted pair no longer
                # depends on the old handles.
                with open(marker, "wb") as mf:
                    mf.flush()
                    os.fsync(mf.fileno())
                fsync_dir(marker)
                with contextlib.suppress(OSError):
                    self._dat.close()
                with contextlib.suppress(OSError):
                    self.needle_map.close()
                try:
                    os.replace(cpd, self.dat_path)
                    os.replace(cpx, self.idx_path)
                    fsync_dir(self.dat_path)
                except OSError:
                    if os.path.exists(cpd):
                        # .dat never swapped: the old pair is intact and
                        # consistent — roll back and keep serving it.
                        # The unlinks MUST be made durable: the marker
                        # was fsync'd durable before the swap, so a
                        # crash that resurrects it (+ temps) would make
                        # the next open reconcile the stale compacted
                        # pair over acked post-rollback writes.
                        for p in (cpd, cpx, marker):
                            with contextlib.suppress(OSError):
                                os.unlink(p)
                        fsync_dir(marker)
                        self.needle_map = self._new_map()
                        self._dat = open(self.dat_path, "r+b")
                        self._dat.seek(0, os.SEEK_END)
                        self._append_at = self._pad_tail()
                        raise
                    # .dat swapped: rollback is impossible, so the
                    # commit MUST complete. Retry via the reconcile
                    # path; if the disk still refuses, the marker +
                    # temps stay behind and the next open heals — do
                    # not reopen a diverged new-.dat/old-.idx pair,
                    # and poison the object so no IO (or re-vacuum,
                    # which would truncate the committed .cpx) can
                    # touch it.
                    try:
                        self._reconcile_vacuum_marker(self.dat_path[:-4])
                    except OSError:
                        self.broken = True
                        raise
                else:
                    with contextlib.suppress(OSError):
                        os.unlink(marker)
                        fsync_dir(marker)
                self.super_block = new_sb
                self.needle_map = self._new_map()
                self._dat = open(self.dat_path, "r+b")
                self._dat.seek(0, os.SEEK_END)
                self._append_at = self._pad_tail()
                # writes accepted during the live vacuum inflate the
                # new file; never report negative reclaim
                return max(old_size - self.size, 0)
        finally:
            with self._lock:
                self._vacuuming = False
                if self.broken:
                    # a poisoned volume stays read-only until reopened
                    self.read_only = True
                elif self._vacuum_ro_override is not None:
                    # an operator's set_read_only during the unlocked
                    # compaction window must not be clobbered
                    self.read_only = self._vacuum_ro_override
                else:
                    self.read_only = was_ro
                self._vacuum_ro_override = None

    def _replay_idx_tail(
        self, rfd: int, start: int, end: int, df, xf, pos: int
    ) -> tuple[int, int]:
        """Apply .idx entries in [start, end) to the compacted pair:
        puts copy their .dat record, tombstones append a tombstone
        needle. Returns (new cpd position, consumed idx offset) —
        a torn trailing entry is left for the next round."""
        from .types import NEEDLE_MAP_ENTRY_SIZE, TOMBSTONE_FILE_SIZE

        with open(self.idx_path, "rb") as f:
            f.seek(start)
            raw = f.read(end - start)
        usable = len(raw) - len(raw) % NEEDLE_MAP_ENTRY_SIZE
        for i in range(0, usable, NEEDLE_MAP_ENTRY_SIZE):
            nv = NeedleValue.from_bytes(raw[i : i + NEEDLE_MAP_ENTRY_SIZE])
            if nv.is_deleted:
                tomb = Needle(cookie=0, needle_id=nv.needle_id).to_bytes(
                    self.version
                )
                df.write(tomb)
                pos += len(tomb)
                xf.write(
                    NeedleValue(
                        nv.needle_id, 0, TOMBSTONE_FILE_SIZE
                    ).to_bytes()
                )
            else:
                rec_len = self._record_disk_len(nv.size)
                data = os.pread(rfd, rec_len, actual_offset(nv.offset))
                df.write(data)
                xf.write(
                    NeedleValue(
                        nv.needle_id, to_stored_offset(pos), nv.size
                    ).to_bytes()
                )
                pos += rec_len
        return pos, start + usable

    def _record_disk_len(self, body_size: int) -> int:
        return padded_record_size(
            NEEDLE_HEADER_SIZE + body_size + footer_size(self.version)
        )

    # ------------------------------------------- incremental follow/tail
    # Reference: weed/storage/volume_backup.go (findLastAppendAtNs,
    # BinarySearchByAppendAtNs) — the .idx is the search array; each
    # probe reads the record's v3 footer appendAtNs from the .dat.
    # Divergence from the reference (deliberate): the search pins the
    # LAST put <= since and then walks .dat records forward, so
    # tombstones — which live between puts and carry their own ts —
    # are never skipped; the reference starts at the first put > since
    # and silently loses any delete not followed by a newer put.

    def _require_v3(self) -> None:
        if self.version != 3:
            raise VolumeError(
                f"volume {self.volume_id} is v{self.version}: "
                "tail/incremental sync needs the v3 appendAtNs footer"
            )

    def _read_append_at_ns_at(self, byte_offset: int) -> int:
        """appendAtNs of the record starting at `byte_offset` (v3)."""
        header = self._pread_raw(byte_offset, NEEDLE_HEADER_SIZE)
        _, _, body_size = Needle.parse_header(header)
        ts_off = (
            byte_offset + NEEDLE_HEADER_SIZE + body_size + NEEDLE_CHECKSUM_SIZE
        )
        raw = self._pread_raw(ts_off, 8)
        return struct.unpack(">Q", raw)[0]

    def _pread_raw(self, offset: int, length: int) -> bytes:
        with self._lock:
            self._dat.seek(offset)
            got = self._dat.read(length)
        if len(got) != length:
            raise VolumeError(
                f"short read at {offset} ({len(got)}/{length})"
            )
        return got

    def _live_idx_entries(self) -> list[NeedleValue]:
        """All PUT entries of the .idx in append order (tombstone
        entries have offset 0 — their .dat record is located by the
        forward walk instead). Flushes the map so the journal is
        current."""
        self.needle_map.flush()
        out: list[NeedleValue] = []
        with open(self.idx_path, "rb") as f:
            while True:
                b = f.read(NEEDLE_MAP_ENTRY_SIZE)
                if len(b) < NEEDLE_MAP_ENTRY_SIZE:
                    break
                nv = NeedleValue.from_bytes(b)
                if nv.offset != 0 and not nv.is_deleted:
                    out.append(nv)
        return out

    def _append_end(self) -> int:
        with self._lock:
            self._dat.flush()
            return self._append_at

    def _walk_start_for(self, since_ns: int) -> int:
        """.dat offset of the last PUT with appendAtNs <= since_ns (or
        the superblock end): walking forward from here visits every
        record — put or tombstone — newer than since_ns."""
        entries = self._live_idx_entries()
        lo, hi = 0, len(entries)
        while lo < hi:
            mid = (lo + hi) // 2
            ts = self._read_append_at_ns_at(actual_offset(entries[mid].offset))
            if ts > since_ns:
                hi = mid
            else:
                lo = mid + 1
        if lo == 0:
            return SUPER_BLOCK_SIZE
        return actual_offset(entries[lo - 1].offset)

    def last_append_at_ns(self) -> int:
        """appendAtNs of the newest record — tombstones included, so a
        follower's resume point never re-spans its own trailing
        deletes; 0 for an empty volume."""
        self._require_v3()
        entries = self._live_idx_entries()
        start = (
            actual_offset(entries[-1].offset) if entries else SUPER_BLOCK_SIZE
        )
        last = 0
        for _n, _raw, ts in self.scan_records_between(start, self._append_end()):
            last = max(last, ts)
        return last

    def offset_after_ns(self, since_ns: int) -> int:
        """First .dat byte offset whose record has appendAtNs >
        since_ns (== the append end when nothing is newer). This is the
        byte-level resume point for VolumeIncrementalCopy."""
        self._require_v3()
        end = self._append_end()
        offset = self._walk_start_for(since_ns)
        for _n, raw, ts in self.scan_records_between(offset, end):
            if ts > since_ns:
                return offset
            offset += padded_record_size(len(raw))
        return end

    def scan_records_between(self, start: int, end: int):
        """Yield (needle, record_without_padding, append_at_ns) for
        every record in [start, end) — puts AND tombstones. Reads use
        an independent fd so a concurrent writer can't move this scan's
        file position; `end` must be a snapshot of _append_end()."""
        fd = os.open(self.dat_path, os.O_RDONLY)
        try:
            offset = start
            while offset + NEEDLE_HEADER_SIZE <= end:
                header = os.pread(fd, NEEDLE_HEADER_SIZE, offset)
                if len(header) < NEEDLE_HEADER_SIZE:
                    return
                _, _, body_size = Needle.parse_header(header)
                rec_len = self._record_disk_len(body_size)
                if offset + rec_len > end:
                    return  # racing append: stop at the snapshot
                raw = os.pread(fd, rec_len, offset)
                n = Needle.from_bytes(raw, self.version)
                unpadded = NEEDLE_HEADER_SIZE + body_size + footer_size(
                    self.version
                )
                yield n, raw[:unpadded], n.append_at_ns
                offset += rec_len
        finally:
            os.close(fd)

    def scan_raw_since(self, since_ns: int):
        """Yield (needle, record_without_padding, append_at_ns) for
        every record appended after since_ns, up to a stable size
        snapshot."""
        self._require_v3()
        end = self._append_end()
        start = self._walk_start_for(since_ns)
        for n, raw, ts in self.scan_records_between(start, end):
            if ts > since_ns:
                yield n, raw, ts
