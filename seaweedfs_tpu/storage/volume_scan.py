"""Sequential .dat scanner: the foundation for fix/export/scrub.

Reference: weed/storage/volume_read_all.go (ReadAllNeedles) and the
offline tools weed fix (command/fix.go:86) / weed export (:149).
"""

from __future__ import annotations

import os
from typing import Iterator

from .needle import CrcError, Needle, NeedleError, footer_size
from .super_block import SUPER_BLOCK_SIZE, SuperBlock
from .types import (
    NEEDLE_HEADER_SIZE,
    padded_record_size,
)


class ScanItem:
    __slots__ = ("needle", "offset", "body_size", "crc_ok")

    def __init__(self, needle: Needle, offset: int, body_size: int, crc_ok: bool):
        self.needle = needle
        self.offset = offset
        self.body_size = body_size
        self.crc_ok = crc_ok


def scan_volume_file(dat_path: str) -> tuple[SuperBlock, Iterator[ScanItem]]:
    """-> (superblock, iterator over records in append order).

    Corrupt records yield crc_ok=False with whatever parsed; a record
    whose header is unparsable terminates the scan (truncated tail)."""
    f = open(dat_path, "rb")
    sb = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE))
    size = os.path.getsize(dat_path)
    version = sb.version

    def it() -> Iterator[ScanItem]:
        try:
            offset = SUPER_BLOCK_SIZE
            while offset + NEEDLE_HEADER_SIZE <= size:
                f.seek(offset)
                header = f.read(NEEDLE_HEADER_SIZE)
                if len(header) < NEEDLE_HEADER_SIZE:
                    return
                try:
                    _, _, body_size = Needle.parse_header(header)
                except NeedleError:
                    return
                rec_len = padded_record_size(
                    NEEDLE_HEADER_SIZE + body_size + footer_size(version)
                )
                if offset + rec_len > size:
                    return  # truncated tail
                f.seek(offset)
                raw = f.read(rec_len)
                crc_ok = True
                try:
                    n = Needle.from_bytes(raw, version)
                except CrcError:
                    crc_ok = False
                    try:
                        n = Needle.from_bytes(raw, version, verify=False)
                    except NeedleError:
                        return
                except NeedleError:
                    return
                yield ScanItem(n, offset, body_size, crc_ok)
                offset += rec_len
        finally:
            f.close()

    return sb, it()
