"""Needle maps: needleId -> (offset, size) per volume.

The reference offers in-memory, LevelDB and sorted-file impls behind
`NeedleMapper` (weed/storage/needle_map.go:23). Here:

- `MemoryNeedleMap`: dict-backed, rebuilt by replaying the .idx journal
  (the reference's default for hot volumes).
- `SortedFileNeedleMap`: binary search over a sealed, sorted .ecx-style
  file (reference weed/storage/erasure_coding/ec_volume.go:501).
- `MemDb`: numpy-backed builder used to convert a write-ordered .idx
  into a sorted .ecx (reference ec_encoder.go:32-59).

All on-disk entries are the 16-byte big-endian format from types.py.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from .types import (
    NEEDLE_MAP_ENTRY_SIZE,
    TOMBSTONE_FILE_SIZE,
    NeedleValue,
)


def walk_index_file(path: str) -> Iterator[NeedleValue]:
    """Yield idx entries in write order (reference weed/storage/idx)."""
    with open(path, "rb") as f:
        while True:
            chunk = f.read(NEEDLE_MAP_ENTRY_SIZE * 4096)
            if not chunk:
                return
            usable = len(chunk) - (len(chunk) % NEEDLE_MAP_ENTRY_SIZE)
            for i in range(0, usable, NEEDLE_MAP_ENTRY_SIZE):
                yield NeedleValue.from_bytes(chunk[i : i + NEEDLE_MAP_ENTRY_SIZE])


class MemoryNeedleMap:
    """Write-through needle map: dict in memory + append-only .idx file."""

    def __init__(self, idx_path: str):
        self.idx_path = idx_path
        self._map: dict[int, NeedleValue] = {}
        self.file_counter = 0
        self.deleted_counter = 0
        self.deleted_bytes = 0
        self._idx_file = None
        if os.path.exists(idx_path):
            # a crash can tear the trailing entry; appending after a torn
            # tail would skew EVERY later entry's alignment, so truncate
            # to whole records before replay + reopen
            size = os.path.getsize(idx_path)
            torn = size % NEEDLE_MAP_ENTRY_SIZE
            if torn:
                with open(idx_path, "r+b") as f:
                    f.truncate(size - torn)
            for nv in walk_index_file(idx_path):
                self._replay(nv)
        self._idx_file = open(idx_path, "ab")

    def _replay(self, nv: NeedleValue) -> None:
        if nv.is_deleted:
            old = self._map.pop(nv.needle_id, None)
            if old is not None:
                self.deleted_counter += 1
                self.deleted_bytes += old.size
        else:
            self._log_put(nv)

    def _log_put(self, nv: NeedleValue) -> None:
        # Overwrites dead-record the previous copy in the .dat; count it
        # as garbage so vacuum triggers (reference needle_map_metric.go
        # logPut adds oldSize to the deletion counters).
        old = self._map.get(nv.needle_id)
        self._map[nv.needle_id] = nv
        self.file_counter += 1
        if old is not None and old.size > 0:
            self.deleted_counter += 1
            self.deleted_bytes += old.size

    def put(self, needle_id: int, offset: int, size: int) -> None:
        nv = NeedleValue(needle_id, offset, size)
        self._log_put(nv)
        self._idx_file.write(nv.to_bytes())
        # to the kernel with every journal append: an acked write's index
        # entry must survive SIGKILL (fsync is the caller's power-loss knob)
        self._idx_file.flush()

    def delete(self, needle_id: int) -> int:
        """Append a tombstone; returns freed byte count (0 if absent)."""
        old = self._map.pop(needle_id, None)
        self._idx_file.write(
            NeedleValue(needle_id, 0, TOMBSTONE_FILE_SIZE).to_bytes()
        )
        self._idx_file.flush()
        if old is None:
            return 0
        self.deleted_counter += 1
        self.deleted_bytes += old.size
        return old.size

    def get(self, needle_id: int) -> Optional[NeedleValue]:
        return self._map.get(needle_id)

    def __len__(self) -> int:
        return len(self._map)

    def ascending_visit(self) -> Iterator[NeedleValue]:
        for nid in sorted(self._map):
            yield self._map[nid]

    def flush(self) -> None:
        if self._idx_file:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    def close(self) -> None:
        if self._idx_file:
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None


class MemDb:
    """In-memory id->entry store for index conversions (.idx -> .ecx)."""

    def __init__(self):
        self._map: dict[int, NeedleValue] = {}

    def load_idx(self, idx_path: str) -> None:
        for nv in walk_index_file(idx_path):
            if nv.is_deleted:
                self._map.pop(nv.needle_id, None)
            else:
                self._map[nv.needle_id] = nv

    def put(self, nv: NeedleValue) -> None:
        self._map[nv.needle_id] = nv

    def ascending_visit(self) -> Iterator[NeedleValue]:
        for nid in sorted(self._map):
            yield self._map[nid]

    def write_sorted_file(self, path: str) -> None:
        """Write entries ascending by needleId, fsync'd (sealed .ecx)."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for nv in self.ascending_visit():
                f.write(nv.to_bytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        from ..utils.fs import fsync_dir

        fsync_dir(path)

    def __len__(self) -> int:
        return len(self._map)


class SortedFileNeedleMap:
    """Binary search over a sealed sorted index file (.ecx semantics).

    A partial trailing record means corruption (reference
    ec_decoder.go:152-156 treats it as fatal).
    """

    def __init__(self, path: str):
        self.path = path
        size = os.path.getsize(path)
        if size % NEEDLE_MAP_ENTRY_SIZE != 0:
            raise ValueError(f"{path}: corrupt sorted index (partial record)")
        self.count = size // NEEDLE_MAP_ENTRY_SIZE
        # Only the 8-byte id column stays resident for the binary search;
        # full 16-byte entries are pread on demand, so a sealed index of
        # tens of millions of needles costs 8B/needle of RAM, not 16B+file.
        raw = np.fromfile(path, dtype=np.uint8).reshape(self.count, NEEDLE_MAP_ENTRY_SIZE)
        self._ids = raw[:, :8].copy().view(">u8").reshape(self.count)
        self._fd = os.open(path, os.O_RDONLY)

    def _entry(self, i: int) -> NeedleValue:
        b = os.pread(self._fd, NEEDLE_MAP_ENTRY_SIZE, i * NEEDLE_MAP_ENTRY_SIZE)
        return NeedleValue.from_bytes(b)

    def get(self, needle_id: int) -> Optional[NeedleValue]:
        i = int(np.searchsorted(self._ids, needle_id))
        if i >= self.count or int(self._ids[i]) != needle_id:
            return None
        return self._entry(i)

    def ascending_visit(self) -> Iterator[NeedleValue]:
        for i in range(self.count):
            yield self._entry(i)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __len__(self) -> int:
        return self.count
