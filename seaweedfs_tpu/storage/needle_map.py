"""Needle maps: needleId -> (offset, size) per volume.

The reference offers in-memory, LevelDB and sorted-file impls behind
`NeedleMapper` (weed/storage/needle_map.go:23). Here:

- `MemoryNeedleMap`: dict-backed, rebuilt by replaying the .idx journal
  (the reference's default for hot volumes).
- `SortedFileNeedleMap`: binary search over a sealed, sorted .ecx-style
  file (reference weed/storage/erasure_coding/ec_volume.go:501).
- `MemDb`: numpy-backed builder used to convert a write-ordered .idx
  into a sorted .ecx (reference ec_encoder.go:32-59).

All on-disk entries are the 16-byte big-endian format from types.py.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

from .types import (
    NEEDLE_MAP_ENTRY_SIZE,
    TOMBSTONE_FILE_SIZE,
    NeedleValue,
)


def walk_index_file(path: str, start: int = 0) -> Iterator[NeedleValue]:
    """Yield idx entries in write order (reference weed/storage/idx),
    optionally from a byte offset (watermark-tail replay)."""
    with open(path, "rb") as f:
        if start:
            f.seek(start)
        while True:
            chunk = f.read(NEEDLE_MAP_ENTRY_SIZE * 4096)
            if not chunk:
                return
            usable = len(chunk) - (len(chunk) % NEEDLE_MAP_ENTRY_SIZE)
            for i in range(0, usable, NEEDLE_MAP_ENTRY_SIZE):
                yield NeedleValue.from_bytes(chunk[i : i + NEEDLE_MAP_ENTRY_SIZE])


def heal_torn_tail(idx_path: str) -> None:
    """A crash can tear the trailing entry; appending after a torn tail
    would skew EVERY later entry's alignment, so truncate to whole
    records before replay + reopen."""
    if not os.path.exists(idx_path):
        return
    size = os.path.getsize(idx_path)
    torn = size % NEEDLE_MAP_ENTRY_SIZE
    if torn:
        with open(idx_path, "r+b") as f:
            f.truncate(size - torn)


class MemoryNeedleMap:
    """Write-through needle map: dict in memory + append-only .idx file."""

    def __init__(self, idx_path: str):
        self.idx_path = idx_path
        self._map: dict[int, NeedleValue] = {}
        self.file_counter = 0
        self.deleted_counter = 0
        self.deleted_bytes = 0
        # journal appends since the last fsync: flush() is a no-op on a
        # clean map, so a group-commit window with no index traffic (or
        # back-to-back flushes) costs zero extra fsyncs
        self._dirty = False
        self._idx_file = None
        if os.path.exists(idx_path):
            heal_torn_tail(idx_path)
            for nv in walk_index_file(idx_path):
                self._replay(nv)
        self._idx_file = open(idx_path, "ab")

    def _replay(self, nv: NeedleValue) -> None:
        if nv.is_deleted:
            old = self._map.pop(nv.needle_id, None)
            if old is not None:
                self.deleted_counter += 1
                self.deleted_bytes += old.size
        else:
            self._log_put(nv)

    def _log_put(self, nv: NeedleValue) -> None:
        # Overwrites dead-record the previous copy in the .dat; count it
        # as garbage so vacuum triggers (reference needle_map_metric.go
        # logPut adds oldSize to the deletion counters).
        old = self._map.get(nv.needle_id)
        self._map[nv.needle_id] = nv
        self.file_counter += 1
        if old is not None and old.size > 0:
            self.deleted_counter += 1
            self.deleted_bytes += old.size

    def put(self, needle_id: int, offset: int, size: int) -> None:
        nv = NeedleValue(needle_id, offset, size)
        self._log_put(nv)
        self._idx_file.write(nv.to_bytes())
        # to the kernel with every journal append: an acked write's index
        # entry must survive SIGKILL (fsync is the caller's power-loss knob)
        self._idx_file.flush()
        self._dirty = True

    def delete(self, needle_id: int) -> int:
        """Append a tombstone; returns freed byte count (0 if absent)."""
        old = self._map.pop(needle_id, None)
        self._idx_file.write(
            NeedleValue(needle_id, 0, TOMBSTONE_FILE_SIZE).to_bytes()
        )
        self._idx_file.flush()
        self._dirty = True
        if old is None:
            return 0
        self.deleted_counter += 1
        self.deleted_bytes += old.size
        return old.size

    def get(self, needle_id: int) -> Optional[NeedleValue]:
        return self._map.get(needle_id)

    def __len__(self) -> int:
        return len(self._map)

    def ascending_visit(self) -> Iterator[NeedleValue]:
        for nid in sorted(self._map):
            yield self._map[nid]

    def flush(self) -> None:
        if self._idx_file and self._dirty:
            self._dirty = False
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    def close(self) -> None:
        if self._idx_file:
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None


class SqliteNeedleMap:
    """Durable B-tree needle map: the LevelDB-class mapper
    (reference weed/storage/needle_map_leveldb.go) on sqlite.

    The .idx journal stays authoritative (EC conversion, replication,
    crash recovery all read it); the sqlite DB at ``<idx>.ldb`` is an
    index OF the journal with a persisted replay watermark, so reopening
    a volume replays only the .idx tail written since the last flush —
    O(delta), not O(live needles) — and resident memory is a small
    pending-write buffer instead of the whole map."""

    FLUSH_EVERY = 2000  # pending ops before a sqlite transaction

    def __init__(self, idx_path: str, generation: int = 0):
        import sqlite3
        import threading

        self.idx_path = idx_path
        self.db_path = idx_path + ".ldb"
        self._pending: dict[int, Optional[NeedleValue]] = {}  # None = delete
        # guards _pending + db access: has_needle/scrub read the map
        # WITHOUT the volume lock (safe for the memory map's atomic
        # dict.get; sqlite needs explicit serialization)
        self._op_lock = threading.Lock()
        self.file_counter = 0
        self.deleted_counter = 0
        self.deleted_bytes = 0
        self._dirty = False  # journal appends since the last fsync
        self._generation = generation
        self._idx_file = None
        heal_torn_tail(idx_path)
        try:
            self._open_db()
        except sqlite3.DatabaseError:
            # synchronous=OFF can physically corrupt the .ldb on power
            # loss; the .idx journal is authoritative, so discard the
            # cache and rebuild rather than keeping the volume offline
            self._discard_db()
            self._open_db()
        watermark = self._meta("watermark")
        idx_size = os.path.getsize(idx_path) if os.path.exists(idx_path) else 0
        if watermark > idx_size or self._meta("generation") != generation:
            # the journal was replaced (vacuum commit) or shrank: the DB
            # indexes a different file — rebuild from scratch
            self._db.execute("DELETE FROM needles")
            self._db.execute("DELETE FROM meta")
            watermark = 0
        else:
            self.file_counter = self._meta("file_counter")
            self.deleted_counter = self._meta("deleted_counter")
            self.deleted_bytes = self._meta("deleted_bytes")
        # replay only the journal tail the DB hasn't absorbed yet
        if idx_size > watermark:
            for nv in walk_index_file(idx_path, start=watermark):
                if nv.is_deleted:
                    self._apply_delete(nv.needle_id)
                else:
                    self._apply_put(nv)
            with self._op_lock:
                self._commit_pending_locked()
        self._idx_file = open(idx_path, "ab")

    def _open_db(self) -> None:
        import sqlite3

        # autocommit connection; _commit_pending manages its own
        # BEGIN/COMMIT batches (implicit transactions would collide)
        self._db = sqlite3.connect(
            self.db_path, check_same_thread=False, isolation_level=None
        )
        self._db.execute("PRAGMA journal_mode=WAL")
        # the .idx journal is the durability story; sqlite may lose its
        # last transactions on power loss and recover from the watermark
        self._db.execute("PRAGMA synchronous=OFF")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS needles"
            " (id INTEGER PRIMARY KEY, offset INTEGER, size INTEGER)"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v INTEGER)"
        )

    def _discard_db(self) -> None:
        try:
            self._db.close()
        except Exception:
            pass
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(self.db_path + suffix)
            except OSError:
                pass

    def _meta(self, key: str) -> int:
        row = self._db.execute(
            "SELECT v FROM meta WHERE k = ?", (key,)
        ).fetchone()
        return int(row[0]) if row else 0

    # ---------------------------------------------------------- mutation

    def _apply_put(self, nv: NeedleValue) -> None:
        old = self.get(nv.needle_id)
        with self._op_lock:
            self._pending[nv.needle_id] = nv
        self.file_counter += 1
        if old is not None and old.size > 0:
            self.deleted_counter += 1
            self.deleted_bytes += old.size

    def _apply_delete(self, needle_id: int) -> int:
        old = self.get(needle_id)
        with self._op_lock:
            self._pending[needle_id] = None
        if old is None:
            return 0
        self.deleted_counter += 1
        self.deleted_bytes += old.size
        return old.size

    def put(self, needle_id: int, offset: int, size: int) -> None:
        self._apply_put(NeedleValue(needle_id, offset, size))
        self._idx_file.write(NeedleValue(needle_id, offset, size).to_bytes())
        self._idx_file.flush()
        self._dirty = True
        self._maybe_commit()

    def delete(self, needle_id: int) -> int:
        freed = self._apply_delete(needle_id)
        self._idx_file.write(
            NeedleValue(needle_id, 0, TOMBSTONE_FILE_SIZE).to_bytes()
        )
        self._idx_file.flush()
        self._dirty = True
        self._maybe_commit()
        return freed

    def _maybe_commit(self) -> None:
        if len(self._pending) >= self.FLUSH_EVERY:
            with self._op_lock:
                self._commit_pending_locked()

    def _commit_pending_locked(self) -> None:
        if not self._pending and self._meta("watermark") == self._idx_tell():
            return
        cur = self._db.cursor()
        cur.execute("BEGIN")
        for nid, nv in self._pending.items():
            if nv is None:
                cur.execute("DELETE FROM needles WHERE id = ?", (nid,))
            else:
                cur.execute(
                    "INSERT OR REPLACE INTO needles VALUES (?, ?, ?)",
                    (nid, nv.offset, nv.size),
                )
        for k, v in (
            ("watermark", self._idx_tell()),
            ("generation", self._generation),
            ("file_counter", self.file_counter),
            ("deleted_counter", self.deleted_counter),
            ("deleted_bytes", self.deleted_bytes),
        ):
            cur.execute("INSERT OR REPLACE INTO meta VALUES (?, ?)", (k, v))
        self._db.commit()
        self._pending.clear()

    def _idx_tell(self) -> int:
        if getattr(self, "_idx_file", None):
            return self._idx_file.tell()
        return os.path.getsize(self.idx_path) if os.path.exists(self.idx_path) else 0

    # ------------------------------------------------------------- reads

    def get(self, needle_id: int) -> Optional[NeedleValue]:
        with self._op_lock:
            if needle_id in self._pending:
                return self._pending[needle_id]
            row = self._db.execute(
                "SELECT offset, size FROM needles WHERE id = ?", (needle_id,)
            ).fetchone()
        if row is None:
            return None
        return NeedleValue(needle_id, int(row[0]), int(row[1]))

    def __len__(self) -> int:
        with self._op_lock:
            self._commit_pending_locked()
            return int(
                self._db.execute("SELECT COUNT(*) FROM needles").fetchone()[0]
            )

    def ascending_visit(self) -> Iterator[NeedleValue]:
        with self._op_lock:
            self._commit_pending_locked()
            rows = self._db.execute(
                "SELECT id, offset, size FROM needles ORDER BY id"
            ).fetchall()
        for nid, off, size in rows:
            yield NeedleValue(int(nid), int(off), int(size))

    def snapshot_batches(self, batch_size: int = 8192) -> Iterator[NeedleValue]:
        """Memory-bounded ascending scan via keyset pagination: each
        batch holds the op lock only briefly, so a live vacuum of a
        large volume never materializes the whole map (the point of
        this mapper). Rows added concurrently may appear (id > cursor)
        — harmless: vacuum's .idx-tail replay re-copies them."""
        last = -1
        while True:
            with self._op_lock:
                self._commit_pending_locked()
                rows = self._db.execute(
                    "SELECT id, offset, size FROM needles"
                    " WHERE id > ? ORDER BY id LIMIT ?",
                    (last, batch_size),
                ).fetchall()
            if not rows:
                return
            for nid, off, size in rows:
                yield NeedleValue(int(nid), int(off), int(size))
            last = int(rows[-1][0])

    def flush(self) -> None:
        # the .idx journal IS the durability contract; a sqlite commit
        # per fsync'd write would defeat the FLUSH_EVERY batching (a
        # crash before commit is the watermark-tail-replay case)
        if getattr(self, "_idx_file", None) and self._dirty:
            self._dirty = False
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    def close(self) -> None:
        if getattr(self, "_idx_file", None):
            with self._op_lock:
                self._commit_pending_locked()
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None
        # the sqlite connection stays open for lock-free straggler
        # readers (scrub/has_needle racing a vacuum's map swap); the
        # GC closes it when the last reference drops


class MemDb:
    """In-memory id->entry store for index conversions (.idx -> .ecx)."""

    def __init__(self):
        self._map: dict[int, NeedleValue] = {}

    def load_idx(self, idx_path: str) -> None:
        for nv in walk_index_file(idx_path):
            if nv.is_deleted:
                self._map.pop(nv.needle_id, None)
            else:
                self._map[nv.needle_id] = nv

    def put(self, nv: NeedleValue) -> None:
        self._map[nv.needle_id] = nv

    def ascending_visit(self) -> Iterator[NeedleValue]:
        for nid in sorted(self._map):
            yield self._map[nid]

    def write_sorted_file(self, path: str) -> None:
        """Write entries ascending by needleId, fsync'd (sealed .ecx)."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for nv in self.ascending_visit():
                f.write(nv.to_bytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        from ..utils.fs import fsync_dir

        fsync_dir(path)

    def __len__(self) -> int:
        return len(self._map)


class SortedFileNeedleMap:
    """Binary search over a sealed sorted index file (.ecx semantics).

    A partial trailing record means corruption (reference
    ec_decoder.go:152-156 treats it as fatal).
    """

    def __init__(self, path: str):
        self.path = path
        size = os.path.getsize(path)
        if size % NEEDLE_MAP_ENTRY_SIZE != 0:
            raise ValueError(f"{path}: corrupt sorted index (partial record)")
        self.count = size // NEEDLE_MAP_ENTRY_SIZE
        # Only the 8-byte id column stays resident for the binary search;
        # full 16-byte entries are pread on demand, so a sealed index of
        # tens of millions of needles costs 8B/needle of RAM, not 16B+file.
        raw = np.fromfile(path, dtype=np.uint8).reshape(self.count, NEEDLE_MAP_ENTRY_SIZE)
        # NATIVE byte order: searchsorted over a big-endian view takes
        # numpy's slow non-native comparison path (~300 us/lookup at
        # 200k entries, measured); converting once at load makes the
        # binary search ~1 us
        self._ids = np.ascontiguousarray(
            raw[:, :8].copy().view(">u8").reshape(self.count),
            dtype=np.uint64,
        )
        self._fd = os.open(path, os.O_RDONLY)

    def _entry(self, i: int) -> NeedleValue:
        b = os.pread(self._fd, NEEDLE_MAP_ENTRY_SIZE, i * NEEDLE_MAP_ENTRY_SIZE)
        return NeedleValue.from_bytes(b)

    def get(self, needle_id: int) -> Optional[NeedleValue]:
        # np.uint64 scalar, NOT a Python int: comparing uint64 cells
        # against a Python int routes searchsorted through a ~200 us
        # casting slow path (measured); the typed scalar is ~2 us
        i = int(np.searchsorted(self._ids, np.uint64(needle_id)))
        if i >= self.count or int(self._ids[i]) != needle_id:
            return None
        return self._entry(i)

    def ascending_visit(self) -> Iterator[NeedleValue]:
        for i in range(self.count):
            yield self._entry(i)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __len__(self) -> int:
        return self.count
