"""Cross-cluster filer sync: replay one filer's namespace onto another.

Reference: weed/command/filer_sync.go + weed/replication (replicator
core + filersink) — event-driven continuous sync with an initial full
copy. Content is re-uploaded through the target filer (fids are
cluster-local, only bytes travel).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse

import requests

from ..utils.retry import Backoff, RetryPolicy
from ..utils.urls import service_url

# Unified tail-retry schedule (utils/retry.py): quick first retry while
# a filer restarts, 30s tail so a long outage doesn't hammer it. Shared
# by FilerSync / FilerBackup / S3Sink.
TAIL_RETRY_POLICY = RetryPolicy(
    max_attempts=7, base_delay=0.5, max_delay=30.0
)


class FilerSync:
    def __init__(
        self,
        source: str,
        target: str,
        path_prefix: str = "/",
        state_file: str = "",
        exclude_prefixes: tuple = ("/topics",),
    ):
        self.source = source
        self.target = target
        self.prefix = path_prefix.rstrip("/") or "/"
        self.exclude = exclude_prefixes
        self.state_file = state_file
        self.watermark = 0
        if state_file and os.path.exists(state_file):
            try:
                self.watermark = json.load(open(state_file))["sinceNs"]
            except (ValueError, KeyError, OSError):
                pass
        self._http = requests.Session()
        self._stop = threading.Event()
        self.synced_files = 0
        self.deleted_files = 0

    # ------------------------------------------------------------ helpers

    def _src(self, path: str) -> str:
        return service_url(self.source, urllib.parse.quote(path))

    def _dst(self, path: str) -> str:
        return service_url(self.target, urllib.parse.quote(path))

    @staticmethod
    def _under(path: str, prefix: str) -> bool:
        """Subtree membership with a path boundary: '/docs' covers
        '/docs/x' but NOT '/docs-archive/x'."""
        return path == prefix or path.startswith(prefix.rstrip("/") + "/")

    def _in_scope(self, path: str) -> bool:
        if any(self._under(path, x) for x in self.exclude):
            return False
        return self.prefix == "/" or self._under(path, self.prefix)

    def _save_state(self) -> None:
        if self.state_file:
            with open(self.state_file, "w") as f:
                json.dump({"sinceNs": self.watermark}, f)

    # --------------------------------------------------------- full copy

    def full_sync(self) -> int:
        """Initial walk: copy every in-scope file source -> target."""
        from ..client.filer_client import list_dir

        copied = 0
        stack = [self.prefix if self.prefix != "/" else "/"]
        while stack:
            d = stack.pop()
            for e in list_dir(self.source, d, session=self._http):
                path = e["FullPath"]
                if not self._in_scope(path):
                    continue
                if e["IsDirectory"]:
                    self._http.post(self._dst(path) + "?mkdir=true", timeout=30)
                    stack.append(path)
                else:
                    if self._copy_file(path, e.get("Mime", "")):
                        copied += 1
        return copied

    def _copy_file(self, path: str, mime: str) -> bool:
        r = self._http.get(self._src(path), timeout=300)
        if r.status_code != 200:
            return False
        put = self._http.post(
            self._dst(path),
            data=r.content,
            headers={"Content-Type": mime or r.headers.get("Content-Type", "")},
            timeout=300,
        )
        if put.ok:
            self.synced_files += 1
            return True
        return False

    # -------------------------------------------------------------- tail

    def apply_event(self, ev: dict) -> None:
        directory = ev.get("directory", "")
        old, new = ev.get("oldEntry"), ev.get("newEntry")
        if new:
            path = f"{directory.rstrip('/')}/{new['name']}" if new["name"] else directory
            if not self._in_scope(path):
                return
            if new["isDirectory"]:
                self._http.post(self._dst(path) + "?mkdir=true", timeout=30)
            else:
                self._copy_file(path, "")
        elif old:
            path = f"{directory.rstrip('/')}/{old['name']}" if old["name"] else directory
            if not self._in_scope(path):
                return
            r = self._http.delete(self._dst(path) + "?recursive=true", timeout=60)
            if r.status_code in (200, 204):
                self.deleted_files += 1

    def _source_now_ns(self) -> int:
        """The SOURCE filer's clock (watermarks must never mix clocks —
        skew would skip events emitted during the full copy)."""
        r = self._http.get(
            service_url(self.source, "/~meta/tail"),
            params={"sinceNs": str(1 << 62), "waitSeconds": "0"},
            timeout=30,
        )
        r.raise_for_status()
        return int(r.json().get("nowNs", 0)) or time.time_ns()

    def tail_once(self, wait_seconds: float = 10.0) -> int:
        r = self._http.get(
            service_url(self.source, "/~meta/tail"),
            params={
                "sinceNs": str(self.watermark),
                "waitSeconds": str(wait_seconds),
            },
            timeout=wait_seconds + 30,
        )
        r.raise_for_status()
        body = r.json()
        dropped_before = int(body.get("droppedBeforeTsNs", 0))
        if 0 < self.watermark < dropped_before:
            # events up to dropped_before were rotated away: deletions in
            # the gap are unrecoverable from the log — full resync
            # (reference SubscribeMetadata errors for the same reason)
            print(
                f"meta log gap (watermark {self.watermark} < dropped-before "
                f"{dropped_before}); running full resync",
                flush=True,
            )
            self.watermark = self._source_now_ns() - 1
            self.full_sync()
            self._save_state()
            return 0
        for ev in body.get("events", []):
            self.apply_event(ev)
            self.watermark = max(self.watermark, ev.get("tsNs", 0))
        self._save_state()
        return len(body.get("events", []))

    def run(self) -> None:
        if self.watermark == 0:
            # watermark (in the SOURCE's clock) BEFORE the walk so events
            # racing the copy replay afterwards
            self.watermark = self._source_now_ns() - 1
            n = self.full_sync()
            print(f"initial sync: {n} files copied", flush=True)
            self._save_state()
        backoff = Backoff(TAIL_RETRY_POLICY)
        while not self._stop.is_set():
            try:
                self.tail_once()
                backoff.reset()
            except requests.RequestException:
                self._stop.wait(backoff.next_delay())

    def stop(self) -> None:
        self._stop.set()
