"""Continuous filer -> local-directory backup (reference `weed
filer.backup`, weed/command/filer_backup.go): an initial full copy of
the watched path, then the filer meta-event tail applied to a local
tree — adds, updates, deletes, and directory ops — with a persisted
watermark so restarts resume instead of recopying.

Shares FilerSync's semantics (same tail endpoint, same
gap-means-full-resync rule); the sink is the local filesystem instead
of a second filer.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time

import requests

from ..utils.retry import Backoff
from ..utils.urls import service_url
from .sync import TAIL_RETRY_POLICY


class FilerBackup:
    def __init__(
        self,
        source: str,
        dest_dir: str,
        path: str = "/",
        state_path: str = "filer.backup.state",
    ):
        self.source = source
        self.dest_dir = os.path.abspath(dest_dir)
        self.path = path.rstrip("/") or "/"
        self.state_path = state_path
        self.watermark = 0
        self.copied_files = 0
        self.deleted_files = 0
        self._http = requests.Session()
        self._stop = threading.Event()
        os.makedirs(self.dest_dir, exist_ok=True)
        if os.path.exists(state_path):
            try:
                with open(state_path) as f:
                    self.watermark = int(json.load(f)["watermark"])
            except (ValueError, KeyError, OSError):
                self.watermark = 0

    # ----------------------------------------------------------- helpers

    def _src(self, path: str) -> str:
        return service_url(self.source, path)

    def _local(self, path: str) -> str:
        rel = path[len(self.path) :].lstrip("/") if self.path != "/" else path.lstrip("/")
        out = os.path.abspath(os.path.join(self.dest_dir, rel))
        # a hostile path ('..') must never escape the backup root
        if out != self.dest_dir and not out.startswith(self.dest_dir + os.sep):
            raise ValueError(f"path {path!r} escapes the backup dir")
        return out

    def _in_scope(self, path: str) -> bool:
        return self.path == "/" or path == self.path or path.startswith(
            self.path + "/"
        )

    def _save_state(self) -> None:
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"watermark": self.watermark}, f)
        os.replace(tmp, self.state_path)

    # ------------------------------------------------------------- copy

    def _copy_file(self, path: str) -> bool:
        r = self._http.get(self._src(path), timeout=300)
        if r.status_code != 200:
            return False
        local = self._local(path)
        os.makedirs(os.path.dirname(local), exist_ok=True)
        tmp = local + ".part"
        with open(tmp, "wb") as f:
            f.write(r.content)
        os.replace(tmp, local)
        self.copied_files += 1
        return True

    def full_sync(self) -> int:
        n = 0
        stack = [self.path]
        while stack:
            d = stack.pop()
            r = self._http.get(
                self._src(d),
                headers={"Accept": "application/json"},
                timeout=60,
            )
            if r.status_code != 200:
                continue
            for e in r.json().get("Entries") or []:
                p = e["FullPath"]
                if e.get("IsDirectory"):
                    os.makedirs(self._local(p), exist_ok=True)
                    stack.append(p)
                elif self._copy_file(p):
                    n += 1
        return n

    # ------------------------------------------------------------- tail

    def apply_event(self, ev: dict) -> None:
        directory = ev.get("directory", "")
        old, new = ev.get("oldEntry"), ev.get("newEntry")
        if new:
            path = (
                f"{directory.rstrip('/')}/{new['name']}"
                if new["name"]
                else directory
            )
            if not self._in_scope(path):
                return
            old_path = (
                f"{directory.rstrip('/')}/{old['name']}"
                if old and old.get("name")
                else ""
            )
            if old_path and old_path != path and self._in_scope(old_path):
                # rename: move locally instead of re-downloading
                try:
                    os.replace(self._local(old_path), self._local(path))
                    return
                except OSError:
                    pass  # fall through to a fresh copy
            if new["isDirectory"]:
                os.makedirs(self._local(path), exist_ok=True)
            else:
                self._copy_file(path)
        elif old:
            path = (
                f"{directory.rstrip('/')}/{old['name']}"
                if old["name"]
                else directory
            )
            if not self._in_scope(path):
                return
            local = self._local(path)
            try:
                if os.path.isdir(local):
                    shutil.rmtree(local, ignore_errors=True)
                else:
                    os.unlink(local)
                self.deleted_files += 1
            except FileNotFoundError:
                pass

    def _source_now_ns(self) -> int:
        r = self._http.get(
            self._src("/~meta/tail"),
            params={"sinceNs": str(1 << 62), "waitSeconds": "0"},
            timeout=30,
        )
        r.raise_for_status()
        return int(r.json().get("nowNs", 0)) or time.time_ns()

    def tail_once(self, wait_seconds: float = 10.0) -> int:
        r = self._http.get(
            self._src("/~meta/tail"),
            params={
                "sinceNs": str(self.watermark),
                "waitSeconds": str(wait_seconds),
            },
            timeout=wait_seconds + 30,
        )
        r.raise_for_status()
        body = r.json()
        dropped_before = int(body.get("droppedBeforeTsNs", 0))
        if 0 < self.watermark < dropped_before:
            # deletions in the rotated-away gap are unrecoverable from
            # the log: full resync (same rule as FilerSync)
            self.watermark = self._source_now_ns() - 1
            self.full_sync()
            self._save_state()
            return 0
        for ev in body.get("events", []):
            self.apply_event(ev)
            self.watermark = max(self.watermark, ev.get("tsNs", 0))
        self._save_state()
        return len(body.get("events", []))

    def run(self) -> None:
        if self.watermark == 0:
            self.watermark = self._source_now_ns() - 1
            n = self.full_sync()
            print(f"initial backup: {n} files copied", flush=True)
            self._save_state()
        backoff = Backoff(TAIL_RETRY_POLICY)
        while not self._stop.is_set():
            try:
                self.tail_once()
                backoff.reset()
            except requests.RequestException:
                self._stop.wait(backoff.next_delay())

    def stop(self) -> None:
        self._stop.set()
