"""Cross-cluster replication (filer.sync analog)."""

from .sync import FilerSync
