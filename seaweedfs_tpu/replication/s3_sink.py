"""Cloud sink: continuous filer → S3-compatible bucket replication.

Reference: weed/replication/sink/s3sink — the same source plumbing as
the filer→filer daemon (full walk, then meta-log tail with a persisted
watermark) but the write side is a RemoteS3Client, so any filer subtree
mirrors into a bucket/prefix on this framework's own S3 gateway or any
S3 endpoint.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse

import requests

from ..remote.s3_client import RemoteS3Client, RemoteStorageError
from ..utils.glog import logger
from ..utils.retry import Backoff
from ..utils.urls import service_url
from .sync import TAIL_RETRY_POLICY

log = logger("s3sink")


class S3Sink:
    def __init__(
        self,
        source: str,
        client: RemoteS3Client,
        bucket: str,
        key_prefix: str = "",
        path_prefix: str = "/",
        state_file: str = "",
        exclude_prefixes: tuple = ("/topics", "/.tus", "/.uploads"),
    ):
        self.source = source
        self.client = client
        self.bucket = bucket
        self.key_prefix = key_prefix.strip("/")
        self.prefix = path_prefix.rstrip("/") or "/"
        self.exclude = exclude_prefixes
        self.state_file = state_file
        self.watermark = 0
        if state_file and os.path.exists(state_file):
            try:
                self.watermark = json.load(open(state_file))["sinceNs"]
            except (ValueError, KeyError, OSError):
                pass
        self._http = requests.Session()
        self._stop = threading.Event()
        self.synced_files = 0
        self.deleted_files = 0

    # ------------------------------------------------------------ helpers

    def _key(self, path: str) -> str:
        rel = path
        if self.prefix != "/" and path.startswith(self.prefix):
            rel = path[len(self.prefix) :]
        rel = rel.lstrip("/")
        return f"{self.key_prefix}/{rel}".strip("/")

    @staticmethod
    def _under(path: str, prefix: str) -> bool:
        return path == prefix or path.startswith(prefix.rstrip("/") + "/")

    def _in_scope(self, path: str) -> bool:
        if any(self._under(path, x) for x in self.exclude):
            return False
        return self.prefix == "/" or self._under(path, self.prefix)

    def _save_state(self) -> None:
        if self.state_file:
            with open(self.state_file, "w") as f:
                json.dump({"sinceNs": self.watermark}, f)

    def _copy(self, path: str) -> bool:
        r = self._http.get(
            service_url(self.source, urllib.parse.quote(path)), timeout=300
        )
        if r.status_code != 200:
            return False
        try:
            self.client.put_object(self.bucket, self._key(path), r.content)
        except RemoteStorageError as e:
            log.warning("put %s: %s", path, e)
            return False
        self.synced_files += 1
        return True

    # ------------------------------------------------------------- phases

    def full_sync(self) -> int:
        from ..client.filer_client import list_dir

        self.client.ensure_bucket(self.bucket)
        copied = 0
        stack = [self.prefix]
        while stack:
            d = stack.pop()
            for e in list_dir(self.source, d, session=self._http):
                path = e["FullPath"]
                if not self._in_scope(path):
                    continue
                if e["IsDirectory"]:
                    stack.append(path)  # S3 has no directories
                elif self._copy(path):
                    copied += 1
        return copied

    def apply_event(self, ev: dict) -> None:
        directory = ev.get("directory", "")
        old, new = ev.get("oldEntry"), ev.get("newEntry")
        if new:
            path = (
                f"{directory.rstrip('/')}/{new['name']}"
                if new["name"]
                else directory
            )
            if self._in_scope(path) and not new["isDirectory"]:
                self._copy(path)
        elif old:
            path = (
                f"{directory.rstrip('/')}/{old['name']}"
                if old["name"]
                else directory
            )
            if not self._in_scope(path):
                return
            try:
                self.client.delete_object(self.bucket, self._key(path))
                self.deleted_files += 1
            except RemoteStorageError as e:
                log.warning("delete %s: %s", path, e)

    def tail_once(self, wait_seconds: float = 10.0) -> int:
        r = self._http.get(
            service_url(self.source, "/~meta/tail"),
            params={
                "sinceNs": str(self.watermark),
                "waitSeconds": str(wait_seconds),
            },
            timeout=wait_seconds + 30,
        )
        r.raise_for_status()
        payload = r.json()
        events = payload.get("events", [])
        for ev in events:
            self.apply_event(ev)
            self.watermark = max(self.watermark, int(ev.get("tsNs", 0)))
        if events:
            self._save_state()
        return len(events)

    def run(self) -> None:
        if self.watermark == 0:
            # watermark BEFORE the walk: events during the copy replay
            self.watermark = self._source_now_ns()
            n = self.full_sync()
            log.info("initial copy: %d files -> s3://%s", n, self.bucket)
            self._save_state()
        backoff = Backoff(TAIL_RETRY_POLICY)
        while not self._stop.is_set():
            try:
                self.tail_once()
                backoff.reset()
            except (requests.RequestException, ValueError) as e:
                log.warning("tail error: %s", e)
                self._stop.wait(backoff.next_delay())

    def _source_now_ns(self) -> int:
        r = self._http.get(
            service_url(self.source, "/~meta/tail"),
            params={"sinceNs": str(1 << 62), "waitSeconds": "0"},
            timeout=30,
        )
        r.raise_for_status()
        return int(r.json().get("nowNs", 0)) or time.time_ns()

    def stop(self) -> None:
        self._stop.set()
