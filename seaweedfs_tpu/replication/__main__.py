"""`python -m seaweedfs_tpu.replication` — continuous filer-to-filer sync.

  python -m seaweedfs_tpu.replication -from hostA:8888 -to hostB:8888 \
      [-path /buckets] [-state sync.state]
"""

from __future__ import annotations

import argparse
import signal
import sys

from .sync import FilerSync


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.replication")
    p.add_argument("-from", dest="source", required=True)
    p.add_argument("-to", dest="target", required=True)
    p.add_argument("-path", default="/")
    p.add_argument("-state", default="filer.sync.state")
    a = p.parse_args(argv)
    sync = FilerSync(a.source, a.target, a.path, a.state)
    signal.signal(signal.SIGTERM, lambda *x: sync.stop())
    signal.signal(signal.SIGINT, lambda *x: sync.stop())
    print(f"syncing {a.source}{a.path} -> {a.target}", flush=True)
    sync.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
