"""`python -m seaweedfs_tpu.replication` — continuous filer-to-filer sync.

  python -m seaweedfs_tpu.replication -from hostA:8888 -to hostB:8888 \
      [-path /buckets] [-state sync.state]
"""

from __future__ import annotations

import argparse
import signal
import sys

from .sync import FilerSync


def main(argv=None) -> int:
    # replication.toml supplies source/sink defaults (scaffold template)
    from ..utils.config import load_config

    rcfg = load_config("replication")
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.replication")
    p.add_argument(
        "-from", dest="source",
        default=rcfg.get_str("source.filer.address"),
    )
    p.add_argument(
        "-to", dest="target",
        default=rcfg.get_str("sink.filer.address"),
    )
    p.add_argument(
        "-path", default=rcfg.get_str("sink.filer.directory", "/") or "/"
    )
    p.add_argument("-state", default="filer.sync.state")
    a = p.parse_args(argv)
    if not a.source or not a.target:
        p.error("-from/-to required (or replication.toml source/sink)")
    sync = FilerSync(a.source, a.target, a.path, a.state)
    signal.signal(signal.SIGTERM, lambda *x: sync.stop())
    signal.signal(signal.SIGINT, lambda *x: sync.stop())
    print(f"syncing {a.source}{a.path} -> {a.target}", flush=True)
    sync.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
