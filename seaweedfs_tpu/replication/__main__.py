"""`python -m seaweedfs_tpu.replication` — continuous filer replication.

  python -m seaweedfs_tpu.replication -from hostA:8888 -to hostB:8888 \
      [-path /buckets] [-state sync.state]

Sinks by -to shape: another filer (host:port), a cloud bucket
(s3://endpoint/bucket[/prefix]), or a LOCAL DIRECTORY (absolute path
or file:// URL — the reference's `weed filer.backup`)."""

from __future__ import annotations

import argparse
import signal
import sys

from .sync import FilerSync


def main(argv=None) -> int:
    # replication.toml supplies source/sink defaults (scaffold template)
    from ..utils.config import load_config

    rcfg = load_config("replication")
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.replication")
    p.add_argument(
        "-from", dest="source",
        default=rcfg.get_str("source.filer.address"),
    )
    p.add_argument(
        "-to", dest="target",
        default=rcfg.get_str("sink.filer.address"),
    )
    p.add_argument(
        "-path", default=rcfg.get_str("sink.filer.directory", "/") or "/"
    )
    p.add_argument("-state", default="filer.sync.state")
    p.add_argument("-s3.accessKey", dest="s3_access", default="")
    p.add_argument("-s3.secretKey", dest="s3_secret", default="")
    a = p.parse_args(argv)
    if not a.source or not a.target:
        p.error("-from/-to required (or replication.toml source/sink)")
    if a.target.startswith("file://") or a.target.startswith("/"):
        from .backup import FilerBackup

        dest = a.target[len("file://") :] if a.target.startswith("file://") else a.target
        job = FilerBackup(
            a.source, dest, path=a.path,
            state_path=a.state
            if a.state != "filer.sync.state"
            else "filer.backup.state",
        )
        signal.signal(signal.SIGTERM, lambda *_: job.stop())
        signal.signal(signal.SIGINT, lambda *_: job.stop())
        print(
            f"filer.backup {a.source}{a.path} -> {dest}", flush=True
        )
        job.run()
        return 0
    if a.target.startswith("s3://"):
        # cloud sink: -to s3://endpoint-host:port/bucket[/key-prefix]
        from ..remote.s3_client import RemoteS3Client
        from .s3_sink import S3Sink

        rest = a.target[len("s3://") :]
        host, _, bucket_path = rest.partition("/")
        bucket, _, key_prefix = bucket_path.partition("/")
        if not bucket:
            p.error("s3 target needs s3://host:port/bucket[/prefix]")
        client = RemoteS3Client(
            endpoint=f"http://{host}",
            access_key=a.s3_access,
            secret_key=a.s3_secret,
        )
        sync = S3Sink(
            a.source, client, bucket,
            key_prefix=key_prefix, path_prefix=a.path, state_file=a.state,
        )
    else:
        sync = FilerSync(a.source, a.target, a.path, a.state)
    signal.signal(signal.SIGTERM, lambda *x: sync.stop())
    signal.signal(signal.SIGINT, lambda *x: sync.stop())
    print(f"syncing {a.source}{a.path} -> {a.target}", flush=True)
    sync.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
