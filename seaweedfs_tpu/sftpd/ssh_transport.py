"""SSH 2.0 transport (RFC 4253) — server and client roles.

One algorithm suite, chosen for clean mappings onto `cryptography`
primitives and universal client support:

  kex        curve25519-sha256 (RFC 8731)
  host key   ssh-ed25519
  cipher     aes128-ctr (both directions)
  mac        hmac-sha2-256
  compression none

The binary packet protocol, KEX, and key derivation follow RFC 4253;
re-keying is answered if the peer asks but never initiated.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import socket
import struct

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

VERSION_STRING = b"SSH-2.0-seaweedfs_tpu_0.2"

# message numbers (RFC 4253 / 4252 / 4254)
MSG_DISCONNECT = 1
MSG_IGNORE = 2
MSG_UNIMPLEMENTED = 3
MSG_DEBUG = 4
MSG_SERVICE_REQUEST = 5
MSG_SERVICE_ACCEPT = 6
MSG_KEXINIT = 20
MSG_NEWKEYS = 21
MSG_KEX_ECDH_INIT = 30
MSG_KEX_ECDH_REPLY = 31
MSG_USERAUTH_REQUEST = 50
MSG_USERAUTH_FAILURE = 51
MSG_USERAUTH_SUCCESS = 52
MSG_USERAUTH_BANNER = 53
MSG_GLOBAL_REQUEST = 80
MSG_REQUEST_SUCCESS = 81
MSG_REQUEST_FAILURE = 82
MSG_CHANNEL_OPEN = 90
MSG_CHANNEL_OPEN_CONFIRMATION = 91
MSG_CHANNEL_OPEN_FAILURE = 92
MSG_CHANNEL_WINDOW_ADJUST = 93
MSG_CHANNEL_DATA = 94
MSG_CHANNEL_EXTENDED_DATA = 95
MSG_CHANNEL_EOF = 96
MSG_CHANNEL_CLOSE = 97
MSG_CHANNEL_REQUEST = 98
MSG_CHANNEL_SUCCESS = 99
MSG_CHANNEL_FAILURE = 100

KEX_ALG = b"curve25519-sha256"
HOSTKEY_ALG = b"ssh-ed25519"
CIPHER_ALG = b"aes128-ctr"
MAC_ALG = b"hmac-sha2-256"
COMP_ALG = b"none"


class SshError(Exception):
    pass


# ------------------------------------------------------------- encoding


def sshstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def mpint(n: int) -> bytes:
    if n == 0:
        return sshstr(b"")
    b = n.to_bytes((n.bit_length() + 8) // 8, "big")  # leading 0 if MSB set
    return sshstr(b)


def namelist(*names: bytes) -> bytes:
    return sshstr(b",".join(names))


class PacketReader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def byte(self) -> int:
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def boolean(self) -> bool:
        return self.byte() != 0

    def u32(self) -> int:
        (v,) = struct.unpack_from(">I", self.buf, self.pos)
        self.pos += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from(">Q", self.buf, self.pos)
        self.pos += 8
        return v

    def string(self) -> bytes:
        n = self.u32()
        s = self.buf[self.pos : self.pos + n]
        self.pos += n
        return s

    def rest(self) -> bytes:
        return self.buf[self.pos :]


# ------------------------------------------------------------ transport


class SshTransport:
    """Packet layer over a connected socket; call kex_server()/
    kex_client() immediately after construction."""

    def __init__(self, sock: socket.socket, server_side: bool):
        self.sock = sock
        self.server_side = server_side
        self._send_seq = 0
        self._recv_seq = 0
        self._send_cipher = None
        self._recv_cipher = None
        self._send_mac_key = b""
        self._recv_mac_key = b""
        self.session_id = b""
        self._local_version = VERSION_STRING
        self._remote_version = b""

    # ---- version exchange ----

    def exchange_versions(self) -> None:
        self.sock.sendall(self._local_version + b"\r\n")
        line = b""
        while True:  # servers may send banner lines before the version
            line = self._read_line()
            if line.startswith(b"SSH-"):
                break
        self._remote_version = line
        if not line.startswith((b"SSH-2.0-", b"SSH-1.99-")):
            raise SshError(f"unsupported peer version {line!r}")

    def _read_line(self) -> bytes:
        out = b""
        while not out.endswith(b"\n"):
            c = self.sock.recv(1)
            if not c:
                raise SshError("peer closed during version exchange")
            out += c
            if len(out) > 1024:
                raise SshError("version line too long")
        return out.rstrip(b"\r\n")

    # ---- binary packet protocol ----

    def send_packet(self, payload: bytes) -> None:
        block = 16 if self._send_cipher else 8
        # padding so total (len+padlen+payload+pad) % block == 0, pad >= 4
        pad_len = block - ((5 + len(payload)) % block)
        if pad_len < 4:
            pad_len += block
        packet = (
            struct.pack(">IB", 1 + len(payload) + pad_len, pad_len)
            + payload
            + os.urandom(pad_len)
        )
        if self._send_cipher is None:
            self.sock.sendall(packet)
        else:
            mac = hmac_mod.new(
                self._send_mac_key,
                struct.pack(">I", self._send_seq) + packet,
                hashlib.sha256,
            ).digest()
            self.sock.sendall(self._send_cipher.update(packet) + mac)
        self._send_seq = (self._send_seq + 1) & 0xFFFFFFFF

    def recv_packet(self) -> bytes:
        if self._recv_cipher is None:
            head = self._read_exact(4)
            (n,) = struct.unpack(">I", head)
            if n > 1024 * 1024:
                raise SshError("packet too large")
            body = self._read_exact(n)
            pad = body[0]
            payload = body[1 : n - pad]
        else:
            head = self._recv_cipher.update(self._read_exact(4))
            (n,) = struct.unpack(">I", head)
            if n > 1024 * 1024:
                raise SshError("packet too large")
            body = self._recv_cipher.update(self._read_exact(n))
            mac = self._read_exact(32)
            want = hmac_mod.new(
                self._recv_mac_key,
                struct.pack(">I", self._recv_seq) + head + body,
                hashlib.sha256,
            ).digest()
            if not hmac_mod.compare_digest(mac, want):
                raise SshError("MAC mismatch")
            pad = body[0]
            payload = body[1 : n - pad]
        self._recv_seq = (self._recv_seq + 1) & 0xFFFFFFFF
        return payload

    def recv_msg(self) -> bytes:
        """recv_packet, transparently handling IGNORE/DEBUG."""
        while True:
            p = self.recv_packet()
            if not p:
                continue
            if p[0] in (MSG_IGNORE, MSG_DEBUG):
                continue
            if p[0] == MSG_UNIMPLEMENTED:
                continue
            if p[0] == MSG_DISCONNECT:
                r = PacketReader(p[1:])
                code = r.u32()
                msg = r.string()
                raise SshError(f"peer disconnected ({code}): {msg.decode(errors='replace')}")
            return p

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise SshError("connection closed")
            buf += chunk
        return buf

    # ---- KEXINIT ----

    def _kexinit_payload(self) -> bytes:
        return (
            bytes([MSG_KEXINIT])
            + os.urandom(16)
            + namelist(KEX_ALG)
            + namelist(HOSTKEY_ALG)
            + namelist(CIPHER_ALG)
            + namelist(CIPHER_ALG)
            + namelist(MAC_ALG)
            + namelist(MAC_ALG)
            + namelist(COMP_ALG)
            + namelist(COMP_ALG)
            + namelist()  # languages c2s
            + namelist()  # languages s2c
            + b"\x00"  # first_kex_packet_follows
            + struct.pack(">I", 0)
        )

    @staticmethod
    def _check_kexinit(payload: bytes) -> None:
        r = PacketReader(payload)
        r.byte()
        r.pos += 16  # cookie
        lists = [r.string() for _ in range(10)]
        for i, ours in enumerate(
            (KEX_ALG, HOSTKEY_ALG, CIPHER_ALG, CIPHER_ALG, MAC_ALG, MAC_ALG,
             COMP_ALG, COMP_ALG)
        ):
            if ours not in lists[i].split(b","):
                raise SshError(
                    f"no common algorithm (slot {i}): "
                    f"peer offers {lists[i].decode()!r}"
                )

    # ---- key schedule ----

    def _derive(self, K: int, H: bytes, letter: bytes, length: int) -> bytes:
        out = hashlib.sha256(
            mpint(K) + H + letter + self.session_id
        ).digest()
        while len(out) < length:
            out += hashlib.sha256(mpint(K) + H + out).digest()
        return out[:length]

    def _activate(self, K: int, H: bytes) -> None:
        if not self.session_id:
            self.session_id = H
        if self.server_side:
            c2s_iv, s2c_iv = b"A", b"B"
            c2s_key, s2c_key = b"C", b"D"
            c2s_mac, s2c_mac = b"E", b"F"
            recv_iv = self._derive(K, H, c2s_iv, 16)
            recv_key = self._derive(K, H, c2s_key, 16)
            self._recv_mac_key = self._derive(K, H, c2s_mac, 32)
            send_iv = self._derive(K, H, s2c_iv, 16)
            send_key = self._derive(K, H, s2c_key, 16)
            self._send_mac_key = self._derive(K, H, s2c_mac, 32)
        else:
            send_iv = self._derive(K, H, b"A", 16)
            send_key = self._derive(K, H, b"C", 16)
            self._send_mac_key = self._derive(K, H, b"E", 32)
            recv_iv = self._derive(K, H, b"B", 16)
            recv_key = self._derive(K, H, b"D", 16)
            self._recv_mac_key = self._derive(K, H, b"F", 32)
        self._send_cipher = Cipher(
            algorithms.AES(send_key), modes.CTR(send_iv)
        ).encryptor()
        self._recv_cipher = Cipher(
            algorithms.AES(recv_key), modes.CTR(recv_iv)
        ).decryptor()

    # ---- server-side KEX ----

    def kex_server(self, host_key: Ed25519PrivateKey) -> None:
        self.exchange_versions()
        I_S = self._kexinit_payload()
        self.send_packet(I_S)
        I_C = self.recv_msg()
        if I_C[0] != MSG_KEXINIT:
            raise SshError(f"expected KEXINIT, got {I_C[0]}")
        self._kex_server_rounds(host_key, I_S, I_C)

    def rekey_server(
        self, host_key: Ed25519PrivateKey, their_kexinit: bytes
    ) -> None:
        """Answer a client-initiated re-key (OpenSSH re-keys every few
        GB): same exchange as the initial KEX but the session id stays
        pinned to the first H (RFC 4253 §7.2)."""
        self._check_kexinit(their_kexinit)
        I_S = self._kexinit_payload()
        self.send_packet(I_S)
        self._kex_server_rounds(host_key, I_S, their_kexinit)

    def _kex_server_rounds(
        self, host_key: Ed25519PrivateKey, I_S: bytes, I_C: bytes
    ) -> None:
        self._check_kexinit(I_C)
        pkt = self.recv_msg()
        if pkt[0] != MSG_KEX_ECDH_INIT:
            raise SshError(f"expected KEX_ECDH_INIT, got {pkt[0]}")
        q_c = PacketReader(pkt[1:]).string()
        eph = X25519PrivateKey.generate()
        q_s = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        shared = eph.exchange(X25519PublicKey.from_public_bytes(q_c))
        K = int.from_bytes(shared, "big")
        pub = host_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        K_S = sshstr(HOSTKEY_ALG) + sshstr(pub)
        H = hashlib.sha256(
            sshstr(self._remote_version)
            + sshstr(self._local_version)
            + sshstr(I_C)
            + sshstr(I_S)
            + sshstr(K_S)
            + sshstr(q_c)
            + sshstr(q_s)
            + mpint(K)
        ).digest()
        sig = sshstr(HOSTKEY_ALG) + sshstr(host_key.sign(H))
        self.send_packet(
            bytes([MSG_KEX_ECDH_REPLY])
            + sshstr(K_S)
            + sshstr(q_s)
            + sshstr(sig)
        )
        self.send_packet(bytes([MSG_NEWKEYS]))
        pkt = self.recv_msg()
        if pkt[0] != MSG_NEWKEYS:
            raise SshError(f"expected NEWKEYS, got {pkt[0]}")
        self._activate(K, H)

    # ---- client-side KEX ----

    def kex_client(self) -> bytes:
        """Returns the server's raw ed25519 host public key (for
        known-hosts pinning by the caller)."""
        self.exchange_versions()
        I_C = self._kexinit_payload()
        self.send_packet(I_C)
        I_S = self.recv_msg()
        if I_S[0] != MSG_KEXINIT:
            raise SshError(f"expected KEXINIT, got {I_S[0]}")
        return self._kex_client_rounds(I_C, I_S)

    def rekey_client(self) -> bytes:
        """Initiate a re-key mid-session (what OpenSSH does every few
        GB); session id stays pinned to the first exchange hash."""
        I_C = self._kexinit_payload()
        self.send_packet(I_C)
        I_S = self.recv_msg()
        if I_S[0] != MSG_KEXINIT:
            raise SshError(f"expected KEXINIT (rekey), got {I_S[0]}")
        return self._kex_client_rounds(I_C, I_S)

    def _kex_client_rounds(self, I_C: bytes, I_S: bytes) -> bytes:
        self._check_kexinit(I_S)
        eph = X25519PrivateKey.generate()
        q_c = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        self.send_packet(bytes([MSG_KEX_ECDH_INIT]) + sshstr(q_c))
        pkt = self.recv_msg()
        if pkt[0] != MSG_KEX_ECDH_REPLY:
            raise SshError(f"expected KEX_ECDH_REPLY, got {pkt[0]}")
        r = PacketReader(pkt[1:])
        K_S = r.string()
        q_s = r.string()
        sig_blob = r.string()
        shared = eph.exchange(X25519PublicKey.from_public_bytes(q_s))
        K = int.from_bytes(shared, "big")
        H = hashlib.sha256(
            sshstr(self._local_version)
            + sshstr(self._remote_version)
            + sshstr(I_C)
            + sshstr(I_S)
            + sshstr(K_S)
            + sshstr(q_c)
            + sshstr(q_s)
            + mpint(K)
        ).digest()
        ks = PacketReader(K_S)
        alg = ks.string()
        if alg != HOSTKEY_ALG:
            raise SshError(f"unexpected host key algorithm {alg!r}")
        host_pub = ks.string()
        sr = PacketReader(sig_blob)
        if sr.string() != HOSTKEY_ALG:
            raise SshError("bad signature algorithm")
        Ed25519PublicKey.from_public_bytes(host_pub).verify(sr.string(), H)
        self.send_packet(bytes([MSG_NEWKEYS]))
        pkt = self.recv_msg()
        if pkt[0] != MSG_NEWKEYS:
            raise SshError(f"expected NEWKEYS, got {pkt[0]}")
        self._activate(K, H)
        return host_pub
