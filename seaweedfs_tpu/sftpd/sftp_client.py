"""Minimal SFTP v3 client over the in-repo SSH transport (for tests
and tooling — the counterpart of sftp_server, in the role paramiko
would play if it were available)."""

from __future__ import annotations

import socket
import struct

from .sftp_server import (
    FX_EOF,
    FX_OK,
    FXP_ATTRS,
    FXP_CLOSE,
    FXP_DATA,
    FXP_HANDLE,
    FXP_INIT,
    FXP_LSTAT,
    FXP_MKDIR,
    FXP_NAME,
    FXP_OPEN,
    FXP_OPENDIR,
    FXP_READ,
    FXP_READDIR,
    FXP_REALPATH,
    FXP_REMOVE,
    FXP_RENAME,
    FXP_RMDIR,
    FXP_STAT,
    FXP_STATUS,
    FXP_VERSION,
    FXP_WRITE,
    FXF_CREAT,
    FXF_READ,
    FXF_TRUNC,
    FXF_WRITE,
)
from .ssh_transport import (
    MSG_CHANNEL_CLOSE,
    MSG_CHANNEL_DATA,
    MSG_CHANNEL_EOF,
    MSG_CHANNEL_OPEN,
    MSG_CHANNEL_OPEN_CONFIRMATION,
    MSG_CHANNEL_REQUEST,
    MSG_CHANNEL_SUCCESS,
    MSG_CHANNEL_WINDOW_ADJUST,
    MSG_SERVICE_ACCEPT,
    MSG_SERVICE_REQUEST,
    MSG_USERAUTH_REQUEST,
    MSG_USERAUTH_SUCCESS,
    PacketReader,
    SshError,
    SshTransport,
    sshstr,
)


class SftpStatusError(Exception):
    def __init__(self, code: int, message: str):
        self.code = code
        super().__init__(f"sftp status {code}: {message}")


class SftpClient:
    def __init__(
        self, host: str, port: int, user: str, password: str
    ):
        self._sock = socket.create_connection((host, port), timeout=30)
        self.t = SshTransport(self._sock, server_side=False)
        self.host_public_key = self.t.kex_client()
        self._auth(user, password)
        self._open_channel()
        self._rid = 0
        self._inbuf = b""
        v = self._sftp_rpc(bytes([FXP_INIT]) + struct.pack(">I", 3))
        if v[0] != FXP_VERSION:
            raise SshError("no SFTP version response")

    # ---- ssh plumbing ----

    def _auth(self, user: str, password: str) -> None:
        self.t.send_packet(
            bytes([MSG_SERVICE_REQUEST]) + sshstr(b"ssh-userauth")
        )
        pkt = self.t.recv_msg()
        if pkt[0] != MSG_SERVICE_ACCEPT:
            raise SshError("service not accepted")
        self.t.send_packet(
            bytes([MSG_USERAUTH_REQUEST])
            + sshstr(user.encode())
            + sshstr(b"ssh-connection")
            + sshstr(b"password")
            + b"\x00"
            + sshstr(password.encode())
        )
        pkt = self.t.recv_msg()
        if pkt[0] != MSG_USERAUTH_SUCCESS:
            raise SshError("authentication failed")

    def _open_channel(self) -> None:
        self.t.send_packet(
            bytes([MSG_CHANNEL_OPEN])
            + sshstr(b"session")
            + struct.pack(">III", 0, 1 << 30, 1 << 15)
        )
        pkt = self.t.recv_msg()
        if pkt[0] != MSG_CHANNEL_OPEN_CONFIRMATION:
            raise SshError("channel open failed")
        r = PacketReader(pkt[1:])
        r.u32()  # our id echoed
        self.peer = r.u32()
        self.t.send_packet(
            bytes([MSG_CHANNEL_REQUEST])
            + struct.pack(">I", self.peer)
            + sshstr(b"subsystem")
            + b"\x01"
            + sshstr(b"sftp")
        )
        pkt = self.t.recv_msg()
        if pkt[0] != MSG_CHANNEL_SUCCESS:
            raise SshError("sftp subsystem refused")

    def _sftp_rpc(self, body: bytes) -> bytes:
        self.t.send_packet(
            bytes([MSG_CHANNEL_DATA])
            + struct.pack(">I", self.peer)
            + sshstr(struct.pack(">I", len(body)) + body)
        )
        while True:
            if len(self._inbuf) >= 4:
                (n,) = struct.unpack(">I", self._inbuf[:4])
                if len(self._inbuf) >= 4 + n:
                    resp = self._inbuf[4 : 4 + n]
                    self._inbuf = self._inbuf[4 + n :]
                    return resp
            pkt = self.t.recv_msg()
            if pkt[0] == MSG_CHANNEL_DATA:
                r = PacketReader(pkt[1:])
                r.u32()
                self._inbuf += r.string()
            elif pkt[0] in (MSG_CHANNEL_WINDOW_ADJUST, MSG_CHANNEL_EOF):
                continue
            elif pkt[0] == MSG_CHANNEL_CLOSE:
                raise SshError("channel closed")

    def _rpc(self, kind: int, payload: bytes) -> tuple[int, PacketReader]:
        self._rid += 1
        rid = self._rid
        resp = self._sftp_rpc(
            bytes([kind]) + struct.pack(">I", rid) + payload
        )
        r = PacketReader(resp[1:])
        got = r.u32()
        if got != rid:
            raise SshError(f"request id mismatch {got} != {rid}")
        return resp[0], r

    @staticmethod
    def _raise_status(r: PacketReader) -> None:
        code = r.u32()
        msg = r.string().decode()
        if code not in (FX_OK,):
            raise SftpStatusError(code, msg)

    # ---- operations ----

    def realpath(self, path: str) -> str:
        kind, r = self._rpc(FXP_REALPATH, sshstr(path.encode()))
        if kind != FXP_NAME:
            self._raise_status(r)
        r.u32()  # count
        return r.string().decode()

    def stat(self, path: str) -> dict:
        kind, r = self._rpc(FXP_STAT, sshstr(path.encode()))
        if kind != FXP_ATTRS:
            self._raise_status(r)
        return self._parse_attrs(r)

    def listdir(self, path: str) -> list[str]:
        kind, r = self._rpc(FXP_OPENDIR, sshstr(path.encode()))
        if kind != FXP_HANDLE:
            self._raise_status(r)
        handle = r.string()
        names: list[str] = []
        try:
            while True:
                kind, r = self._rpc(FXP_READDIR, sshstr(handle))
                if kind == FXP_STATUS:
                    code = r.u32()
                    if code == FX_EOF:
                        break
                    raise SftpStatusError(code, r.string().decode())
                count = r.u32()
                for _ in range(count):
                    names.append(r.string().decode())
                    r.string()  # longname
                    self._parse_attrs(r)
        finally:
            self._rpc(FXP_CLOSE, sshstr(handle))
        return names

    def write_file(self, path: str, data: bytes, chunk: int = 32768) -> None:
        kind, r = self._rpc(
            FXP_OPEN,
            sshstr(path.encode())
            + struct.pack(">I", FXF_WRITE | FXF_CREAT | FXF_TRUNC)
            + struct.pack(">I", 0),
        )
        if kind != FXP_HANDLE:
            self._raise_status(r)
        handle = r.string()
        try:
            for off in range(0, len(data), chunk) or [0]:
                kind, r = self._rpc(
                    FXP_WRITE,
                    sshstr(handle)
                    + struct.pack(">Q", off)
                    + sshstr(data[off : off + chunk]),
                )
                self._raise_status(r)
        finally:
            kind, r = self._rpc(FXP_CLOSE, sshstr(handle))
            self._raise_status(r)

    def read_file(self, path: str, chunk: int = 32768) -> bytes:
        kind, r = self._rpc(
            FXP_OPEN,
            sshstr(path.encode())
            + struct.pack(">I", FXF_READ)
            + struct.pack(">I", 0),
        )
        if kind != FXP_HANDLE:
            self._raise_status(r)
        handle = r.string()
        out = b""
        try:
            while True:
                kind, r = self._rpc(
                    FXP_READ,
                    sshstr(handle)
                    + struct.pack(">Q", len(out))
                    + struct.pack(">I", chunk),
                )
                if kind == FXP_STATUS:
                    code = r.u32()
                    if code == FX_EOF:
                        break
                    raise SftpStatusError(code, r.string().decode())
                out += r.string()
        finally:
            self._rpc(FXP_CLOSE, sshstr(handle))
        return out

    def mkdir(self, path: str) -> None:
        kind, r = self._rpc(
            FXP_MKDIR, sshstr(path.encode()) + struct.pack(">I", 0)
        )
        self._raise_status(r)

    def rmdir(self, path: str) -> None:
        kind, r = self._rpc(FXP_RMDIR, sshstr(path.encode()))
        self._raise_status(r)

    def remove(self, path: str) -> None:
        kind, r = self._rpc(FXP_REMOVE, sshstr(path.encode()))
        self._raise_status(r)

    def rename(self, old: str, new: str) -> None:
        kind, r = self._rpc(
            FXP_RENAME, sshstr(old.encode()) + sshstr(new.encode())
        )
        self._raise_status(r)

    def close(self) -> None:
        try:
            self.t.send_packet(
                bytes([MSG_CHANNEL_CLOSE]) + struct.pack(">I", self.peer)
            )
            self._sock.close()
        except (OSError, SshError):
            pass

    @staticmethod
    def _parse_attrs(r: PacketReader) -> dict:
        flags = r.u32()
        out: dict = {}
        if flags & 0x01:
            out["size"] = r.u64()
        if flags & 0x02:
            out["uid"], out["gid"] = r.u32(), r.u32()
        if flags & 0x04:
            out["permissions"] = r.u32()
        if flags & 0x08:
            out["atime"], out["mtime"] = r.u32(), r.u32()
        return out
