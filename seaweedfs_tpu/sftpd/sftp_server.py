"""SSH server + SFTP v3 subsystem over the filer namespace.

Reference: weed/sftpd/sftp_server.go + sftp_service.go — per-user
password auth, a home-directory jail, optional read-only users, and
the SFTP v3 operation set (open/read/write/close, opendir/readdir,
stat/lstat/fstat, setstat, mkdir/rmdir/remove/rename, realpath).

Writes accumulate per handle and publish to the filer on close (the
gateway pattern WebDAV uses); reads stream straight through the
filer's ranged read path.
"""

from __future__ import annotations

import socket
import stat as stat_mod
import struct
import threading
import time
from dataclasses import dataclass, field

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey

from ..filer.entry import new_entry, normalize_path
from ..filer.filer import Filer, FilerError
from ..filer.filer_store import NotFound
from ..utils.glog import logger
from .ssh_transport import (
    MSG_CHANNEL_CLOSE,
    MSG_CHANNEL_DATA,
    MSG_CHANNEL_EOF,
    MSG_CHANNEL_FAILURE,
    MSG_CHANNEL_OPEN,
    MSG_CHANNEL_OPEN_CONFIRMATION,
    MSG_CHANNEL_OPEN_FAILURE,
    MSG_CHANNEL_REQUEST,
    MSG_CHANNEL_SUCCESS,
    MSG_CHANNEL_WINDOW_ADJUST,
    MSG_KEXINIT,
    MSG_SERVICE_ACCEPT,
    MSG_SERVICE_REQUEST,
    MSG_USERAUTH_FAILURE,
    MSG_USERAUTH_REQUEST,
    MSG_USERAUTH_SUCCESS,
    PacketReader,
    SshError,
    SshTransport,
    sshstr,
)

log = logger("sftpd")

# SFTP v3 (draft-ietf-secsh-filexfer-02)
FXP_INIT = 1
FXP_VERSION = 2
FXP_OPEN = 3
FXP_CLOSE = 4
FXP_READ = 5
FXP_WRITE = 6
FXP_LSTAT = 7
FXP_FSTAT = 8
FXP_SETSTAT = 9
FXP_FSETSTAT = 10
FXP_OPENDIR = 11
FXP_READDIR = 12
FXP_REMOVE = 13
FXP_MKDIR = 14
FXP_RMDIR = 15
FXP_REALPATH = 16
FXP_STAT = 17
FXP_RENAME = 18
FXP_STATUS = 101
FXP_HANDLE = 102
FXP_DATA = 103
FXP_NAME = 104
FXP_ATTRS = 105

FX_OK = 0
FX_EOF = 1
FX_NO_SUCH_FILE = 2
FX_PERMISSION_DENIED = 3
FX_FAILURE = 4
FX_OP_UNSUPPORTED = 8

FXF_READ = 0x01
FXF_WRITE = 0x02
FXF_APPEND = 0x04
FXF_CREAT = 0x08
FXF_TRUNC = 0x10
FXF_EXCL = 0x20

ATTR_SIZE = 0x01
ATTR_UIDGID = 0x02
ATTR_PERMISSIONS = 0x04
ATTR_ACMODTIME = 0x08


@dataclass
class SftpUser:
    name: str
    password: str
    home: str = "/"
    read_only: bool = False


@dataclass
class _Handle:
    path: str
    is_dir: bool = False
    # file handles
    writable: bool = False
    append: bool = False
    buffer: bytearray | None = None
    entry: object = None
    dirty: bool = False
    # dir handles
    listing: list | None = None
    cursor: int = 0


class SftpServer:
    def __init__(
        self,
        filer: Filer,
        ip: str = "localhost",
        port: int = 2022,
        users: dict[str, SftpUser] | None = None,
        host_key: Ed25519PrivateKey | None = None,
    ):
        self.filer = filer
        self.users = users or {}
        self.host_key = host_key or Ed25519PrivateKey.generate()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((ip, port))
        self.ip = ip
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)

    @property
    def host_public_key(self) -> bytes:
        return self.host_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    # ---------------------------------------------------------- session

    def _serve(self, conn: socket.socket) -> None:
        try:
            t = SshTransport(conn, server_side=True)
            t.kex_server(self.host_key)
            user = self._authenticate(t)
            if user is None:
                return
            self._connection_loop(t, user)
        except (SshError, OSError, EOFError) as e:
            log.v(1, "sftp session ended: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _authenticate(self, t: SshTransport) -> SftpUser | None:
        pkt = t.recv_msg()
        if pkt[0] != MSG_SERVICE_REQUEST:
            raise SshError("expected SERVICE_REQUEST")
        svc = PacketReader(pkt[1:]).string()
        if svc != b"ssh-userauth":
            raise SshError(f"unexpected service {svc!r}")
        t.send_packet(bytes([MSG_SERVICE_ACCEPT]) + sshstr(svc))
        for _attempt in range(8):
            pkt = t.recv_msg()
            if pkt[0] != MSG_USERAUTH_REQUEST:
                raise SshError("expected USERAUTH_REQUEST")
            r = PacketReader(pkt[1:])
            username = r.string().decode()
            r.string()  # service
            method = r.string()
            if method == b"password":
                r.boolean()
                password = r.string().decode()
                u = self.users.get(username)
                if u is not None and u.password == password:
                    t.send_packet(bytes([MSG_USERAUTH_SUCCESS]))
                    return u
            # also advertises what we DO support on "none" probes
            t.send_packet(
                bytes([MSG_USERAUTH_FAILURE])
                + sshstr(b"password")
                + b"\x00"
            )
        return None

    def _connection_loop(self, t: SshTransport, user: SftpUser) -> None:
        channel_id = None
        peer_channel = None
        sftp = None
        inbuf = b""
        while True:
            pkt = t.recv_msg()
            kind = pkt[0]
            r = PacketReader(pkt[1:])
            if kind == MSG_KEXINIT:
                # client-initiated re-key (OpenSSH: every few GB)
                t.rekey_server(self.host_key, pkt)
                continue
            if kind == MSG_CHANNEL_OPEN:
                ctype = r.string()
                sender = r.u32()
                r.u32()  # window
                r.u32()  # max packet
                if ctype != b"session" or channel_id is not None:
                    t.send_packet(
                        bytes([MSG_CHANNEL_OPEN_FAILURE])
                        + struct.pack(">II", sender, 1)
                        + sshstr(b"only one session channel")
                        + sshstr(b"")
                    )
                    continue
                channel_id, peer_channel = 0, sender
                t.send_packet(
                    bytes([MSG_CHANNEL_OPEN_CONFIRMATION])
                    + struct.pack(
                        ">IIII", sender, channel_id, 1 << 30, 1 << 15
                    )
                )
            elif kind == MSG_CHANNEL_REQUEST:
                r.u32()  # our channel
                req = r.string()
                want_reply = r.boolean()
                ok = False
                if req == b"subsystem" and r.string() == b"sftp":
                    sftp = _SftpSession(self.filer, user)
                    ok = True
                if want_reply:
                    t.send_packet(
                        bytes(
                            [MSG_CHANNEL_SUCCESS if ok else MSG_CHANNEL_FAILURE]
                        )
                        + struct.pack(">I", peer_channel)
                    )
            elif kind == MSG_CHANNEL_DATA:
                r.u32()
                data = r.string()
                # replenish the flow-control window as we consume, or
                # uploads stall once the initial grant is spent
                t.send_packet(
                    bytes([MSG_CHANNEL_WINDOW_ADJUST])
                    + struct.pack(">II", peer_channel, len(data))
                )
                if sftp is None:
                    continue
                inbuf += data
                out = b""
                # sftp packets: u32 length + body
                while len(inbuf) >= 4:
                    (n,) = struct.unpack(">I", inbuf[:4])
                    if len(inbuf) < 4 + n:
                        break
                    body = inbuf[4 : 4 + n]
                    inbuf = inbuf[4 + n :]
                    resp = sftp.handle(body)
                    if resp is not None:
                        out += struct.pack(">I", len(resp)) + resp
                # chunk responses under the negotiated max packet size
                for i in range(0, len(out), 1 << 15):
                    t.send_packet(
                        bytes([MSG_CHANNEL_DATA])
                        + struct.pack(">I", peer_channel)
                        + sshstr(out[i : i + (1 << 15)])
                    )
            elif kind == MSG_CHANNEL_WINDOW_ADJUST:
                pass
            elif kind == MSG_CHANNEL_EOF:
                pass
            elif kind == MSG_CHANNEL_CLOSE:
                if sftp is not None:
                    sftp.close_all()
                t.send_packet(
                    bytes([MSG_CHANNEL_CLOSE])
                    + struct.pack(">I", peer_channel)
                )
                return


class _SftpSession:
    def __init__(self, filer: Filer, user: SftpUser):
        self.filer = filer
        self.user = user
        self.handles: dict[bytes, _Handle] = {}
        self._next = 0

    # ---- path jail ----

    def _resolve(self, raw: bytes) -> str:
        import posixpath

        p = raw.decode("utf-8", errors="replace")
        if not p or p == ".":
            p = "/"
        if not p.startswith("/"):
            p = "/" + p
        # collapse ./.. INSIDE the client's view first ("/.." == "/"),
        # then graft onto the home jail — dot segments can never climb
        # above the jail root
        p = posixpath.normpath(p)
        full = normalize_path(self.user.home.rstrip("/") + p)
        home = normalize_path(self.user.home)
        if home != "/" and not (full == home or full.startswith(home + "/")):
            full = home  # jailed: climbing out lands at home
        return full

    def _visible(self, full: str) -> str:
        home = normalize_path(self.user.home)
        if home == "/":
            return full
        if full == home:
            return "/"
        return full[len(home) :]

    # ---- dispatch ----

    def handle(self, body: bytes) -> bytes | None:
        kind = body[0]
        r = PacketReader(body[1:])
        if kind == FXP_INIT:
            return bytes([FXP_VERSION]) + struct.pack(">I", 3)
        rid = r.u32()
        try:
            return self._dispatch(kind, rid, r)
        except NotFound:
            return self._status(rid, FX_NO_SUCH_FILE, "no such file")
        except PermissionError as e:
            return self._status(rid, FX_PERMISSION_DENIED, str(e))
        except (FilerError, OSError, ValueError) as e:
            return self._status(rid, FX_FAILURE, str(e))

    def _dispatch(self, kind: int, rid: int, r: PacketReader) -> bytes:
        if kind == FXP_REALPATH:
            path = self._resolve(r.string())
            vis = self._visible(path) or "/"
            return (
                bytes([FXP_NAME])
                + struct.pack(">II", rid, 1)
                + sshstr(vis.encode())
                + sshstr(vis.encode())
                + self._attrs_absent()
            )
        if kind == FXP_STAT or kind == FXP_LSTAT:
            entry = self.filer.find_entry(self._resolve(r.string()))
            return bytes([FXP_ATTRS]) + struct.pack(">I", rid) + self._attrs(entry)
        if kind == FXP_FSTAT:
            h = self.handles.get(r.string())
            if h is None:
                return self._status(rid, FX_FAILURE, "bad handle")
            if h.buffer is not None:
                attrs = (
                    struct.pack(">I", ATTR_SIZE)
                    + struct.pack(">Q", len(h.buffer))
                )
                return bytes([FXP_ATTRS]) + struct.pack(">I", rid) + attrs
            entry = self.filer.find_entry(h.path)
            return bytes([FXP_ATTRS]) + struct.pack(">I", rid) + self._attrs(entry)
        if kind in (FXP_SETSTAT, FXP_FSETSTAT):
            # attribute changes (chmod/utimes) are accepted and ignored,
            # matching the reference's permissive default
            return self._status(rid, FX_OK, "ok")
        if kind == FXP_OPENDIR:
            path = self._resolve(r.string())
            entry = self.filer.find_entry(path)
            if not entry.is_directory:
                return self._status(rid, FX_FAILURE, "not a directory")
            h = self._new_handle(
                _Handle(
                    path=path,
                    is_dir=True,
                    listing=list(self.filer.list_entries(path, limit=100_000)),
                )
            )
            return bytes([FXP_HANDLE]) + struct.pack(">I", rid) + sshstr(h)
        if kind == FXP_READDIR:
            h = self.handles.get(r.string())
            if h is None or not h.is_dir:
                return self._status(rid, FX_FAILURE, "bad handle")
            if h.cursor >= len(h.listing):
                return self._status(rid, FX_EOF, "end of listing")
            batch = h.listing[h.cursor : h.cursor + 100]
            h.cursor += len(batch)
            out = bytes([FXP_NAME]) + struct.pack(">II", rid, len(batch))
            for e in batch:
                name = e.name.encode()
                out += sshstr(name) + sshstr(self._longname(e).encode())
                out += self._attrs(e)
            return out
        if kind == FXP_OPEN:
            return self._open(rid, r)
        if kind == FXP_READ:
            return self._read(rid, r)
        if kind == FXP_WRITE:
            return self._write(rid, r)
        if kind == FXP_CLOSE:
            return self._close(rid, r)
        if kind == FXP_REMOVE:
            self._check_writable()
            path = self._resolve(r.string())
            entry = self.filer.find_entry(path)
            if entry.is_directory:
                return self._status(rid, FX_FAILURE, "is a directory")
            self.filer.delete_entry(path)
            return self._status(rid, FX_OK, "removed")
        if kind == FXP_MKDIR:
            self._check_writable()
            path = self._resolve(r.string())
            self.filer.create_entry(
                new_entry(path, is_directory=True, mode=0o755)
            )
            return self._status(rid, FX_OK, "created")
        if kind == FXP_RMDIR:
            self._check_writable()
            path = self._resolve(r.string())
            entry = self.filer.find_entry(path)
            if not entry.is_directory:
                return self._status(rid, FX_FAILURE, "not a directory")
            self.filer.delete_entry(path)  # non-recursive: fails if non-empty
            return self._status(rid, FX_OK, "removed")
        if kind == FXP_RENAME:
            self._check_writable()
            old = self._resolve(r.string())
            new = self._resolve(r.string())
            self.filer.rename(old, new)
            return self._status(rid, FX_OK, "renamed")
        return self._status(rid, FX_OP_UNSUPPORTED, f"op {kind}")

    # ---- file io ----

    def _open(self, rid: int, r: PacketReader) -> bytes:
        path = self._resolve(r.string())
        pflags = r.u32()
        writable = bool(pflags & (FXF_WRITE | FXF_APPEND))
        if writable:
            self._check_writable()
        exists = self.filer.exists(path)
        if writable and (pflags & FXF_EXCL) and exists:
            return self._status(rid, FX_FAILURE, "exists")
        if not writable and not exists:
            return self._status(rid, FX_NO_SUCH_FILE, path)
        h = _Handle(path=path, writable=writable)
        if writable:
            if exists and not (pflags & FXF_TRUNC):
                entry = self.filer.find_entry(path)
                h.buffer = bytearray(self.filer.read_entry(entry))
            else:
                h.buffer = bytearray()
            h.append = bool(pflags & FXF_APPEND)
        else:
            h.entry = self.filer.find_entry(path)
        return (
            bytes([FXP_HANDLE])
            + struct.pack(">I", rid)
            + sshstr(self._new_handle(h))
        )

    def _read(self, rid: int, r: PacketReader) -> bytes:
        h = self.handles.get(r.string())
        offset = r.u64()
        length = min(r.u32(), 1 << 20)
        if h is None or h.is_dir:
            return self._status(rid, FX_FAILURE, "bad handle")
        if h.buffer is not None:
            data = bytes(h.buffer[offset : offset + length])
        else:
            data = self.filer.read_entry(h.entry, offset=offset, size=length)
        if not data:
            return self._status(rid, FX_EOF, "eof")
        return bytes([FXP_DATA]) + struct.pack(">I", rid) + sshstr(data)

    def _write(self, rid: int, r: PacketReader) -> bytes:
        h = self.handles.get(r.string())
        offset = r.u64()
        data = r.string()
        if h is None or not h.writable or h.buffer is None:
            return self._status(rid, FX_PERMISSION_DENIED, "not writable")
        if h.append:
            offset = len(h.buffer)
        end = offset + len(data)
        if end > len(h.buffer):
            h.buffer.extend(b"\x00" * (end - len(h.buffer)))
        h.buffer[offset:end] = data
        h.dirty = True
        return self._status(rid, FX_OK, "written")

    def _close(self, rid: int, r: PacketReader) -> bytes:
        hid = r.string()
        h = self.handles.pop(hid, None)
        if h is None:
            return self._status(rid, FX_FAILURE, "bad handle")
        if h.writable and h.buffer is not None and (h.dirty or not self.filer.exists(h.path)):
            self.filer.write_file(h.path, bytes(h.buffer))
        return self._status(rid, FX_OK, "closed")

    def close_all(self) -> None:
        for hid in list(self.handles):
            h = self.handles.pop(hid)
            if h.writable and h.buffer is not None and h.dirty:
                try:
                    self.filer.write_file(h.path, bytes(h.buffer))
                except FilerError:
                    pass

    # ---- helpers ----

    def _check_writable(self) -> None:
        if self.user.read_only:
            raise PermissionError(f"user {self.user.name} is read-only")

    def _new_handle(self, h: _Handle) -> bytes:
        hid = b"h%d" % self._next
        self._next += 1
        self.handles[hid] = h
        return hid

    @staticmethod
    def _status(rid: int, code: int, msg: str) -> bytes:
        return (
            bytes([FXP_STATUS])
            + struct.pack(">II", rid, code)
            + sshstr(msg.encode())
            + sshstr(b"en")
        )

    @staticmethod
    def _attrs_absent() -> bytes:
        return struct.pack(">I", 0)

    @staticmethod
    def _attrs(entry) -> bytes:
        flags = ATTR_SIZE | ATTR_PERMISSIONS | ATTR_ACMODTIME
        mode = entry.mode() or (0o755 if entry.is_directory else 0o644)
        if entry.is_directory:
            mode |= stat_mod.S_IFDIR
        else:
            mode |= stat_mod.S_IFREG
        mtime = entry.attr.mtime or int(time.time())
        return (
            struct.pack(">I", flags)
            + struct.pack(">Q", entry.file_size)
            + struct.pack(">I", mode)
            + struct.pack(">II", mtime, mtime)
        )

    def _longname(self, e) -> str:
        kind = "d" if e.is_directory else "-"
        return f"{kind}rw-r--r-- 1 sw sw {e.file_size:>10} Jan  1 00:00 {e.name}"
