"""SFTP gateway: an SSH server exposing the filer namespace.

Reference: weed/sftpd (sftp_server.go) — SSH/SFTP over the filer with
per-user permissions. The reference rides golang.org/x/crypto/ssh;
here the SSH transport itself is implemented on the `cryptography`
primitives (curve25519 kex, ed25519 host keys, aes128-ctr +
hmac-sha2-256), plus an SFTP v3 subsystem.
"""

from .sftp_server import SftpServer  # noqa: F401
