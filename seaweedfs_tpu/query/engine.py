"""SQL engine over MQ topics.

Reference: weed/query/engine/engine.go:553 (ExecuteSQL) +
hybrid_message_scanner.go — topics are tables; each record's
JSON-decoded value supplies the columns, plus the system columns
_key, _ts (ms), _offset, _partition. Statements:

  SHOW TABLES
  DESCRIBE <topic>
  SELECT <*|cols|aggregates> FROM <topic>
      [WHERE <expr>] [GROUP BY col, ...] [HAVING <expr>]
      [ORDER BY col [ASC|DESC], ...] [LIMIT n] [OFFSET n]

Aggregates: COUNT(*) COUNT(col) SUM MIN MAX AVG; WHERE supports
= != <> < <= > >= LIKE, AND/OR/NOT, parentheses, NULL literals.
Values that are not JSON objects appear as a single _value column.

Predicate pushdown: conjunctive _ts / _offset bounds prune whole
parquet-archived segments via their .stats.json sidecars WITHOUT
fetching the data; Result.stats reports segments_scanned /
segments_skipped / rows_scanned as the audit trail.

The engine is deliberately a hand-rolled recursive-descent parser over
a small grammar — the reference embeds a full cockroach SQL parser,
which is out of proportion here; the surface above covers the
reference's documented topic-query examples.
"""

from __future__ import annotations

import fnmatch
import json
import re
from dataclasses import dataclass, field
from typing import Any, Iterator

NAMESPACES = ("kafka", "default")


class QueryError(Exception):
    pass


# ------------------------------------------------------------ tokenizer

_TOKEN_RE = re.compile(
    r"""\s*(?:
      (?P<num>-?\d+\.\d+|-?\d+)
    | (?P<str>'(?:[^']|'')*')
    | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|\*|,|\.)
    | (?P<word>[A-Za-z_][A-Za-z0-9_\-]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "LIMIT", "OFFSET", "AND", "OR", "NOT",
    "LIKE", "SHOW", "TABLES", "TOPICS", "DESCRIBE", "DESC", "ASC",
    "ORDER", "BY", "AS", "NULL", "IS", "TRUE", "FALSE", "GROUP",
    "HAVING",
}


@dataclass
class Token:
    kind: str  # num | str | op | word | kw | end
    value: Any


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            rest = sql[pos:].strip()
            if not rest or rest.startswith(";"):
                break
            raise QueryError(f"syntax error near {rest[:20]!r}")
        pos = m.end()
        if m.group("num") is not None:
            n = m.group("num")
            out.append(Token("num", float(n) if "." in n else int(n)))
        elif m.group("str") is not None:
            out.append(Token("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("op") is not None:
            out.append(Token("op", m.group("op")))
        else:
            w = m.group("word")
            if w.upper() in _KEYWORDS:
                out.append(Token("kw", w.upper()))
            else:
                out.append(Token("word", w))
    out.append(Token("end", None))
    return out


# --------------------------------------------------------------- parser


@dataclass
class Select:
    columns: list  # ("col", name, alias) | ("agg", fn, arg, alias) | ("star",)
    table: str
    where: Any = None
    group_by: list[str] | None = None
    having: Any = None  # expr over output aliases
    order_by: list[tuple[str, bool]] | None = None  # [(col, descending)...]
    limit: int = -1
    offset: int = 0


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_kw(self, kw: str) -> None:
        t = self.next()
        if t.kind != "kw" or t.value != kw:
            raise QueryError(f"expected {kw}, got {t.value!r}")

    def accept_kw(self, kw: str) -> bool:
        if self.peek().kind == "kw" and self.peek().value == kw:
            self.i += 1
            return True
        return False

    def accept_op(self, op: str) -> bool:
        if self.peek().kind == "op" and self.peek().value == op:
            self.i += 1
            return True
        return False

    def ident(self) -> str:
        t = self.next()
        if t.kind == "word":
            return t.value
        if t.kind == "kw":  # allow keywords as identifiers where safe
            return t.value.lower()
        raise QueryError(f"expected identifier, got {t.value!r}")

    # ---- statements ----

    def statement(self):
        if self.accept_kw("SHOW"):
            if self.accept_kw("TABLES") or self.accept_kw("TOPICS"):
                return ("show_tables",)
            raise QueryError("expected TABLES after SHOW")
        if self.accept_kw("DESCRIBE") or self.accept_kw("DESC"):
            return ("describe", self.ident())
        if self.accept_kw("SELECT"):
            return self.select()
        raise QueryError(f"unsupported statement {self.peek().value!r}")

    def select(self) -> Select:
        cols = [self.select_item()]
        while self.accept_op(","):
            cols.append(self.select_item())
        self.expect_kw("FROM")
        table = self.ident()
        sel = Select(columns=cols, table=table)
        if self.accept_kw("WHERE"):
            sel.where = self.expr()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            sel.group_by = [self.ident()]
            while self.accept_op(","):
                sel.group_by.append(self.ident())
        if self.accept_kw("HAVING"):
            sel.having = self.expr()
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            sel.order_by = []
            while True:
                col = self.ident()
                desc = False
                if self.accept_kw("DESC"):
                    desc = True
                else:
                    self.accept_kw("ASC")
                sel.order_by.append((col, desc))
                if not self.accept_op(","):
                    break
        if self.accept_kw("LIMIT"):
            sel.limit = int(self._num())
        if self.accept_kw("OFFSET"):
            sel.offset = int(self._num())
        if self.peek().kind != "end":
            raise QueryError(f"trailing input near {self.peek().value!r}")
        return sel

    def _num(self):
        t = self.next()
        if t.kind != "num":
            raise QueryError(f"expected number, got {t.value!r}")
        return t.value

    def select_item(self):
        if self.accept_op("*"):
            return ("star",)
        t = self.peek()
        if (
            t.kind in ("word", "kw")
            and self.toks[self.i + 1].kind == "op"
            and self.toks[self.i + 1].value == "("
        ):
            fn = self.ident().upper()
            if fn not in ("COUNT", "SUM", "MIN", "MAX", "AVG"):
                raise QueryError(f"unknown function {fn}")
            self.accept_op("(")
            arg = "*" if self.accept_op("*") else self.ident()
            if not self.accept_op(")"):
                raise QueryError("expected ) after aggregate")
            alias = self.ident() if self.accept_kw("AS") else f"{fn.lower()}({arg})"
            return ("agg", fn, arg, alias)
        name = self.ident()
        alias = self.ident() if self.accept_kw("AS") else name
        return ("col", name, alias)

    # ---- where expressions ----

    def expr(self):
        left = self.and_expr()
        while self.accept_kw("OR"):
            left = ("or", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while self.accept_kw("AND"):
            left = ("and", left, self.not_expr())
        return left

    def not_expr(self):
        if self.accept_kw("NOT"):
            return ("not", self.not_expr())
        return self.comparison()

    def comparison(self):
        if self.accept_op("("):
            e = self.expr()
            if not self.accept_op(")"):
                raise QueryError("expected )")
            return e
        col = self.ident()
        if self.accept_kw("IS"):
            neg = self.accept_kw("NOT")
            self.expect_kw("NULL")
            return ("isnull", col, neg)
        if self.accept_kw("LIKE"):
            t = self.next()
            if t.kind != "str":
                raise QueryError("LIKE needs a string pattern")
            return ("like", col, t.value)
        t = self.next()
        if t.kind != "op" or t.value not in ("=", "!=", "<>", "<", "<=", ">", ">="):
            raise QueryError(f"expected comparison operator, got {t.value!r}")
        op = "!=" if t.value == "<>" else t.value
        v = self.next()
        if v.kind == "kw" and v.value in ("TRUE", "FALSE"):
            value: Any = v.value == "TRUE"
        elif v.kind == "kw" and v.value == "NULL":
            value = None
        elif v.kind in ("num", "str"):
            value = v.value
        else:
            raise QueryError(f"expected literal, got {v.value!r}")
        return ("cmp", op, col, value)


def parse(sql: str):
    return _Parser(tokenize(sql)).statement()


# ------------------------------------------------------------- executor


@dataclass
class Result:
    columns: list[str]
    rows: list[list[Any]]
    tag: str = "SELECT"
    # scan accounting (predicate pushdown audit): segments_scanned /
    # segments_skipped / rows_scanned when the source was a topic scan
    stats: dict = field(default_factory=dict)


def _pushdown_bounds(where) -> dict:
    """Conservative bounds extractable from the WHERE's top-level AND
    chain: _offset >= / > give off_lo; _ts (ms) comparisons give a ns
    range. OR/NOT subtrees contribute nothing (they could widen the
    match set)."""
    out: dict = {}

    def ms_to_ns(ms):
        # exact int arithmetic for integral milliseconds: int(x * 1e6)
        # drifts past 2^53 and can prune boundary-matching segments
        if isinstance(ms, int) or float(ms).is_integer():
            return int(ms) * 1_000_000
        return int(ms * 1_000_000)

    def visit(node):
        if node is None:
            return
        if node[0] == "and":
            visit(node[1])
            visit(node[2])
            return
        if node[0] != "cmp":
            return
        _k, op, col, value = node
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            return
        if col == "_offset":
            if op in (">", ">="):
                lo = int(value) + (1 if op == ">" else 0)
                out["off_lo"] = max(out.get("off_lo", 0), lo)
            elif op == "=":
                out["off_lo"] = max(out.get("off_lo", 0), int(value))
        elif col == "_ts":  # milliseconds in query space, ns in storage
            if op in (">", ">="):
                out["ts_lo_ns"] = max(
                    out.get("ts_lo_ns", -(1 << 62)), ms_to_ns(value)
                )
            elif op in ("<", "<="):
                out["ts_hi_ns"] = min(
                    out.get("ts_hi_ns", 1 << 62),
                    ms_to_ns(value) + 999_999,  # whole-ms granularity
                )
            elif op == "=":
                out["ts_lo_ns"] = max(
                    out.get("ts_lo_ns", -(1 << 62)), ms_to_ns(value)
                )
                out["ts_hi_ns"] = min(
                    out.get("ts_hi_ns", 1 << 62), ms_to_ns(value) + 999_999
                )

    visit(where)
    return out


def _like_to_match(pattern: str, s: str) -> bool:
    # SQL LIKE: % = any run, _ = one char; all other characters —
    # including fnmatch's *, ?, [ metacharacters — are literals
    translated = (
        pattern.replace("[", "[[]")
        .replace("*", "[*]")
        .replace("?", "[?]")
        .replace("%", "*")
        .replace("_", "?")
    )
    return fnmatch.fnmatchcase(s, translated)


def _cmp(op: str, a: Any, b: Any) -> bool:
    if a is None or b is None:
        return False  # SQL three-valued logic: NULL comparisons are false
    try:
        if op == "=":
            return a == b
        if op == "!=":
            return a != b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
    except TypeError:
        return False
    return False


class QueryEngine:
    """Executes parsed statements against an MqBroker.

    scan_limit 0 = UNLIMITED scanning: aggregates fold incrementally
    over any number of rows and LIMIT-ed SELECTs stop early, so full
    archived topics are queryable (the pre-r4 1M-row cap silently
    truncated results). Queries that must MATERIALIZE an unbounded
    result set (SELECT without LIMIT, or ORDER BY) are bounded by
    max_result_rows and FAIL LOUDLY when exceeded — an explicit "add a
    LIMIT" error beats both silent truncation and an OOM'd broker. A
    positive scan_limit is still honored as an operator guardrail."""

    def __init__(
        self,
        broker,
        scan_limit: int = 0,
        max_result_rows: int = 1_000_000,
    ):
        self.broker = broker
        self.scan_limit = scan_limit
        self.max_result_rows = max_result_rows

    # ---- table helpers ----

    def _tables(self) -> list[tuple[str, str, int]]:
        return [
            (ns, name, count)
            for ns, name, count in self.broker.list_topics()
        ]

    def _resolve(self, table: str) -> tuple[str, str, int]:
        matches = [
            (ns, name, c)
            for ns, name, c in self._tables()
            if name == table
        ]
        if not matches:
            raise QueryError(f"unknown table {table!r}")
        # prefer well-known namespaces deterministically
        matches.sort(
            key=lambda t: NAMESPACES.index(t[0])
            if t[0] in NAMESPACES
            else len(NAMESPACES)
        )
        return matches[0]

    def _scan(
        self,
        ns: str,
        name: str,
        count: int,
        bounds: dict | None = None,
        counters: dict | None = None,
    ) -> Iterator[dict]:
        scanned = 0
        st = self.broker.topic(ns, name)
        # topics written through the Kafka gateway carry its one-byte
        # null framing; native MQ topics store raw bytes
        unwrap = _strip_null if ns == "kafka" else (lambda b: b)
        bounds = bounds or {}
        use_pushdown = hasattr(self.broker, "scan_records")
        for p in range(count):
            plog = st.logs.get(p)
            if plog is None:
                continue
            if use_pushdown:
                recs_iter = self.broker.scan_records(
                    ns,
                    name,
                    p,
                    off_lo=bounds.get("off_lo", 0),
                    ts_lo_ns=bounds.get("ts_lo_ns"),
                    ts_hi_ns=bounds.get("ts_hi_ns"),
                    counters=counters,
                )
            else:
                def _plain(plog=plog):
                    off = plog.earliest_offset
                    while True:
                        recs = plog.read_from(off, max_records=2048)
                        if not recs:
                            return
                        yield from recs
                        off = recs[-1][0] + 1

                recs_iter = _plain()
            for o, ts_ns, key, value in recs_iter:
                if self.scan_limit > 0 and scanned >= self.scan_limit:
                    return
                scanned += 1
                row = {}
                payload = unwrap(value)
                doc = None
                if payload:
                    try:
                        doc = json.loads(payload)
                    except (ValueError, UnicodeDecodeError):
                        doc = None
                if isinstance(doc, dict):
                    row.update(doc)
                else:
                    row["_value"] = _maybe_text(payload)
                # system columns LAST: they must win over payload keys
                # of the same name, or pushdown (which prunes on the
                # STORAGE ts/offset) would disagree with WHERE and
                # silently drop matching rows
                row["_key"] = _maybe_text(unwrap(key))
                row["_ts"] = ts_ns // 1_000_000
                row["_offset"] = o
                row["_partition"] = p
                yield row

    # ---- execution ----

    def execute(self, sql: str) -> Result:
        stmt = parse(sql)
        if isinstance(stmt, Select):
            return self._execute_select(stmt)
        if stmt[0] == "show_tables":
            return Result(
                columns=["namespace", "table", "partitions"],
                rows=[[ns, n, c] for ns, n, c in self._tables()],
                tag="SHOW",
            )
        if stmt[0] == "describe":
            ns, name, count = self._resolve(stmt[1])
            cols: dict[str, str] = {
                "_key": "text",
                "_ts": "bigint",
                "_offset": "bigint",
                "_partition": "int",
            }
            # a REGISTERED schema is authoritative (reference
            # weed/mq/schema); otherwise sample rows for discovery
            schema = ""
            if hasattr(self.broker, "get_schema"):
                schema = self.broker.get_schema(ns, name)
            if schema:
                type_map = {
                    "int": "bigint",
                    "float": "double precision",
                    "string": "text",
                    "bool": "boolean",
                    "bytes": "bytea",
                }
                for f in json.loads(schema).get("fields", []):
                    cols.setdefault(
                        f.get("name", "?"),
                        type_map.get(f.get("type", "string"), "text"),
                    )
            else:
                for i, row in enumerate(self._scan(ns, name, count)):
                    for k, v in row.items():
                        cols.setdefault(k, _pg_type(v))
                    if i >= 100:  # column discovery sample
                        break
            return Result(
                columns=["column", "type"],
                rows=[[k, t] for k, t in cols.items()],
                tag="DESCRIBE",
            )
        raise QueryError(f"unsupported statement {stmt[0]!r}")

    def _execute_select(self, sel: Select) -> Result:
        ns, name, count = self._resolve(sel.table)
        counters: dict = {}
        bounds = _pushdown_bounds(sel.where)
        result = self.execute_rows(
            sel, self._scan(ns, name, count, bounds, counters)
        )
        result.stats = counters
        return result

    def execute_rows(self, sel: Select, source) -> Result:
        """Run a parsed SELECT over an arbitrary row iterator — the
        topic scan normally, but also the S3-Select path, which feeds
        CSV/JSON object rows through the same executor."""
        rows = (
            row
            for row in source
            if sel.where is None or self._eval(sel.where, row)
        )
        is_agg = any(c[0] == "agg" for c in sel.columns)
        if is_agg or sel.group_by:
            return self._aggregate(sel, rows)
        if sel.having is not None:
            raise QueryError("HAVING needs GROUP BY or aggregates")
        out: list[dict] = []
        # ORDER BY needs the full set; otherwise stream until limit
        if sel.order_by is None and sel.limit >= 0:
            take = sel.limit + sel.offset
            for row in rows:
                out.append(row)
                if len(out) >= take:
                    break
        else:
            for row in rows:
                out.append(row)
                if len(out) > self.max_result_rows:
                    raise QueryError(
                        f"result exceeds {self.max_result_rows} rows; "
                        "add a LIMIT or aggregate"
                    )
        _order_rows(out, sel.order_by)
        if sel.offset:
            out = out[sel.offset :]
        if sel.limit >= 0:
            out = out[: sel.limit]
        # column projection
        if any(c[0] == "star" for c in sel.columns):
            names: list[str] = []
            for row in out:
                for k in row:
                    if k not in names:
                        names.append(k)
            if not names:
                names = ["_key", "_ts", "_offset", "_partition", "_value"]
        else:
            names = [c[2] for c in sel.columns]
        data = []
        for row in out:
            if any(c[0] == "star" for c in sel.columns):
                data.append([row.get(n) for n in names])
            else:
                data.append(
                    [row.get(c[1]) for c in sel.columns]
                )
        return Result(columns=names, rows=data)

    def _aggregate(self, sel: Select, rows: Iterator[dict]) -> Result:
        """Aggregation, optionally GROUP BY-ed: states fold
        incrementally per group (one pass, bounded by group count, not
        row count), then HAVING / ORDER BY / OFFSET / LIMIT apply over
        the projected {alias: value} rows."""
        group_cols = sel.group_by or []
        for c in sel.columns:
            if c[0] == "star":
                raise QueryError("* cannot be combined with aggregates")
            if c[0] == "col" and c[1] not in group_cols:
                raise QueryError(
                    f"column {c[1]!r} must appear in GROUP BY or an "
                    "aggregate"
                )

        def fresh() -> list[dict]:
            return [
                {"count": 0, "sum": 0.0, "min": None, "max": None}
                for _ in sel.columns
            ]

        groups: dict[tuple, tuple[tuple, list[dict]]] = {}
        for row in rows:
            key = tuple(_group_key(row.get(g)) for g in group_cols)
            hit = groups.get(key)
            if hit is None:
                if len(groups) >= self.max_result_rows:
                    raise QueryError(
                        f"more than {self.max_result_rows} groups; "
                        "narrow the GROUP BY"
                    )
                hit = (tuple(row.get(g) for g in group_cols), fresh())
                groups[key] = hit
            _, states = hit
            for c, st in zip(sel.columns, states):
                if c[0] != "agg":
                    continue
                _fn, fname, arg, _alias = c
                v = None if arg == "*" else row.get(arg)
                if arg != "*" and v is None:
                    continue
                st["count"] += 1
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    st["sum"] += v
                if v is not None:
                    # mixed-type columns compare on a stable (kind,
                    # value) key — MIN(5, "x") must not TypeError the
                    # whole query
                    st["min"] = (
                        v
                        if st["min"] is None
                        or _sort_key(v) < _sort_key(st["min"])
                        else st["min"]
                    )
                    st["max"] = (
                        v
                        if st["max"] is None
                        or _sort_key(v) > _sort_key(st["max"])
                        else st["max"]
                    )
        if not groups and not group_cols:
            groups[()] = ((), fresh())  # global aggregate over no rows
        names = [c[2] if c[0] == "col" else c[3] for c in sel.columns]
        out: list[dict] = []
        for _key, (values, states) in groups.items():
            row_out: dict = {}
            for c, st in zip(sel.columns, states):
                if c[0] == "col":
                    row_out[c[2]] = values[group_cols.index(c[1])]
                    continue
                _k, fname, _arg, alias = c
                if fname == "COUNT":
                    row_out[alias] = st["count"]
                elif fname == "SUM":
                    row_out[alias] = st["sum"] if st["count"] else None
                elif fname == "AVG":
                    row_out[alias] = (
                        st["sum"] / st["count"] if st["count"] else None
                    )
                elif fname == "MIN":
                    row_out[alias] = st["min"]
                elif fname == "MAX":
                    row_out[alias] = st["max"]
            if sel.having is None or self._eval(sel.having, row_out):
                out.append(row_out)
        _order_rows(out, sel.order_by)
        if sel.offset:
            out = out[sel.offset :]
        if sel.limit >= 0:
            out = out[: sel.limit]
        return Result(
            columns=names, rows=[[r.get(n) for n in names] for r in out]
        )

    def _eval(self, node, row: dict) -> bool:
        kind = node[0]
        if kind == "and":
            return self._eval(node[1], row) and self._eval(node[2], row)
        if kind == "or":
            return self._eval(node[1], row) or self._eval(node[2], row)
        if kind == "not":
            return not self._eval(node[1], row)
        if kind == "isnull":
            isnull = row.get(node[1]) is None
            return isnull != node[2]
        if kind == "like":
            v = row.get(node[1])
            return isinstance(v, str) and _like_to_match(node[2], v)
        if kind == "cmp":
            _k, op, col, value = node
            v = row.get(col)
            if value is None:
                return False
            if (
                isinstance(value, (int, float))
                and isinstance(v, str)
            ):
                try:
                    v = float(v)
                except ValueError:
                    return False
            return _cmp(op, v, value)
        raise QueryError(f"bad expression node {kind}")


def _strip_null(b: bytes) -> bytes | None:
    """Undo the Kafka gateway's null framing (gateway._pack_null)."""
    if not b or b[0] == 0:
        return None
    return b[1:]


def _maybe_text(b: bytes | None):
    if b is None:
        return None
    try:
        return b.decode("utf-8")
    except UnicodeDecodeError:
        return b.hex()


def _sort_key(v: Any):
    if isinstance(v, bool):
        return (1, int(v))
    if isinstance(v, (int, float)):
        return (0, v)
    return (2, str(v))


def _group_key(v: Any):
    """Hashable, type-discriminating grouping key: NULL is its own
    group (never folded with the string 'None'); 1 and 1.0 group
    together per SQL equality."""
    if v is None:
        return ("null",)
    if isinstance(v, bool):
        return ("b", v)
    if isinstance(v, (int, float)):
        return ("n", float(v))
    if isinstance(v, str):
        return ("s", v)
    return ("r", repr(v))


def _order_rows(out: list[dict], order_by) -> None:
    """Multi-column ORDER BY with per-column direction: stable sorts
    applied least-significant-first (NULLs last ascending, first
    descending — Postgres default)."""
    if not order_by:
        return
    for col, descending in reversed(order_by):
        out.sort(
            key=lambda r: (r.get(col) is None, _sort_key(r.get(col))),
            reverse=descending,
        )


def _pg_type(v: Any) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "bigint"
    if isinstance(v, float):
        return "double precision"
    return "text"
