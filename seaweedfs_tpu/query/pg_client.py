"""Minimal PostgreSQL v3 simple-protocol client (for tests/tools).

Speaks exactly what psql speaks for simple queries: startup, optional
cleartext password, 'Q', and parses RowDescription/DataRow/
CommandComplete/ErrorResponse.
"""

from __future__ import annotations

import socket
import struct


class PgError(Exception):
    def __init__(self, code: str, message: str):
        self.code = code
        super().__init__(f"{code}: {message}")


class PgClient:
    def __init__(
        self,
        host: str,
        port: int,
        user: str = "sw",
        password: str | None = None,
        database: str = "topics",
    ):
        self._sock = socket.create_connection((host, port), timeout=30)
        params = (
            b"user\x00" + user.encode() + b"\x00"
            b"database\x00" + database.encode() + b"\x00\x00"
        )
        startup = struct.pack(">ii", len(params) + 8, 196608) + params
        self._sock.sendall(startup)
        self.parameters: dict[str, str] = {}
        while True:
            t, payload = self._read()
            if t == b"R":
                (code,) = struct.unpack(">i", payload[:4])
                if code == 0:
                    continue
                if code == 3:
                    if password is None:
                        raise PgError("28P01", "password required")
                    self._send(b"p", password.encode() + b"\x00")
                    continue
                raise PgError("0A000", f"unsupported auth {code}")
            if t == b"S":
                k, v = payload.rstrip(b"\x00").split(b"\x00", 1)
                self.parameters[k.decode()] = v.decode()
            elif t == b"K":
                pass
            elif t == b"E":
                raise self._parse_error(payload)
            elif t == b"Z":
                break

    def close(self) -> None:
        try:
            self._sock.sendall(b"X" + struct.pack(">i", 4))
            self._sock.close()
        except OSError:
            pass

    def query(self, sql: str) -> tuple[list[str], list[list]]:
        self._send(b"Q", sql.encode() + b"\x00")
        columns: list[str] = []
        rows: list[list] = []
        err: PgError | None = None
        while True:
            t, payload = self._read()
            if t == b"T":
                (n,) = struct.unpack(">h", payload[:2])
                pos = 2
                columns = []
                for _ in range(n):
                    end = payload.index(b"\x00", pos)
                    columns.append(payload[pos:end].decode())
                    pos = end + 1 + 18  # fixed per-column fields
            elif t == b"D":
                (n,) = struct.unpack(">h", payload[:2])
                pos = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack(">i", payload[pos : pos + 4])
                    pos += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[pos : pos + ln].decode())
                        pos += ln
                rows.append(row)
            elif t == b"E":
                err = self._parse_error(payload)
            elif t in (b"C", b"I"):
                pass
            elif t == b"Z":
                if err is not None:
                    raise err
                return columns, rows

    def _send(self, t: bytes, payload: bytes) -> None:
        self._sock.sendall(t + struct.pack(">i", len(payload) + 4) + payload)

    def _read(self) -> tuple[bytes, bytes]:
        t = self._read_exact(1)
        (n,) = struct.unpack(">i", self._read_exact(4))
        return t, self._read_exact(n - 4)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise EOFError("server closed")
            buf += chunk
        return buf

    @staticmethod
    def _parse_error(payload: bytes) -> PgError:
        code = msg = ""
        for field in payload.split(b"\x00"):
            if field.startswith(b"C"):
                code = field[1:].decode()
            elif field.startswith(b"M"):
                msg = field[1:].decode()
        return PgError(code or "XX000", msg or "unknown error")
