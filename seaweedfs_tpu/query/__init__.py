"""SQL over MQ topics + PostgreSQL wire server.

Reference: weed/query/engine (engine.go:553 ExecuteSQL — SELECT /
aggregations / WHERE pushdown over topic messages) and
weed/server/postgres (a PostgreSQL 3.0 wire-protocol front end so
psql/JDBC clients can query topics).
"""

from .engine import QueryEngine, QueryError  # noqa: F401
