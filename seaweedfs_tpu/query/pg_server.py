"""PostgreSQL 3.0 wire-protocol server fronting the SQL engine.

Reference: weed/server/postgres/{server,protocol}.go — a PG front end
so psql/JDBC/psycopg clients can query MQ topics. Implements the v3
startup handshake (SSLRequest politely refused, trust or cleartext-
password auth), the simple query protocol ('Q'), and enough of the
extended protocol (Parse/Bind/Describe/Execute/Sync, no parameters)
for drivers that refuse simple mode.

Message framing: type byte + i32 length (incl. itself) + payload;
the startup message has no type byte.
"""

from __future__ import annotations

import socket
import struct
import threading

from ..utils.glog import logger
from .engine import QueryEngine, QueryError

log = logger("pg")

SSL_REQUEST_CODE = 80877103
CANCEL_REQUEST_CODE = 80877102
PROTOCOL_V3 = 196608

# type OIDs
OID_TEXT = 25
OID_INT8 = 20
OID_FLOAT8 = 701
OID_BOOL = 16

AUTH_OK = 0
AUTH_CLEARTEXT = 3


def _msg(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + struct.pack(">i", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgServer:
    def __init__(
        self,
        engine: QueryEngine,
        ip: str = "localhost",
        port: int = 5432,
        users: dict[str, str] | None = None,
    ):
        """users: name -> password. Empty/None = trust auth (any user)."""
        self.engine = engine
        self.users = users or {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((ip, port))
        self.ip = ip
        self.port = self._sock.getsockname()[1]
        self._sock.listen(32)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    # ----------------------------------------------------------- session

    def _serve(self, conn: socket.socket) -> None:
        try:
            if not self._startup(conn):
                return
            self._session_loop(conn)
        except (OSError, EOFError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _startup(self, conn: socket.socket) -> bool:
        while True:
            head = _read_exact(conn, 4)
            (n,) = struct.unpack(">i", head)
            body = _read_exact(conn, n - 4)
            (code,) = struct.unpack(">i", body[:4])
            if code == SSL_REQUEST_CODE:
                conn.sendall(b"N")  # no TLS on this listener
                continue
            if code == CANCEL_REQUEST_CODE:
                return False
            if code != PROTOCOL_V3:
                self._error(conn, "08P01", f"unsupported protocol {code}")
                return False
            params = _parse_kv(body[4:])
            user = params.get("user", "")
            break
        if self.users:
            conn.sendall(_msg(b"R", struct.pack(">i", AUTH_CLEARTEXT)))
            t, payload = _read_message(conn)
            if t != b"p":
                return False
            password = payload.rstrip(b"\x00").decode()
            if self.users.get(user) != password:
                self._error(conn, "28P01", f"password authentication failed for {user}")
                return False
        conn.sendall(_msg(b"R", struct.pack(">i", AUTH_OK)))
        for k, v in (
            ("server_version", "14.0 (seaweedfs-tpu)"),
            ("client_encoding", "UTF8"),
            ("DateStyle", "ISO"),
            ("integer_datetimes", "on"),
        ):
            conn.sendall(_msg(b"S", _cstr(k) + _cstr(v)))
        conn.sendall(_msg(b"K", struct.pack(">ii", 0, 0)))  # BackendKeyData
        self._ready(conn)
        return True

    def _session_loop(self, conn: socket.socket) -> None:
        # extended-protocol state: the last parsed statement
        prepared: dict[str, str] = {}
        portals: dict[str, str] = {}
        while True:
            t, payload = _read_message(conn)
            if t == b"X":  # Terminate
                return
            if t == b"Q":
                sql = payload.rstrip(b"\x00").decode()
                self._run_simple(conn, sql)
            elif t == b"P":  # Parse: name, query, param types
                name, rest = _take_cstr(payload)
                sql, _ = _take_cstr(rest)
                prepared[name] = sql
                conn.sendall(_msg(b"1", b""))  # ParseComplete
            elif t == b"B":  # Bind: portal, statement, formats/params
                portal, rest = _take_cstr(payload)
                stmt, _ = _take_cstr(rest)
                portals[portal] = prepared.get(stmt, "")
                conn.sendall(_msg(b"2", b""))  # BindComplete
            elif t == b"D":  # Describe
                kind = payload[:1]
                name, _ = _take_cstr(payload[1:])
                sql = (
                    portals.get(name, "")
                    if kind == b"P"
                    else prepared.get(name, "")
                )
                # NoData keeps drivers happy without pre-executing
                conn.sendall(_msg(b"n", b""))
            elif t == b"E":  # Execute: portal, row limit
                portal, _rest = _take_cstr(payload)
                sql = portals.get(portal, "")
                self._run_extended(conn, sql)
            elif t == b"S":  # Sync
                self._ready(conn)
            elif t == b"H":  # Flush
                pass
            else:
                self._error(conn, "0A000", f"unsupported message {t!r}")
                self._ready(conn)

    # ---------------------------------------------------------- queries

    def _run_simple(self, conn: socket.socket, sql: str) -> None:
        sql = sql.strip().rstrip(";").strip()
        if not sql:
            conn.sendall(_msg(b"I", b""))  # EmptyQueryResponse
            self._ready(conn)
            return
        lowered = sql.lower()
        if lowered.startswith(("set ", "begin", "commit", "rollback")):
            # session noise from drivers: accept silently
            conn.sendall(_msg(b"C", _cstr("SET")))
            self._ready(conn)
            return
        try:
            res = self.engine.execute(sql)
        except QueryError as e:
            self._error(conn, "42601", str(e))
            self._ready(conn)
            return
        except Exception as e:  # engine bug: error the query, keep session
            self._error(conn, "XX000", f"internal error: {e}")
            self._ready(conn)
            return
        self._send_result(conn, res)
        self._ready(conn)

    def _run_extended(self, conn: socket.socket, sql: str) -> None:
        sql = sql.strip().rstrip(";").strip()
        if not sql:
            conn.sendall(_msg(b"I", b""))
            return
        try:
            res = self.engine.execute(sql)
        except QueryError as e:
            self._error(conn, "42601", str(e))
            return
        except Exception as e:
            self._error(conn, "XX000", f"internal error: {e}")
            return
        self._send_result(conn, res)

    def _send_result(self, conn: socket.socket, res) -> None:
        # RowDescription
        cols = b"".join(
            _cstr(name)
            + struct.pack(
                ">ihihih",
                0,  # table oid
                0,  # column attr
                _oid_for(res, i),
                -1,  # type size (variable)
                -1,  # type modifier
                0,  # text format
            )
            for i, name in enumerate(res.columns)
        )
        conn.sendall(
            _msg(b"T", struct.pack(">h", len(res.columns)) + cols)
        )
        for row in res.rows:
            fields = []
            for v in row:
                if v is None:
                    fields.append(struct.pack(">i", -1))
                else:
                    s = _render(v).encode()
                    fields.append(struct.pack(">i", len(s)) + s)
            conn.sendall(
                _msg(b"D", struct.pack(">h", len(row)) + b"".join(fields))
            )
        conn.sendall(
            _msg(b"C", _cstr(f"{res.tag} {len(res.rows)}"))
        )

    # ---------------------------------------------------------- helpers

    def _ready(self, conn: socket.socket) -> None:
        conn.sendall(_msg(b"Z", b"I"))

    def _error(self, conn: socket.socket, code: str, message: str) -> None:
        payload = (
            b"S" + _cstr("ERROR")
            + b"C" + _cstr(code)
            + b"M" + _cstr(message)
            + b"\x00"
        )
        conn.sendall(_msg(b"E", payload))


def _oid_for(res, col_index: int) -> int:
    for row in res.rows:
        v = row[col_index]
        if v is None:
            continue
        if isinstance(v, bool):
            return OID_BOOL
        if isinstance(v, int):
            return OID_INT8
        if isinstance(v, float):
            return OID_FLOAT8
        return OID_TEXT
    return OID_TEXT


def _render(v) -> str:
    if isinstance(v, bool):
        return "t" if v else "f"
    if isinstance(v, float) and v == int(v):
        return str(v)
    if isinstance(v, (dict, list)):
        import json

        return json.dumps(v)
    return str(v)


def _read_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise EOFError("client closed")
        buf += chunk
    return buf


def _read_message(conn: socket.socket) -> tuple[bytes, bytes]:
    t = _read_exact(conn, 1)
    (n,) = struct.unpack(">i", _read_exact(conn, 4))
    return t, _read_exact(conn, n - 4)


def _parse_kv(body: bytes) -> dict[str, str]:
    parts = body.split(b"\x00")
    out = {}
    for i in range(0, len(parts) - 1, 2):
        if parts[i]:
            out[parts[i].decode()] = parts[i + 1].decode()
    return out


def _take_cstr(b: bytes) -> tuple[str, bytes]:
    i = b.index(b"\x00")
    return b[:i].decode(), b[i + 1 :]
