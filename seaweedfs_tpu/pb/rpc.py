"""Hand-rolled gRPC service wiring (no grpc_tools codegen in this
environment): a declarative method table per service, from which both
server handlers and client stubs are built.

Server impls are plain classes with one method per RPC (same names);
streaming RPCs receive/return iterators, exactly like generated servicers.
"""

from __future__ import annotations

import grpc

from . import cluster_pb2 as pb
from . import filer_pb2 as fpb
from . import mq_pb2 as mq
from . import worker_pb2 as wk

UNARY = "unary_unary"
SERVER_STREAM = "unary_stream"
CLIENT_STREAM = "stream_unary"
BIDI = "stream_stream"

MASTER_SERVICE = "sw.Seaweed"
VOLUME_SERVICE = "sw.VolumeServer"
MQ_SERVICE = "swmq.Messaging"
MQ_AGENT_SERVICE = "swmqagent.SeaweedMessagingAgent"
FILER_SERVICE = "swfiler.SeaweedFiler"
WORKER_SERVICE = "swworker.WorkerControl"
RAFT_SERVICE = "sw.Raft"

SERVICES: dict[str, dict[str, tuple[str, type, type]]] = {
    MASTER_SERVICE: {
        "SendHeartbeat": (BIDI, pb.Heartbeat, pb.HeartbeatResponse),
        "Assign": (UNARY, pb.AssignRequest, pb.AssignResponse),
        "LookupVolume": (UNARY, pb.LookupVolumeRequest, pb.LookupVolumeResponse),
        "LookupEcVolume": (UNARY, pb.LookupEcVolumeRequest, pb.LookupEcVolumeResponse),
        "Statistics": (UNARY, pb.StatisticsRequest, pb.StatisticsResponse),
        "Topology": (UNARY, pb.TopologyRequest, pb.TopologyResponse),
        "VolumeGrow": (UNARY, pb.VolumeGrowRequest, pb.VolumeGrowResponse),
        "CollectionList": (UNARY, pb.CollectionListRequest, pb.CollectionListResponse),
        "CollectionDelete": (UNARY, pb.CollectionDeleteRequest, pb.CollectionDeleteResponse),
        "KeepConnected": (SERVER_STREAM, pb.KeepConnectedRequest, pb.VolumeLocationUpdate),
        "AdminLock": (UNARY, pb.LockRequest, pb.LockResponse),
        "AdminUnlock": (UNARY, pb.UnlockRequest, pb.UnlockResponse),
        "AdminLockStatus": (UNARY, pb.LockStatusRequest, pb.LockStatusResponse),
        "VacuumControl": (UNARY, pb.VacuumControlRequest, pb.VolumeCommandResponse),
    },
    VOLUME_SERVICE: {
        "AllocateVolume": (UNARY, pb.AllocateVolumeRequest, pb.AllocateVolumeResponse),
        "VolumeDelete": (UNARY, pb.VolumeCommandRequest, pb.VolumeCommandResponse),
        "VolumeMount": (UNARY, pb.AllocateVolumeRequest, pb.VolumeCommandResponse),
        "VolumeCopy": (UNARY, pb.EcShardsCopyRequest, pb.VolumeCommandResponse),
        "VolumeMarkReadonly": (UNARY, pb.VolumeCommandRequest, pb.VolumeCommandResponse),
        "VolumeMarkWritable": (UNARY, pb.VolumeCommandRequest, pb.VolumeCommandResponse),
        "VacuumVolume": (UNARY, pb.VacuumRequest, pb.VacuumResponse),
        "WriteNeedle": (UNARY, pb.WriteNeedleRequest, pb.WriteNeedleResponse),
        "ReadNeedle": (UNARY, pb.ReadNeedleRequest, pb.ReadNeedleResponse),
        "DeleteNeedle": (UNARY, pb.DeleteNeedleRequest, pb.DeleteNeedleResponse),
        "VolumeEcShardsGenerate": (UNARY, pb.EcShardsGenerateRequest, pb.EcShardsGenerateResponse),
        "VolumeEcShardsRebuild": (UNARY, pb.EcShardsRebuildRequest, pb.EcShardsRebuildResponse),
        "VolumeEcShardsCopy": (UNARY, pb.EcShardsCopyRequest, pb.EcShardsCopyResponse),
        "VolumeEcShardsDelete": (UNARY, pb.EcShardsDeleteRequest, pb.EcShardsDeleteResponse),
        "VolumeEcShardsMount": (UNARY, pb.EcShardsMountRequest, pb.EcShardsMountResponse),
        "VolumeEcShardsUnmount": (UNARY, pb.EcShardsUnmountRequest, pb.EcShardsUnmountResponse),
        "VolumeEcShardRead": (SERVER_STREAM, pb.EcShardReadRequest, pb.EcShardReadChunk),
        "VolumeEcBlobDelete": (UNARY, pb.EcBlobDeleteRequest, pb.EcBlobDeleteResponse),
        "VolumeEcShardsToVolume": (UNARY, pb.EcShardsToVolumeRequest, pb.EcShardsToVolumeResponse),
        "CopyFile": (SERVER_STREAM, pb.CopyFileRequest, pb.CopyFileChunk),
        "VolumeServerStatus": (UNARY, pb.VolumeServerStatusRequest, pb.VolumeServerStatusResponse),
        "ScrubVolume": (UNARY, pb.ScrubRequest, pb.ScrubResponse),
        "ScrubEcVolume": (UNARY, pb.ScrubRequest, pb.ScrubResponse),
        "VolumeTierUpload": (UNARY, pb.TierRequest, pb.TierResponse),
        "VolumeTierDownload": (UNARY, pb.TierRequest, pb.TierResponse),
        "VolumeUnmount": (UNARY, pb.VolumeCommandRequest, pb.VolumeCommandResponse),
        "VolumeConfigure": (UNARY, pb.VolumeConfigureRequest, pb.VolumeCommandResponse),
        "VolumeTailSender": (SERVER_STREAM, pb.VolumeTailRequest, pb.VolumeTailChunk),
        "VolumeTailReceiver": (UNARY, pb.VolumeTailReceiverRequest, pb.VolumeTailReceiverResponse),
        "VolumeIncrementalCopy": (SERVER_STREAM, pb.VolumeIncrementalCopyRequest, pb.VolumeIncrementalCopyChunk),
        "ReadVolumeFileStatus": (UNARY, pb.VolumeFileStatusRequest, pb.VolumeFileStatusResponse),
    },
    MQ_SERVICE: {
        "ConfigureTopic": (UNARY, mq.ConfigureTopicRequest, mq.ConfigureTopicResponse),
        "ListTopics": (UNARY, mq.ListTopicsRequest, mq.ListTopicsResponse),
        "Publish": (UNARY, mq.PublishRequest, mq.PublishResponse),
        "Subscribe": (SERVER_STREAM, mq.SubscribeRequest, mq.SubscribeRecord),
        "CommitOffset": (UNARY, mq.CommitOffsetRequest, mq.CommitOffsetResponse),
        "FetchOffset": (UNARY, mq.FetchOffsetRequest, mq.FetchOffsetResponse),
        "PartitionInfo": (UNARY, mq.PartitionInfoRequest, mq.PartitionInfoResponse),
        "BrokerStatus": (UNARY, mq.BrokerStatusRequest, mq.BrokerStatusResponse),
        "LookupTopicBrokers": (UNARY, mq.LookupTopicBrokersRequest, mq.LookupTopicBrokersResponse),
        "FollowAppend": (UNARY, mq.FollowAppendRequest, mq.FollowAppendResponse),
        "CompactTopic": (UNARY, mq.CompactTopicRequest, mq.CompactTopicResponse),
        "DeleteTopic": (UNARY, mq.DeleteTopicRequest, mq.DeleteTopicResponse),
        "TruncateTopic": (UNARY, mq.TruncateTopicRequest, mq.TruncateTopicResponse),
        "RegisterSchema": (UNARY, mq.RegisterSchemaRequest, mq.RegisterSchemaResponse),
        "GetSchema": (UNARY, mq.GetSchemaRequest, mq.GetSchemaResponse),
    },
    MQ_AGENT_SERVICE: {
        "StartPublishSession": (UNARY, mq.AgentStartPublishRequest, mq.AgentStartPublishResponse),
        "ClosePublishSession": (UNARY, mq.AgentClosePublishRequest, mq.AgentClosePublishResponse),
        "PublishRecord": (BIDI, mq.AgentPublishRequest, mq.AgentPublishResponse),
        "SubscribeRecord": (BIDI, mq.AgentSubscribeRequest, mq.AgentSubscribeResponse),
    },
    FILER_SERVICE: {
        "LookupDirectoryEntry": (UNARY, fpb.LookupEntryRequest, fpb.LookupEntryResponse),
        "ListEntries": (SERVER_STREAM, fpb.ListEntriesRequest, fpb.ListEntriesResponse),
        "CreateEntry": (UNARY, fpb.CreateEntryRequest, fpb.FilerOpResponse),
        "UpdateEntry": (UNARY, fpb.UpdateEntryRequest, fpb.FilerOpResponse),
        "DeleteEntry": (UNARY, fpb.DeleteEntryRequest, fpb.FilerOpResponse),
        "AtomicRenameEntry": (UNARY, fpb.AtomicRenameEntryRequest, fpb.FilerOpResponse),
        "SubscribeMetadata": (SERVER_STREAM, fpb.SubscribeMetadataRequest, fpb.FullEventNotification),
        "AssignVolume": (UNARY, fpb.AssignVolumeRequest, fpb.AssignVolumeResponse),
        "KvGet": (UNARY, fpb.FilerKvGetRequest, fpb.FilerKvGetResponse),
        "KvPut": (UNARY, fpb.FilerKvPutRequest, fpb.FilerOpResponse),
        "LockRange": (UNARY, fpb.LockRangeRequest, fpb.LockRangeResponse),
        "HardLink": (UNARY, fpb.HardLinkRequest, fpb.FilerOpResponse),
        "DistributedLock": (UNARY, fpb.DlmRequest, fpb.DlmResponse),
        "RunLifecycle": (UNARY, fpb.LifecycleRunRequest, fpb.LifecycleRunResponse),
        # volume location passthrough (reference filer LookupVolume):
        # mounts resolve chunk fids to volume-server URLs through the
        # filer, so the data plane can go direct + peer-to-peer
        "LookupVolume": (UNARY, pb.LookupVolumeRequest, pb.LookupVolumeResponse),
    },
    WORKER_SERVICE: {
        "WorkerStream": (BIDI, wk.WorkerMessage, wk.ServerMessage),
        "ListTasks": (UNARY, wk.ListTasksRequest, wk.ListTasksResponse),
        "SubmitTask": (UNARY, wk.SubmitTaskRequest, wk.SubmitTaskResponse),
        "ListWorkers": (UNARY, wk.ListWorkersRequest, wk.ListWorkersResponse),
        "GetMaintenanceConfig": (UNARY, wk.GetMaintenanceConfigRequest, wk.MaintenanceConfig),
        "SetMaintenanceConfig": (UNARY, wk.MaintenanceConfig, wk.SetMaintenanceConfigResponse),
    },
    RAFT_SERVICE: {
        "RaftRequestVote": (UNARY, pb.RaftVoteRequest, pb.RaftVoteResponse),
        "RaftAppendEntries": (UNARY, pb.RaftAppendRequest, pb.RaftAppendResponse),
        "RaftStatus": (UNARY, pb.RaftStatusRequest, pb.RaftStatusResponse),
        "RaftInstallSnapshot": (
            UNARY,
            pb.RaftInstallSnapshotRequest,
            pb.RaftInstallSnapshotResponse,
        ),
        "RaftChangeMembership": (UNARY, pb.RaftChangeRequest, pb.RaftChangeResponse),
    },
}


def add_service(server: grpc.Server, service_name: str, impl: object) -> None:
    methods = {}
    for name, (kind, req_t, resp_t) in SERVICES[service_name].items():
        handler_factory = getattr(grpc, f"{kind}_rpc_method_handler")
        methods[name] = handler_factory(
            getattr(impl, name),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, methods),)
    )


class Stub:
    """Client stub: one callable attribute per RPC."""

    def __init__(self, channel: grpc.Channel, service_name: str):
        for name, (kind, req_t, resp_t) in SERVICES[service_name].items():
            factory = getattr(channel, kind)
            setattr(
                self,
                name,
                factory(
                    f"/{service_name}/{name}",
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )


def master_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, MASTER_SERVICE)


def volume_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, VOLUME_SERVICE)


def mq_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, MQ_SERVICE)


def filer_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, FILER_SERVICE)


def worker_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, WORKER_SERVICE)
