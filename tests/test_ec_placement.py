"""Rack-aware EC placement planner tests (reference
weed/shell/command_ec_*_test.go style: synthetic topologies in, move
plans out) plus the nested DC/rack topology tree."""

from seaweedfs_tpu.ec.placement import Drop, Move, NodeView, plan_ec_balance


def _shards(vid, *sids):
    return {vid: set(sids)}


def test_dedupe_drops_extra_copies():
    nodes = [
        NodeView("a", rack="r1", shards={1: {0, 1}}),
        NodeView("b", rack="r1", shards={1: {1, 2}}),  # shard 1 duplicated
    ]
    drops, moves = plan_ec_balance(nodes)
    assert Drop(1, 1, "b") in drops or Drop(1, 1, "a") in drops
    assert len(drops) == 1
    # post-dedupe state holds exactly one copy of each shard
    holders = [n for n in nodes if 1 in n.shards and 1 in n.shards[1]]
    assert len(holders) == 1


def test_shards_spread_across_racks_proportionally():
    """All 14 shards start on one rack; three racks -> no rack may keep
    more than ceil(14/3)=5."""
    nodes = [
        NodeView("a1", rack="r1", shards={7: set(range(14))}),
        NodeView("a2", rack="r1"),
        NodeView("b1", rack="r2"),
        NodeView("b2", rack="r2"),
        NodeView("c1", rack="r3"),
    ]
    drops, moves = plan_ec_balance(nodes)
    assert not drops
    per_rack = {}
    for n in nodes:
        per_rack[n.rack] = per_rack.get(n.rack, 0) + len(n.shards.get(7, ()))
    assert sum(per_rack.values()) == 14
    assert max(per_rack.values()) <= 5
    assert min(per_rack.values()) >= 4  # 14 over 3 racks: 5/5/4
    # within each rack, servers are even too
    for n in nodes:
        assert len(n.shards.get(7, ())) <= 5


def test_destination_prefers_rack_with_fewest_volume_shards():
    nodes = [
        NodeView("src", rack="r1", shards={3: set(range(10))}),
        NodeView("b", rack="r2", shards={3: {10, 11, 12, 13}}),
        NodeView("c", rack="r3"),  # empty rack: must be preferred
    ]
    _, moves = plan_ec_balance(nodes)
    to_c = [m for m in moves if m.dst == "c"]
    assert to_c, "empty rack r3 must receive shards"
    # r2 already holds 4 — overflow should flow to r3 first
    first_dst = moves[0].dst
    assert first_dst == "c"


def test_within_rack_evening():
    nodes = [
        NodeView("a1", rack="r1", shards={5: {0, 1, 2, 3}}),
        NodeView("a2", rack="r1"),
    ]
    _, moves = plan_ec_balance(nodes)
    assert all(m.reason == "within-rack" for m in moves)
    assert len(nodes[0].shards[5]) == 2 and len(nodes[1].shards[5]) == 2


def test_rack_total_flattening_preserves_volume_spread():
    """Totals inside a rack flatten by moving a volume the destination
    does NOT hold (reference balanceEcRack)."""
    nodes = [
        NodeView("a1", rack="r1", shards={1: {0}, 2: {0}, 3: {0}, 4: {0}}),
        NodeView("a2", rack="r1", shards={5: {0}}),
    ]
    _, moves = plan_ec_balance(nodes)
    for m in moves:
        assert m.reason == "rack-total"
        assert m.vid != 5  # never stack a volume onto a holder
    c1, c2 = nodes[0].shard_count(), nodes[1].shard_count()
    assert abs(c1 - c2) <= 1


def test_no_moves_when_balanced():
    nodes = [
        NodeView("a", rack="r1", shards={9: {0, 1, 2}}),
        NodeView("b", rack="r2", shards={9: {3, 4, 5}}),
        NodeView("c", rack="r3", shards={9: {6, 7}}),
    ]
    drops, moves = plan_ec_balance(nodes)
    assert not drops and not moves


def test_full_slots_are_skipped():
    nodes = [
        NodeView("a", rack="r1", shards={1: set(range(14))}),
        NodeView("b", rack="r2", free_slots=0),
    ]
    _, moves = plan_ec_balance(nodes)
    assert all(m.dst != "b" for m in moves)


def test_multi_dc_racks_are_distinct():
    """Same rack name in two DCs must count as two racks."""
    nodes = [
        NodeView("a", data_center="dc1", rack="r", shards={1: set(range(14))}),
        NodeView("b", data_center="dc2", rack="r"),
    ]
    _, moves = plan_ec_balance(nodes)
    assert any(m.dst == "b" for m in moves)
    assert len(nodes[1].shards.get(1, ())) == 7


# ------------------------------------------------------- topology tree


def test_topology_tree_registration():
    from seaweedfs_tpu.pb import cluster_pb2 as pb
    from seaweedfs_tpu.server.topology import Topology

    topo = Topology()
    for ip, dc, rack in [
        ("10.0.0.1", "dc1", "ra"),
        ("10.0.0.2", "dc1", "ra"),
        ("10.0.0.3", "dc1", "rb"),
        ("10.0.0.4", "dc2", "ra"),
    ]:
        topo.register_node(
            pb.Heartbeat(ip=ip, port=8080, data_center=dc, rack=rack)
        )
    assert sorted(topo.data_centers) == ["dc1", "dc2"]
    assert sorted(topo.data_centers["dc1"].racks) == ["ra", "rb"]
    assert len(topo.data_centers["dc1"].racks["ra"].nodes) == 2
    assert len(list(topo.data_centers["dc2"].all_nodes())) == 1
    # unregister prunes empty tree levels
    topo.unregister_node("10.0.0.4:8080")
    assert "dc2" not in topo.data_centers
    topo.unregister_node("10.0.0.3:8080")
    assert sorted(topo.data_centers["dc1"].racks) == ["ra"]


def test_node_view_for_shared_builder():
    """node_view_for is the ONE topology->NodeView mapping shared by
    the shell executor and the master auto-scanner; its capacity math
    and filtering must match what the planner expects."""
    from types import SimpleNamespace

    from seaweedfs_tpu.ec.placement import node_view_for

    entries = [
        SimpleNamespace(id=1, shard_bits=0b111, collection=""),
        SimpleNamespace(id=2, shard_bits=1 << 20, collection="photos"),
    ]
    v = node_view_for("n1", "r1", "dc1", 8, 3, entries)
    # every collection counts against capacity: (8-3)*10 - 4 shards
    assert v.free_slots == 46
    assert v.shards == {1: {0, 1, 2}, 2: {20}}  # 32-bit mask decode
    assert v.rack_key() == ("dc1", "r1")

    # collection filter: unmatched entries still consume capacity but
    # are not planned
    v = node_view_for("n1", "r1", "dc1", 8, 3, entries, collection="photos")
    assert v.shards == {2: {20}}
    assert v.free_slots == 46

    # max_volume_count=0 uses the historical default of 8: with 7
    # volumes held, (8-7)*10 - 4 shards = 6 (a removed default would
    # clamp to 0 and fail here)
    v = node_view_for("n2", "r1", "dc1", 0, 7, entries)
    assert v.free_slots == 6
    # and a genuinely slot-tight node clamps at zero
    v = node_view_for("n3", "r1", "dc1", 0, 8, entries)
    assert v.free_slots == 0


# ----------------------------------------------- live load-feedback scoring


def test_plan_shard_placement_follows_live_chip_load():
    """PR 14: heartbeat-learned DeviceQueue load ranks otherwise-equal
    destinations — shards land on the host with compute headroom."""
    from seaweedfs_tpu.ec.placement import plan_shard_placement

    def views(a_load, b_load):
        return [
            NodeView(id="a", free_slots=50, ec_load=a_load),
            NodeView(id="b", free_slots=50, ec_load=b_load),
        ]

    # static scoring ties (same shard counts/slots): live load decides
    assert plan_shard_placement(views(90_000, 0.0), 7, [0]) == {0: "b"}
    assert plan_shard_placement(views(0.0, 90_000), 7, [0]) == {0: "a"}
    # shard-count spread still outranks load: two shards of ONE volume
    # spread across both nodes (loss domain beats compute headroom)
    plan = plan_shard_placement(views(90_000, 0.0), 7, [0, 1])
    assert set(plan.values()) == {"a", "b"}
    # unknown telemetry (-1) scores as idle: static tie, lowest id wins
    # and the planner's mutate-as-you-assign still spreads by count
    nv = [
        NodeView(id="a", free_slots=50),
        NodeView(id="b", free_slots=50),
    ]
    plan = plan_shard_placement(nv, 7, [0, 1])
    assert set(plan.values()) == {"a", "b"}


def test_plan_shard_placement_shuns_open_breakers():
    from seaweedfs_tpu.ec.placement import plan_shard_placement

    nv = [
        NodeView(id="degraded", free_slots=50, ec_load=0.0,
                 ec_breakers_open=1),
        NodeView(id="healthy", free_slots=50, ec_load=70_000.0),
    ]
    # the degraded node is idle-by-load but its chips are failing over
    # to CPU: the loaded-but-healthy node wins
    plan = plan_shard_placement(nv, 3, [4])
    assert plan == {4: "healthy"}


def test_gravity_chips_split_ties_never_override_capacity():
    """ISSUE 15: heartbeat-learned chip count splits capacity ties
    (bytes drift toward hardware) but NEVER overrides the slot
    gradient — the PR 14 mixed-fleet rule extended to gravity."""
    from seaweedfs_tpu.ec.placement import plan_shard_placement

    # static tie: the chip-rich node wins
    nv = [
        NodeView(id="bare", free_slots=50, ec_chips=0),
        NodeView(id="chips", free_slots=50, ec_chips=8),
    ]
    assert plan_shard_placement(nv, 7, [0]) == {0: "chips"}
    # slots outrank chips: a chip-rich nearly-full node still loses
    nv = [
        NodeView(id="roomy", free_slots=50, ec_chips=0),
        NodeView(id="chips", free_slots=5, ec_chips=8),
    ]
    assert plan_shard_placement(nv, 7, [0]) == {0: "roomy"}
    # within equal chips, live load still decides (PR 14 behavior)
    nv = [
        NodeView(id="busy", free_slots=50, ec_chips=4, ec_load=9e6),
        NodeView(id="idle", free_slots=50, ec_chips=4, ec_load=0.0),
    ]
    assert plan_shard_placement(nv, 7, [0]) == {0: "idle"}


def test_gravity_score_shape():
    idle8 = NodeView(id="a", ec_chips=8)
    busy8 = NodeView(id="b", ec_chips=8, ec_load=1e9)
    broken8 = NodeView(id="c", ec_chips=8, ec_breakers_open=2)
    none0 = NodeView(id="d")
    assert idle8.gravity_score() > busy8.gravity_score() > 0
    assert idle8.gravity_score() > broken8.gravity_score()
    assert none0.gravity_score() == 0.0


def test_node_view_for_parses_ec_telemetry():
    from seaweedfs_tpu.ec.placement import node_view_for

    tele = {
        "chips": {
            "cpu:0": {"load": 1000, "breaker": "closed"},
            "cpu:1": {"load": 234, "breaker": "open"},
        },
        "breakers_open": 1,
        "stage_ewma_s": {
            "ec.encode/h2d_dispatch": 0.25,
            "ec.encode/device_drain": 0.5,
            "ec.encode/disk_read": 99.0,  # host stage: not device load
        },
    }
    v = node_view_for("n1", "r", "dc", 8, 0, [], ec_telemetry=tele)
    assert v.ec_load == 1234.0
    assert v.ec_breakers_open == 1
    assert v.ec_stage_ewma_s == 0.75
    # absent/malformed telemetry stays unknown
    v2 = node_view_for("n2", "r", "dc", 8, 0, [], ec_telemetry=None)
    assert v2.ec_load == -1.0 and v2.ec_breakers_open == 0
    v3 = node_view_for(
        "n3", "r", "dc", 8, 0, [], ec_telemetry={"chips": "garbage"}
    )
    assert v3.ec_load == -1.0
