"""Worker-fleet tests: registration, task dispatch, EC-encode execution,
requeue on worker death (reference test/plugin_workers in-process
harness technique)."""

import threading
import time

import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.worker import Worker


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


@pytest.fixture
def cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield master, vs
    vs.stop()
    master.stop()


def start_worker(master_port, **kw) -> Worker:
    w = Worker(master=f"localhost:{master_port}", backend="cpu", **kw)
    threading.Thread(target=w.run, daemon=True).start()
    wait_for(
        lambda: w.worker_id in master_control(master_port)._workers,
        msg="worker registers",
    )
    return w


_masters = {}


def master_control(port):
    return _masters[port].worker_control


def test_worker_executes_ec_encode(cluster):
    master, vs = cluster
    _masters[master.port] = master
    ops = Operations(f"localhost:{master.port}")
    env = ShellEnv(f"localhost:{master.port}")
    w = start_worker(master.port)
    try:
        data = b"worker encodes me" * 3000
        fid = ops.upload(data)
        vid = FileId.parse(fid).volume_id
        out = run_command(env, f"task.submit -kind ec_encode -volumeId {vid}")
        assert "submitted" in out
        wait_for(
            lambda: "done" in run_command(env, "task.list"),
            msg="task completes",
        )
        # the volume is now EC-backed and still readable
        wait_for(
            lambda: any(
                vid in n.ec_shards for n in master.topo.nodes.values()
            )
        )
        assert ops.read(fid) == data
        # duplicate submits dedupe onto the finished/live task
        out1 = run_command(env, f"task.submit -kind vacuum -volumeId {vid}")
        out2 = run_command(env, f"task.submit -kind vacuum -volumeId {vid}")
        # (ids equal while the first is still live)
        assert "submitted" in out1 and "submitted" in out2
    finally:
        w.stop()
        env.close()
        ops.close()


def test_task_failure_reported(cluster):
    master, vs = cluster
    _masters[master.port] = master
    env = ShellEnv(f"localhost:{master.port}")
    w = start_worker(master.port)
    try:
        run_command(env, "task.submit -kind ec_encode -volumeId 424242")
        wait_for(
            lambda: "failed" in run_command(env, "task.list"),
            msg="missing volume task fails",
        )
        assert "not found" in run_command(env, "task.list")
    finally:
        w.stop()
        env.close()


def test_requeue_on_worker_death(cluster):
    master, vs = cluster
    _masters[master.port] = master
    ctrl = master.worker_control
    # no worker yet: task stays pending
    tid = ctrl.submit("ec_encode", 7777)
    time.sleep(0.8)
    assert ctrl._tasks[tid].state == "pending"
    # a worker without the capability is never picked
    w = start_worker(master.port, capabilities=("vacuum",))
    time.sleep(0.8)
    assert ctrl._tasks[tid].state == "pending"
    w.stop()


def test_scanner_detects_full_volumes(cluster):
    master, vs = cluster
    _masters[master.port] = master
    ops = Operations(f"localhost:{master.port}")
    try:
        fid = ops.upload(b"z" * 10_000)
        vid = FileId.parse(fid).volume_id
        vs.notify_new_volume(vid)  # push fresh size stats to the master
        wait_for(
            lambda: any(
                vid in n.volumes and n.volumes[vid].size > 0
                for n in master.topo.nodes.values()
            )
        )
        # nothing full yet at the real 30GB limit
        assert master.worker_control.scan_for_ec_candidates(
            master.topo, 0.9, master.topo.volume_size_limit
        ) == []
        # with a tiny synthetic limit the volume qualifies (polled: the
        # topology view can briefly lag the fresh heartbeat)
        wait_for(
            lambda: len(
                master.worker_control.scan_for_ec_candidates(
                    master.topo, 0.5, 1000
                )
            )
            >= 1,
            msg="scanner submits for the full volume",
        )
    finally:
        ops.close()
