"""Worker-fleet tests: registration, task dispatch, EC-encode execution,
requeue on worker death (reference test/plugin_workers in-process
harness technique)."""

import threading
import time

import pytest

from conftest import allocate_port as free_port
from conftest import wait_for
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.worker import Worker


@pytest.fixture
def cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield master, vs
    vs.stop()
    master.stop()


def start_worker(master_port, **kw) -> Worker:
    w = Worker(master=f"localhost:{master_port}", backend="cpu", **kw)
    threading.Thread(target=w.run, daemon=True).start()
    wait_for(
        lambda: w.worker_id in master_control(master_port)._workers,
        msg="worker registers",
    )
    return w


_masters = {}


def master_control(port):
    return _masters[port].worker_control


def test_worker_executes_ec_encode(cluster):
    master, vs = cluster
    _masters[master.port] = master
    ops = Operations(f"localhost:{master.port}")
    env = ShellEnv(f"localhost:{master.port}")
    w = start_worker(master.port)
    try:
        data = b"worker encodes me" * 3000
        fid = ops.upload(data)
        vid = FileId.parse(fid).volume_id
        out = run_command(env, f"task.submit -kind ec_encode -volumeId {vid}")
        assert "submitted" in out
        wait_for(
            lambda: "done" in run_command(env, "task.list"),
            msg="task completes",
        )
        # the volume is now EC-backed and still readable
        wait_for(
            lambda: any(
                vid in n.ec_shards for n in master.topo.nodes.values()
            )
        )
        assert ops.read(fid) == data
        # duplicate submits dedupe onto the finished/live task
        out1 = run_command(env, f"task.submit -kind vacuum -volumeId {vid}")
        out2 = run_command(env, f"task.submit -kind vacuum -volumeId {vid}")
        # (ids equal while the first is still live)
        assert "submitted" in out1 and "submitted" in out2
    finally:
        w.stop()
        env.close()
        ops.close()


def test_task_failure_reported(cluster):
    master, vs = cluster
    _masters[master.port] = master
    env = ShellEnv(f"localhost:{master.port}")
    w = start_worker(master.port)
    try:
        run_command(env, "task.submit -kind ec_encode -volumeId 424242")
        wait_for(
            lambda: "failed" in run_command(env, "task.list"),
            msg="missing volume task fails",
        )
        assert "not found" in run_command(env, "task.list")
    finally:
        w.stop()
        env.close()


def test_requeue_on_worker_death(cluster):
    master, vs = cluster
    _masters[master.port] = master
    ctrl = master.worker_control
    # no worker yet: task stays pending
    tid = ctrl.submit("ec_encode", 7777)
    time.sleep(0.8)
    assert ctrl._tasks[tid].state == "pending"
    # a worker without the capability is never picked
    w = start_worker(master.port, capabilities=("vacuum",))
    time.sleep(0.8)
    assert ctrl._tasks[tid].state == "pending"
    w.stop()


def test_scanner_detects_full_volumes(cluster):
    master, vs = cluster
    _masters[master.port] = master
    ops = Operations(f"localhost:{master.port}")
    try:
        fid = ops.upload(b"z" * 10_000)
        vid = FileId.parse(fid).volume_id
        vs.notify_new_volume(vid)  # push fresh size stats to the master
        wait_for(
            lambda: any(
                vid in n.volumes and n.volumes[vid].size > 0
                for n in master.topo.nodes.values()
            )
        )
        # nothing full yet at the real 30GB limit
        assert master.worker_control.scan_for_ec_candidates(
            master.topo, 0.9, master.topo.volume_size_limit
        ) == []
        # with a tiny synthetic limit the volume qualifies (polled: the
        # topology view can briefly lag the fresh heartbeat)
        wait_for(
            lambda: len(
                master.worker_control.scan_for_ec_candidates(
                    master.topo, 0.5, 1000
                )
            )
            >= 1,
            msg="scanner submits for the full volume",
        )
    finally:
        ops.close()


@pytest.fixture
def cluster2(tmp_path):
    """Two volume servers: the balance scanario needs somewhere to go."""
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    wait_for(
        lambda: len(master.topo.nodes) >= 2,
        msg="both volume servers register",
    )
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def test_balance_task_scanner_and_execution(cluster2):
    """Auto-scanner submits a balance task for the imbalanced node; the
    worker executes the move end to end (readonly -> copy -> delete at
    source) and the volume serves from its new home."""
    import grpc as _grpc

    from seaweedfs_tpu.pb import cluster_pb2 as pb
    from seaweedfs_tpu.pb import rpc as _rpc

    master, (a, b) = cluster2
    _masters[master.port] = master
    w = start_worker(master.port)
    try:
        # 3 volumes on A, none on B -> spread 3
        with _grpc.insecure_channel(f"localhost:{a.grpc_port}") as ch:
            stub = _rpc.volume_stub(ch)
            for vid in (31, 32, 33):
                stub.AllocateVolume(
                    pb.AllocateVolumeRequest(volume_id=vid, replication="000"),
                    timeout=10,
                )
                stub.WriteNeedle(
                    pb.WriteNeedleRequest(
                        volume_id=vid, needle_id=1, cookie=9,
                        data=b"move-me", is_replicate=True,
                    ),
                    timeout=10,
                )
        wait_for(
            lambda: any(
                len(n.volumes) >= 3 for n in master.topo.nodes.values()
            ),
            msg="master sees the three volumes",
        )
        submitted = master.worker_control.scan_for_balance_candidates(
            master.topo, spread=2
        )
        assert len(submitted) == 1
        tid = submitted[0]
        wait_for(
            lambda: master.worker_control._tasks[tid].state == "done",
            timeout=60,
            msg=f"balance task finishes "
            f"({master.worker_control._tasks[tid].error})",
        )
        moved_vid = master.worker_control._tasks[tid].volume_id
        # the volume now lives on B and is readable there
        assert b.store.find_volume(moved_vid) is not None
        assert a.store.find_volume(moved_vid) is None
        n = b.store.find_volume(moved_vid).read_needle(1)
        assert n.data == b"move-me"
    finally:
        w.stop()


def test_s3_lifecycle_task_execution(cluster, tmp_path):
    """Worker executes an s3_lifecycle task: expired objects are swept
    by the filer the task points at."""
    import json as _json

    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.filer.entry import new_entry
    from seaweedfs_tpu.server.filer_server import FilerServer

    master, vs = cluster
    _masters[master.port] = master
    filer = Filer(MemoryStore(), master=f"localhost:{master.port}")
    fsrv = FilerServer(filer, ip="localhost", port=free_port())
    fsrv.start()
    w = start_worker(master.port)
    try:
        # a bucket with an already-expired object and a 1-day rule
        filer.create_entry(new_entry("/buckets/lc", is_directory=True))
        e = new_entry("/buckets/lc/old.txt")
        e.attr.mtime = int(time.time()) - 10 * 86400
        filer.create_entry(e)
        filer.store.kv_put(
            b"lifecycle-rules/lc",
            _json.dumps(
                [{"Status": "Enabled", "Prefix": "", "ExpirationDays": 1}]
            ).encode(),
        )
        tid = master.worker_control.submit(
            "s3_lifecycle", 0,
            params={"filer": f"localhost:{fsrv.grpc_port}"},
        )
        wait_for(
            lambda: master.worker_control._tasks[tid].state == "done",
            timeout=30,
            msg=f"lifecycle task finishes "
            f"({master.worker_control._tasks[tid].error})",
        )
        from seaweedfs_tpu.filer.filer_store import NotFound

        with pytest.raises(NotFound):
            filer.find_entry("/buckets/lc/old.txt")
        # periodic trigger path submits through the same scanner
        ids = master.worker_control.scan_for_lifecycle(
            f"localhost:{fsrv.grpc_port}"
        )
        assert len(ids) == 1
    finally:
        w.stop()
        fsrv.stop()
        filer.close()


def test_ec_balance_task(cluster2, tmp_path):
    """Worker executes ec_balance end to end: after an EC encode lands
    every shard on one node, the task spreads them (reference worker
    tasks/ec_balance)."""
    master, (a, b) = cluster2
    _masters[master.port] = master
    ops = Operations(f"localhost:{master.port}")
    env = ShellEnv(f"localhost:{master.port}")
    w = start_worker(master.port)
    try:
        fid = ops.upload(b"spread-me" * 4096)
        vid = FileId.parse(fid).volume_id
        run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        wait_for(
            lambda: any(
                n.ec_shards for n in master.topo.nodes.values()
            ),
            msg="master sees EC shards",
        )
        # the auto-scanner sees the 14-0 imbalance and submits the task
        submitted = master.worker_control.scan_for_ec_balance(master.topo)
        assert len(submitted) == 1
        tid = submitted[0]
        # direct submit dedupes onto the live scanner task
        assert master.worker_control.submit("ec_balance", 0) == tid
        task = master.worker_control._tasks[tid]
        wait_for(
            lambda: task.state in ("done", "failed"),
            timeout=120,
            msg="ec_balance reaches a terminal state",
        )
        assert task.state == "done", task.error
        # the shards now live on BOTH nodes
        counts = []
        with master.topo._lock:
            for n in master.topo.nodes.values():
                bits = 0
                for e in getattr(n, "ec_shards", {}).values():
                    if e.id == vid:
                        bits += bin(e.shard_bits).count("1")
                counts.append(bits)
        assert sorted(counts)[-1] < 14, counts  # no longer all on one node
        assert sum(counts) >= 14, counts
        # balanced cluster: the scanner goes quiet
        assert master.worker_control.scan_for_ec_balance(master.topo) == []
    finally:
        w.stop()
        ops.close()
        env.close()
