"""Volume tiering tests: backend SPI, cold-tier upload/download, reads
served from the cold tier with the .idx local.

Reference models: weed/storage/backend/backend.go,
weed/server/volume_grpc_tier_upload.go / tier_download.go. The cold
tier here is the framework's own S3 gateway — tiering onto itself.
"""

import os
import time

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.s3 import S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import ReadOnlyError, Volume, VolumeError

from conftest import allocate_port as free_port


@pytest.fixture(scope="module")
def cold_tier(tmp_path_factory):
    """master + volume + filer + S3 gateway = the cold-tier endpoint."""
    tmp = tmp_path_factory.mktemp("coldvol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    filer = Filer(MemoryStore(), master=f"localhost:{mport}", chunk_size=256 * 1024)
    s3 = S3Server(filer, ip="localhost", port=free_port(), lifecycle_interval=0)
    s3.start()
    url = f"http://localhost:{s3.port}"
    requests.put(f"{url}/cold")
    yield url, mport
    s3.stop()
    filer.close()
    vs.stop()
    master.stop()


def _fill_volume(tmp_path, vid=7, n=40):
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, n + 1):
        data = bytes((i * 7 + j) % 256 for j in range(1000 + i * 37))
        v.write_needle(Needle(cookie=0x1111 + i, needle_id=i, data=data))
        payloads[i] = data
    return v, payloads


def test_tier_upload_read_download(cold_tier, tmp_path):
    url, _ = cold_tier
    v, payloads = _fill_volume(tmp_path)
    dest = f"{url}/cold/vol7.dat"
    # tiering requires a sealed volume
    with pytest.raises(VolumeError):
        v.tier_upload(dest)
    v.set_read_only(True)
    moved = v.tier_upload(dest)
    assert moved > 0
    assert v.is_tiered
    assert not os.path.exists(v.dat_path)
    assert os.path.exists(v.idx_path)  # index stays local
    # the cold object is a byte-exact .dat
    assert int(requests.head(dest).headers["Content-Length"]) == moved
    # reads come from the cold tier via ranged GETs
    for i, data in payloads.items():
        assert v.read_needle(i).data == data
    # writes refused while tiered
    with pytest.raises(ReadOnlyError):
        v.write_needle(Needle(cookie=1, needle_id=999, data=b"x"))
    with pytest.raises(VolumeError):
        v.set_read_only(False)
    with pytest.raises(VolumeError):
        v.vacuum()
    # bring it back
    fetched = v.tier_download()
    assert fetched == moved
    assert not v.is_tiered
    assert os.path.exists(v.dat_path)
    for i, data in payloads.items():
        assert v.read_needle(i).data == data
    # writable again after download
    v.set_read_only(False)
    v.write_needle(Needle(cookie=2, needle_id=500, data=b"post-download"))
    assert v.read_needle(500).data == b"post-download"
    v.close()


def test_tiered_volume_survives_reopen(cold_tier, tmp_path):
    """Restart path: a .vif with tier info and no .dat mounts in remote
    mode (reference volume_tier.go load)."""
    url, _ = cold_tier
    v, payloads = _fill_volume(tmp_path, vid=8, n=10)
    dest = f"{url}/cold/vol8.dat"
    v.set_read_only(True)
    v.tier_upload(dest)
    v.close()
    # fresh open — simulates a volume-server restart
    v2 = Volume(str(tmp_path), 8, create=False)
    assert v2.is_tiered and v2.read_only
    for i, data in payloads.items():
        assert v2.read_needle(i).data == data
    v2.close()


def test_store_mounts_tiered_volume(cold_tier, tmp_path):
    """DiskLocation.load_existing discovers cold-tiered volumes by
    their .vif even with no local .dat."""
    from seaweedfs_tpu.storage.store import DiskLocation

    url, _ = cold_tier
    v, payloads = _fill_volume(tmp_path, vid=9, n=5)
    v.set_read_only(True)
    v.tier_upload(f"{url}/cold/vol9.dat")
    v.close()
    loc = DiskLocation(directory=str(tmp_path))
    loc.load_existing()
    assert 9 in loc.volumes
    assert loc.volumes[9].is_tiered
    assert loc.volumes[9].read_needle(3).data == payloads[3]
    for vol in loc.volumes.values():
        vol.close()


def test_tier_rpc_and_cluster_read(cold_tier, tmp_path):
    """End-to-end: grow a volume in a live cluster, tier it via the
    gRPC RPC, and read a blob back over plain HTTP (served from the
    cold tier)."""
    import grpc

    from seaweedfs_tpu.client.master_client import MasterClient
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.pb import cluster_pb2 as pb
    from seaweedfs_tpu.pb import rpc

    url, mport = cold_tier
    ops = Operations(f"localhost:{mport}")
    fid = ops.upload(b"cold blob payload " * 100)
    vid = int(fid.split(",")[0])
    mc = MasterClient(f"localhost:{mport}")
    loc = mc.lookup(vid, refresh=True)[0]
    target = f"{loc.url.split(':')[0]}:{loc.grpc_port}"
    with grpc.insecure_channel(target) as ch:
        stub = rpc.volume_stub(ch)
        stub.VolumeMarkReadonly(
            pb.VolumeCommandRequest(volume_id=vid), timeout=30
        )
        r = stub.VolumeTierUpload(
            pb.TierRequest(volume_id=vid, dest_url=f"{url}/cold/clu{vid}.dat"),
            timeout=600,
        )
        assert r.error == "", r.error
        assert r.moved_bytes > 0
    # data-plane read now rides the cold tier
    resp = requests.get(f"http://{loc.url}/{fid}")
    assert resp.status_code == 200
    assert resp.content == b"cold blob payload " * 100
    # and back down
    with grpc.insecure_channel(target) as ch:
        stub = rpc.volume_stub(ch)
        r = stub.VolumeTierDownload(
            pb.TierRequest(volume_id=vid, delete_remote=True), timeout=600
        )
        assert r.error == "" and r.moved_bytes > 0
        stub.VolumeMarkWritable(
            pb.VolumeCommandRequest(volume_id=vid), timeout=30
        )
    assert requests.get(f"http://{loc.url}/{fid}").content == (
        b"cold blob payload " * 100
    )
    ops.close()
    mc.close()


# ------------------------------------------- streaming PUT regression


def test_sized_reader_bounds_every_chunk():
    """_SizedReader never materializes more than _CHUNK bytes per read,
    even when the HTTP stack asks for the whole body at once — the
    memory bound a multi-GiB tier upload relies on."""
    import io

    from seaweedfs_tpu.storage import backend as B

    body = os.urandom(3 * B._CHUNK // 2)
    r = B._SizedReader(io.BytesIO(body), len(body))
    assert len(r) == len(body)
    pieces = []
    while True:
        piece = r.read(-1)  # "give me everything"
        if not piece:
            break
        assert len(piece) <= B._CHUNK
        pieces.append(piece)
    assert b"".join(pieces) == body
    assert len(pieces) >= 2  # the bound actually split the body
    assert r.read() == b""  # drained reader stays drained


def test_sized_reader_truncated_source_raises():
    """A source that runs dry before the promised size raises instead
    of silently sending a short Content-Length body the endpoint would
    stall on."""
    import io

    from seaweedfs_tpu.storage import backend as B

    r = B._SizedReader(io.BytesIO(b"only-ten-b"), 1000)
    assert r.read(10) == b"only-ten-b"
    with pytest.raises(B.BackendError, match="truncated: 990 of 1000"):
        r.read(10)
