"""ISSUE 9: gateway-to-chip observability.

Cross-protocol trace continuity over REAL spawned HTTP servers (S3
gateway + filer server + volume server on ephemeral ports): one S3 GET
against a degraded EC volume must yield a SINGLE trace id spanning the
s3/filer/volume layers down to the EC reconstruction, and the response
must echo the id. Plus: the heartbeat telemetry plane (master
/cluster/status + sw_ec_queue_load learned only from heartbeats), the
/debug/slo surface, /debug/traces op/min_ms filters, and the
span-budget ring bound.
"""

from __future__ import annotations

import json
import os
import time

import pytest
import requests

from conftest import allocate_port as free_port

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.pb import rpc as _rpc
from seaweedfs_tpu.s3 import S3Server
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.utils import metrics as M
from seaweedfs_tpu.utils import trace


def _wait(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        assert time.time() < deadline, f"timed out: {msg}"
        time.sleep(0.05)


@pytest.fixture(scope="module")
def gateway(tmp_path_factory):
    """Master + volume + filer + S3 servers (real HTTP/gRPC, ephemeral
    ports) over ONE object on a DEGRADED EC volume (shard 0 unmounted).
    Yields a dict of the live pieces."""
    tmp = tmp_path_factory.mktemp("gwtrace")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    _wait(lambda: master.topo.nodes, msg="volume registration")

    filer = Filer(
        MemoryStore(), master=f"localhost:{mport}", chunk_size=64 * 1024
    )
    fsrv = FilerServer(filer, ip="localhost", port=free_port())
    fsrv.start()
    s3 = S3Server(filer, ip="localhost", port=free_port())
    s3.start()
    base = f"http://localhost:{s3.port}"

    assert requests.put(f"{base}/b1").status_code == 200
    data = os.urandom(150_000)
    assert requests.put(f"{base}/b1/obj", data=data).status_code == 200
    entry = filer.find_entry("/buckets/b1/obj")
    vid = FileId.parse(entry.chunks[0].fid).volume_id
    env = ShellEnv(f"localhost:{mport}")
    try:
        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        assert "generation" in out, out
    finally:
        env.close()
    _wait(
        lambda: any(
            vid in n.ec_shards for n in master.topo.nodes.values()
        ),
        msg="ec shards via heartbeat",
    )
    # degrade: unmount one data shard — reads of its stripe must now
    # run a verified RS reconstruction on the volume server
    import grpc

    with grpc.insecure_channel(f"localhost:{vs.grpc_port}") as ch:
        _rpc.volume_stub(ch).VolumeEcShardsUnmount(
            pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=[0])
        )

    yield {
        "master": master,
        "mport": mport,
        "vs": vs,
        "filer": filer,
        "fsrv": fsrv,
        "s3_base": base,
        "filer_base": f"http://localhost:{fsrv.port}",
        "data": data,
        "vid": vid,
    }

    s3.stop()
    fsrv.stop()
    filer.close()
    vs.stop()
    master.stop()


@pytest.fixture
def recorder():
    trace.configure(
        enabled=True, ring_size=512,
        ring_spans=trace.DEFAULT_RING_SPANS, slow_op_s=0.0,
    )
    trace.reset()
    yield trace
    trace.configure(
        enabled=False, slow_op_s=0.0,
        ring_spans=trace.DEFAULT_RING_SPANS,
    )
    trace.reset()


def _walk(doc):
    yield doc
    for c in doc["children"]:
        yield from _walk(c)


def _settled_traces(tid: str, want_servers: set, timeout: float = 5.0):
    """An HTTP root span is recorded AFTER the response bytes reach the
    client (`_sw_finish_request` runs post-flush), so a ring read
    immediately after `requests.get` returns can miss the outer roots
    under scheduler load — poll until every expected layer landed."""
    deadline = time.time() + timeout
    while True:
        docs = trace.traces(tid)
        servers = {
            n.get("server") or "" for d in docs for n in _walk(d)
        }
        if want_servers <= servers or time.time() > deadline:
            return docs
        time.sleep(0.01)


# ------------------------------------------------- cross-protocol trace


def test_degraded_s3_get_yields_one_trace(gateway, recorder):
    """THE acceptance path: one S3 GET on a degraded EC volume -> one
    trace id across the s3 / filer / volume layers, an
    ec.degraded_read span below the volume server, the gateway stages
    attributed, and the trace id echoed on the response."""
    gw = gateway
    # drop the filer chunk cache so the GET actually crosses to the
    # volume server instead of serving from the gateway's LRU
    gw["filer"].chunk_cache.clear()

    r = requests.get(f"{gw['s3_base']}/b1/obj")
    assert r.status_code == 200 and r.content == gw["data"]
    tid = r.headers.get(trace.TRACE_ID_HEADER)
    assert tid, "response must echo the trace id"
    assert r.headers.get("X-Request-ID")

    docs = _settled_traces(tid, {"s3", "filer", "volume"})
    assert docs, "trace ring must hold the roots for the echoed id"
    servers, ops, stages = set(), set(), set()
    for d in docs:
        for node in _walk(d):
            assert node["trace_id"] == tid
            servers.add(node.get("server") or "")
            ops.add(node["op"])
            stages.update(node["stages"])
    # all three layers in ONE trace
    assert {"s3", "filer", "volume"} <= servers, servers
    # gateway handler -> chip: the degraded reconstruction is in-trace
    assert "ec.degraded_read" in ops, ops
    assert {"http.s3", "http.volume", "filer.read"} <= ops, ops
    # the budget split the issue names
    assert {
        "s3.auth", "filer.lookup", "chunk.fetch", "volume.read",
    } <= stages, stages
    # every stage label is canonical (the registry the lint enforces)
    assert stages <= trace.STAGES, stages - trace.STAGES
    # the volume-server roots are children of the filer's chunk fetch:
    # adopted parents must be spans of the SAME trace
    vol_roots = [d for d in docs if d["op"] == "http.volume"]
    assert vol_roots
    all_span_ids = {
        n["span_id"] for d in docs for n in _walk(d)
    }
    for d in vol_roots:
        assert d["parent_span_id"] in all_span_ids, (
            "volume root must link to a filer-side parent span"
        )


def test_client_supplied_trace_id_is_adopted(gateway, recorder):
    """A caller-minted trace id (header) is adopted by the filer HTTP
    server and propagated to the volume server — client-side tracing
    joins server-side rings."""
    gw = gateway
    gw["filer"].chunk_cache.clear()
    tid = "feedc0de12345678"
    r = requests.get(
        f"{gw['filer_base']}/buckets/b1/obj",
        headers={trace.TRACE_ID_HEADER: tid},
    )
    assert r.status_code == 200 and r.content == gw["data"]
    assert r.headers.get(trace.TRACE_ID_HEADER) == tid
    docs = _settled_traces(tid, {"filer", "volume"})
    servers = {
        n.get("server") for d in docs for n in _walk(d)
    }
    assert {"filer", "volume"} <= servers, servers


def test_request_id_still_rides_disarmed(gateway):
    """Tracer OFF: no trace header, no spans, but X-Request-ID still
    propagates and echoes (the PR 7 contract is not regressed)."""
    assert not trace.armed
    gw = gateway
    r = requests.get(
        f"{gw['s3_base']}/b1/obj", headers={"X-Request-ID": "req-42"}
    )
    assert r.status_code == 200
    assert r.headers.get("X-Request-ID") == "req-42"
    assert trace.TRACE_ID_HEADER not in r.headers


# ------------------------------------------------------ debug surfaces


def test_debug_traces_op_and_min_ms_filters(gateway, recorder):
    gw = gateway
    gw["filer"].chunk_cache.clear()
    assert requests.get(f"{gw['s3_base']}/b1/obj").status_code == 200
    vbase = f"http://localhost:{gw['vs'].port}"
    docs = requests.get(
        f"{vbase}/debug/traces?format=spans&op=http.volume"
    ).json()
    assert docs and all(d["op"] == "http.volume" for d in docs)
    assert requests.get(
        f"{vbase}/debug/traces?format=spans&min_ms=9999999"
    ).json() == []
    # chrome export respects the same filters
    chrome = requests.get(
        f"{vbase}/debug/traces?op=http.volume"
    ).json()
    assert chrome["traceEvents"]


def test_slo_endpoint_all_servers(gateway):
    gw = gateway
    # prime each server with at least one completed request
    requests.get(f"http://localhost:{gw['mport']}/cluster/status")
    requests.get(f"{gw['s3_base']}/b1/obj")
    for base, kind in (
        (gw["filer_base"], "filer."),
        (f"http://localhost:{gw['vs'].port}", "volume."),
        (f"http://localhost:{gw['mport']}", "master."),
    ):
        slo = requests.get(f"{base}/debug/slo").json()
        assert any(k.startswith(kind) for k in slo), (kind, list(slo))
        for s in slo.values():
            assert {"count", "mean_ms", "p50_ms", "p90_ms", "p99_ms"} <= set(s)
            assert s["p50_ms"] <= s["p99_ms"] + 1e-9
    # the S3 DATA plane does not expose /debug/slo (a bucket named
    # "debug" stays addressable; status must not bypass SigV4) — its op
    # classes surface through co-resident servers' endpoints instead
    slo = requests.get(
        f"http://localhost:{gw['vs'].port}/debug/slo"
    ).json()
    assert any(k.startswith("s3.") for k in slo)
    r = requests.get(f"{gw['s3_base']}/debug/slo")
    assert r.status_code != 200 or r.headers.get(
        "Content-Type", ""
    ).startswith("application/xml")


def test_request_seconds_histogram_populated(gateway):
    text = M.REGISTRY.render().decode()
    assert 'sw_request_seconds_count{server="s3",op="get_object"}' in text
    assert 'server="volume",op="read"' in text


# -------------------------------------------------- telemetry plane


def test_heartbeat_telemetry_reaches_master(gateway):
    """Per-host chip load / breaker state appears in /cluster/status
    and the sw_ec_queue_load gauge, learned ONLY from heartbeats (the
    master never probes the volume server)."""
    gw = gateway
    node_id = f"localhost:{gw['vs'].port}"

    def master_has_tele():
        st = requests.get(
            f"http://localhost:{gw['mport']}/cluster/status"
        ).json()
        tele = st.get("EcTelemetry", {})
        return node_id in tele and tele[node_id].get("chips")

    _wait(master_has_tele, timeout=10, msg="telemetry via heartbeat")
    st = requests.get(
        f"http://localhost:{gw['mport']}/cluster/status"
    ).json()
    tele = st["EcTelemetry"][node_id]
    assert {"chips", "breakers_open", "degraded"} <= set(tele)
    for chip, c in tele["chips"].items():
        assert "load" in c and "breaker" in c
    # matches what the node itself would report (single source)
    local = json.loads(gw["vs"]._ec_telemetry_json())
    assert set(local["chips"]) == set(tele["chips"])
    # fleet gauge renders per node+chip
    mtx = requests.get(
        f"http://localhost:{gw['mport']}/metrics"
    ).text
    assert f'sw_ec_queue_load{{node="{node_id}"' in mtx
    assert f'sw_ec_fleet_breakers_open{{node="{node_id}"}}' in mtx


def test_chip_load_hint_read_only(gateway):
    """chip_load_hint reads the scope's existing queues without
    creating any; shape = {chip: {load, breaker}}."""
    from seaweedfs_tpu.ec.chip_pool import chip_load_hint

    scope = gateway["vs"].store.ec_scheduler
    before = len(scope._queues)
    hint = chip_load_hint(scope)
    assert len(scope._queues) == before
    for chip, c in hint.items():
        assert isinstance(c["load"], int) and "breaker" in c


def test_shell_cluster_status_shows_telemetry(gateway):
    env = ShellEnv(f"localhost:{gateway['mport']}")
    try:
        out = run_command(env, "cluster.status")
    finally:
        env.close()
    assert "chips localhost" in out, out
    assert "slo (master, ms):" in out, out


# ------------------------------------------------- span-budget ring


def test_ring_is_span_budget_bounded(recorder):
    """A span-heavy op class cannot pin an unbounded share of memory:
    the ring evicts oldest docs once the TOTAL retained span count
    exceeds the budget, trace-count bound notwithstanding."""
    trace.configure(ring_size=256, ring_spans=50)
    for i in range(20):
        sp = trace.Span("ec.encode", name=f"heavy{i}")
        for _ in range(9):
            sp.child("ec.peer_fetch")
        sp.finish()
    docs = trace.traces()
    total = sum(d["span_count"] for d in docs)
    assert total <= 50, total
    assert len(docs) == 5  # 10 spans per doc -> the 5 newest fit
    assert docs[-1]["name"] == "heavy19"
    # the newest doc is always kept even if alone it exceeds the budget
    trace.configure(ring_spans=3)
    sp = trace.Span("ec.encode", name="huge")
    for _ in range(9):
        sp.child("ec.peer_fetch")
    sp.finish()
    docs = trace.traces()
    assert [d["name"] for d in docs] == ["huge"]


def test_slow_op_tree_carries_rid_and_root_op(recorder, capfd):
    """Slow-op log satellite: the logged span tree itself carries the
    request id and root op, so a tree separated from its log prefix
    still joins against gateway access logs."""
    from seaweedfs_tpu.utils import request_id as rid

    trace.configure(slow_op_s=0.001)
    rid.ensure("rid-join-1")
    try:
        sp = trace.start("ec.rebuild", name="slowtree")
        with trace.stage(sp, "disk_read"):
            time.sleep(0.01)
        trace.finish(sp)
    finally:
        rid.clear()
    err = capfd.readouterr().err
    assert "rid=rid-join-1" in err
    assert "root=ec.rebuild" in err
