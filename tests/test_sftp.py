"""SFTP gateway (sftpd/): SSH transport + SFTP v3 over the filer.

Mirrors the reference's test/sftp: full file CRUD through a real SSH
connection, per-user jails and read-only enforcement, and transport
security properties (host key verification, MAC integrity).
"""

import threading
import time

import pytest

from conftest import allocate_port
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_store import MemoryStore
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.sftpd import SftpServer
from seaweedfs_tpu.sftpd.sftp_client import SftpClient, SftpStatusError
from seaweedfs_tpu.sftpd.sftp_server import FX_PERMISSION_DENIED, SftpUser
from seaweedfs_tpu.sftpd.ssh_transport import SshError


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sftp")
    mport = allocate_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=allocate_port(),
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def filer(cluster):
    f = Filer(MemoryStore(), master=f"localhost:{cluster}")
    yield f
    f.close()


@pytest.fixture
def server(filer):
    srv = SftpServer(
        filer,
        ip="127.0.0.1",
        port=0,
        users={
            "alice": SftpUser("alice", "pw-a", home="/alice"),
            "bob": SftpUser("bob", "pw-b", home="/", read_only=True),
        },
    )
    srv.start()
    yield srv
    srv.stop()


def _connect(server, user="alice", password="pw-a") -> SftpClient:
    return SftpClient("127.0.0.1", server.port, user, password)


def test_auth_and_host_key(server):
    c = _connect(server)
    assert c.host_public_key == server.host_public_key
    assert c.realpath(".") == "/"
    c.close()
    with pytest.raises(SshError, match="auth"):
        _connect(server, "alice", "wrong")
    with pytest.raises(SshError, match="auth"):
        _connect(server, "nobody", "pw")


def test_file_round_trip_and_listing(server, filer):
    c = _connect(server)
    try:
        c.mkdir("/docs")
        payload = b"hello over ssh\n" * 1000
        c.write_file("/docs/readme.txt", payload)
        assert c.read_file("/docs/readme.txt") == payload
        assert c.stat("/docs/readme.txt")["size"] == len(payload)
        assert c.listdir("/docs") == ["readme.txt"]
        # the jail maps /docs to /alice/docs in the filer namespace
        entry = filer.find_entry("/alice/docs/readme.txt")
        assert entry.file_size == len(payload)
        # rename + remove
        c.rename("/docs/readme.txt", "/docs/moved.txt")
        assert c.listdir("/docs") == ["moved.txt"]
        c.remove("/docs/moved.txt")
        assert c.listdir("/docs") == []
        c.rmdir("/docs")
        with pytest.raises(SftpStatusError):
            c.stat("/docs")
    finally:
        c.close()


def test_multi_chunk_write_and_random_read(server):
    c = _connect(server)
    try:
        data = bytes(range(256)) * 2048  # 512 KiB, multi-chunk both ways
        c.write_file("/big.bin", data, chunk=17_000)
        assert c.read_file("/big.bin", chunk=23_000) == data
    finally:
        c.close()


def test_jail_cannot_escape(server, filer):
    filer.write_file("/secret.txt", b"top secret")
    c = _connect(server)  # alice is jailed to /alice
    try:
        with pytest.raises(SftpStatusError):
            c.read_file("/../secret.txt")
        with pytest.raises(SftpStatusError):
            c.read_file("/secret.txt")  # resolves inside the jail
        # and the jail root realpath stays "/"
        assert c.realpath("/../..") == "/"
    finally:
        c.close()


def test_read_only_user(server, filer):
    filer.write_file("/public.txt", b"readable")
    c = _connect(server, "bob", "pw-b")
    try:
        assert c.read_file("/public.txt") == b"readable"
        with pytest.raises(SftpStatusError) as ei:
            c.write_file("/nope.txt", b"x")
        assert ei.value.code == FX_PERMISSION_DENIED
        with pytest.raises(SftpStatusError):
            c.remove("/public.txt")
        with pytest.raises(SftpStatusError):
            c.mkdir("/newdir")
    finally:
        c.close()


def test_concurrent_sessions(server):
    errs = []

    def session(i: int):
        try:
            c = _connect(server)
            c.write_file(f"/c{i}.txt", b"x" * (i + 1) * 1000)
            assert len(c.read_file(f"/c{i}.txt")) == (i + 1) * 1000
            c.close()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=session, args=(i,)) for i in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert errs == []


def test_rekey_mid_session(server):
    """A client-initiated re-key (OpenSSH does this every few GB) must
    be answered, and the session must keep working on the new keys."""
    c = _connect(server)
    try:
        c.write_file("/pre.txt", b"before rekey")
        c.t.rekey_client()
        assert c.read_file("/pre.txt") == b"before rekey"
        c.write_file("/post.txt", b"after rekey")
        assert c.read_file("/post.txt") == b"after rekey"
    finally:
        c.close()


def test_tampered_traffic_fails_mac(server):
    """Flipping ciphertext bits must kill the session, not corrupt data."""
    import socket as sock_mod

    from seaweedfs_tpu.sftpd.ssh_transport import SshTransport

    raw = sock_mod.create_connection(("127.0.0.1", server.port), timeout=10)
    t = SshTransport(raw, server_side=False)
    t.kex_client()
    # handshake ok; now corrupt one encrypted byte mid-stream by sending
    # garbage bytes directly — the server must MAC-fail and drop us, so
    # our next read sees a closed/han-gup socket rather than data
    raw.sendall(b"\x00" * 64)
    raw.settimeout(10)
    # the server must MAC-fail and DROP the connection: the only
    # acceptable outcome is a clean close (recv -> b"") or a reset —
    # any response bytes would mean it processed forged traffic
    try:
        while True:
            data = raw.recv(1024)
            assert data == b"", f"server responded to tampered bytes: {data[:32]!r}"
            break
    except (ConnectionResetError, OSError):
        pass
    raw.close()
