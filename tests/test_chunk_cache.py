"""Chunk cache tests + filer read-path integration + auto-EC scanner
wiring (reference weed/util/chunk_cache, admin maintenance loop), plus
the ISSUE 11 read-through/singleflight layer (get_or_load)."""

import threading
import time

from conftest import allocate_port as free_port
from seaweedfs_tpu.utils.chunk_cache import ChunkCache, SingleFlight


def test_lru_eviction_and_bounds():
    c = ChunkCache(capacity_bytes=1000)
    c.put("a", b"x" * 400)
    c.put("b", b"y" * 400)
    assert c.get("a") == b"x" * 400  # refresh a
    c.put("c", b"z" * 400)  # evicts b (LRU)
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("c") is not None
    assert c.size_bytes <= 1000
    # oversized items are rejected, not cached
    c.put("huge", b"q" * 2000)
    assert c.get("huge") is None
    # replacement updates accounting
    c.put("a", b"small")
    assert c.get("a") == b"small"
    c.drop("a")
    assert c.get("a") is None


def test_get_or_load_hit_load_and_admission():
    c = ChunkCache(capacity_bytes=1000)
    calls = []

    def loader():
        calls.append(1)
        return b"v" * 10

    data, src = c.get_or_load("k", loader)
    assert (data, src, len(calls)) == (b"v" * 10, "load", 1)
    data, src = c.get_or_load("k", loader)
    assert (data, src, len(calls)) == (b"v" * 10, "hit", 1)
    # admit=False keeps the result OUT of the cache: next call loads
    data, src = c.get_or_load("big", loader, admit=lambda d: False)
    assert src == "load"
    data, src = c.get_or_load("big", loader)
    assert src == "load" and len(calls) == 3


def test_singleflight_collapses_concurrent_misses():
    """K concurrent misses on ONE key -> exactly one loader call, every
    caller byte-identical (the tentpole's reconstruction-collapse
    contract, unit-level)."""
    c = ChunkCache(capacity_bytes=1 << 20)
    gate = threading.Event()
    loads = []
    load_lock = threading.Lock()

    def loader():
        with load_lock:
            loads.append(threading.get_ident())
        gate.wait(5)  # hold every concurrent caller in the same flight
        return b"payload-bytes"

    results = []
    res_lock = threading.Lock()

    def reader():
        data, src = c.get_or_load("hot", loader)
        with res_lock:
            results.append((data, src))

    threads = [threading.Thread(target=reader) for _ in range(8)]
    for t in threads:
        t.start()
    # let everyone pile onto the flight, then release the leader
    deadline = time.time() + 5
    while len(loads) == 0 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)
    gate.set()
    for t in threads:
        t.join(timeout=10)
    assert len(loads) == 1, "concurrent misses must collapse to ONE load"
    assert len(results) == 8
    assert all(d == b"payload-bytes" for d, _ in results)
    srcs = [s for _, s in results]
    assert srcs.count("load") == 1 and srcs.count("wait") == 7
    assert c.singleflight_waits == 7
    # after the flight lands, it's a plain hit
    assert c.get_or_load("hot", loader)[1] == "hit"


def test_singleflight_leader_exception_propagates_to_waiters():
    c = ChunkCache(capacity_bytes=1 << 20)
    gate = threading.Event()

    def loader():
        gate.wait(5)
        raise RuntimeError("reconstruction refused")

    failures = []

    def reader():
        try:
            c.get_or_load("bad", loader)
        except RuntimeError as e:
            failures.append(str(e))

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    gate.set()
    for t in threads:
        t.join(timeout=10)
    # everyone saw the leader's refusal; NOBODY retried the loader
    # inside the flight (a failed verified reconstruction must not be
    # re-run by each waiter in turn)
    assert len(failures) == 4
    # the key is not poisoned: a later call runs a fresh loader
    assert c.get_or_load("bad", lambda: b"ok")[0] == b"ok"


def test_invalidation_fences_inflight_load():
    """A drop_matching/drop_prefix/clear racing an in-flight load must
    win: the leader's result goes to its callers but is NOT admitted —
    otherwise a reconstruction started over pre-patch bytes would
    repopulate the just-invalidated key with stale data."""
    c = ChunkCache(capacity_bytes=1 << 20)
    in_loader = threading.Event()
    release = threading.Event()

    def loader():
        in_loader.set()
        release.wait(5)
        return b"pre-patch-bytes"

    out = {}

    def reader():
        out["result"] = c.get_or_load("ns:2:0:0:1024", loader)

    t = threading.Thread(target=reader)
    t.start()
    assert in_loader.wait(5)
    # invalidation lands while the load is in flight
    dropped = c.drop_matching("ns:2:0:", lambda k: True)
    assert dropped == 0  # nothing cached yet — the fence is the point
    # a reader that begins strictly AFTER the invalidation must NOT
    # join the doomed flight: it runs its own (post-patch) loader and
    # its result IS cached
    data, src = c.get_or_load("ns:2:0:0:1024", lambda: b"post-patch")
    assert (data, src) == (b"post-patch", "load")
    assert c.get("ns:2:0:0:1024") == b"post-patch"
    release.set()
    t.join(timeout=10)
    data, src = out["result"]
    assert data == b"pre-patch-bytes" and src == "load"
    # the doomed leader's result went to ITS caller but must not have
    # clobbered the fresh post-invalidation entry
    assert c.get("ns:2:0:0:1024") == b"post-patch"


def test_get_or_load_zero_capacity_is_passthrough():
    """The cache-off (naive) configuration: no storage, no collapsing —
    every caller pays its own loader call."""
    c = ChunkCache(capacity_bytes=0)
    calls = []
    for _ in range(3):
        data, src = c.get_or_load("k", lambda: calls.append(1) or b"x")
        assert src == "load"
    assert len(calls) == 3


def test_singleflight_distinct_keys_run_concurrently():
    sf = SingleFlight()
    order = []
    gate = threading.Event()

    def slow(fl):
        order.append("slow-start")
        gate.wait(5)
        return "slow"

    t = threading.Thread(target=lambda: sf.do("a", slow))
    t.start()
    deadline = time.time() + 5
    while not order and time.time() < deadline:
        time.sleep(0.01)
    # a DIFFERENT key must not queue behind key "a"
    val, waited = sf.do("b", lambda fl: "fast")
    assert (val, waited) == ("fast", False)
    gate.set()
    t.join(timeout=10)


def test_eviction_under_get_or_load_budget():
    """The byte budget holds under read-through population: older keys
    fall out, the hot key stays."""
    c = ChunkCache(capacity_bytes=1000)
    for i in range(10):
        c.get_or_load(f"k{i}", lambda i=i: bytes([i]) * 300)
        c.get_or_load("k0", lambda: b"\x00" * 300)  # keep k0 hot
    assert c.size_bytes <= 1000
    assert c.get("k0") is not None, "hot key must survive the budget"
    assert c.get("k1") is None, "cold keys must be evicted"


def test_filer_read_path_uses_cache(tmp_path):
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    f = Filer(MemoryStore(), master=f"localhost:{mport}", chunk_size=16 * 1024)
    try:
        data = bytes(range(256)) * 300  # ~75KB -> 5 chunks
        f.write_file("/c/cached.bin", data)
        assert f.read_file("/c/cached.bin") == data
        misses_after_first = f.chunk_cache.misses
        assert f.read_file("/c/cached.bin") == data
        assert f.chunk_cache.misses == misses_after_first, "second read cached"
        assert f.chunk_cache.hits >= 5
    finally:
        f.close()
        vs.stop()
        master.stop()


def test_master_auto_ec_scanner(tmp_path):
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.storage.file_id import FileId

    mport = free_port()
    master = MasterServer(
        ip="localhost",
        port=mport,
        volume_size_limit=1000,  # tiny: any write crosses fullness
        vacuum_interval=0.3,
        ec_auto_fullness=0.5,
        ec_quiet_seconds=0.0,
    )
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ops = Operations(f"localhost:{mport}")
    try:
        fid = ops.upload(b"F" * 5000)
        vid = FileId.parse(fid).volume_id
        vs.notify_new_volume(vid)
        deadline = time.time() + 10
        while True:
            tasks = [
                t
                for t in master.worker_control._tasks.values()
                if t.kind == "ec_encode" and t.volume_id == vid
            ]
            if tasks:
                break
            assert time.time() < deadline, "scanner should submit ec task"
            time.sleep(0.1)
    finally:
        ops.close()
        vs.stop()
        master.stop()
