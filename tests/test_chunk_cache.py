"""Chunk cache tests + filer read-path integration + auto-EC scanner
wiring (reference weed/util/chunk_cache, admin maintenance loop)."""

import time

from conftest import allocate_port as free_port
from seaweedfs_tpu.utils.chunk_cache import ChunkCache


def test_lru_eviction_and_bounds():
    c = ChunkCache(capacity_bytes=1000)
    c.put("a", b"x" * 400)
    c.put("b", b"y" * 400)
    assert c.get("a") == b"x" * 400  # refresh a
    c.put("c", b"z" * 400)  # evicts b (LRU)
    assert c.get("b") is None
    assert c.get("a") is not None and c.get("c") is not None
    assert c.size_bytes <= 1000
    # oversized items are rejected, not cached
    c.put("huge", b"q" * 2000)
    assert c.get("huge") is None
    # replacement updates accounting
    c.put("a", b"small")
    assert c.get("a") == b"small"
    c.drop("a")
    assert c.get("a") is None


def test_filer_read_path_uses_cache(tmp_path):
    from seaweedfs_tpu.filer import Filer, MemoryStore
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    f = Filer(MemoryStore(), master=f"localhost:{mport}", chunk_size=16 * 1024)
    try:
        data = bytes(range(256)) * 300  # ~75KB -> 5 chunks
        f.write_file("/c/cached.bin", data)
        assert f.read_file("/c/cached.bin") == data
        misses_after_first = f.chunk_cache.misses
        assert f.read_file("/c/cached.bin") == data
        assert f.chunk_cache.misses == misses_after_first, "second read cached"
        assert f.chunk_cache.hits >= 5
    finally:
        f.close()
        vs.stop()
        master.stop()


def test_master_auto_ec_scanner(tmp_path):
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.storage.file_id import FileId

    mport = free_port()
    master = MasterServer(
        ip="localhost",
        port=mport,
        volume_size_limit=1000,  # tiny: any write crosses fullness
        vacuum_interval=0.3,
        ec_auto_fullness=0.5,
        ec_quiet_seconds=0.0,
    )
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ops = Operations(f"localhost:{mport}")
    try:
        fid = ops.upload(b"F" * 5000)
        vid = FileId.parse(fid).volume_id
        vs.notify_new_volume(vid)
        deadline = time.time() + 10
        while True:
            tasks = [
                t
                for t in master.worker_control._tasks.values()
                if t.kind == "ec_encode" and t.volume_id == vid
            ]
            if tasks:
                break
            assert time.time() < deadline, "scanner should submit ec task"
            time.sleep(0.1)
    finally:
        ops.close()
        vs.stop()
        master.stop()
