"""Zero-copy native data plane (ISSUE 10): bit-identity vs the Python
source/sink, fault routing, torn-write crash consistency, skip-clean
fallback, and the build-and-symbol tier-1 gate for native/.
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import native_io
from seaweedfs_tpu.ec.backend import CpuBackend
from seaweedfs_tpu.ec.bitrot import BitrotProtection, ShardChecksumBuilder
from seaweedfs_tpu.ec.context import ECContext, ECError
from seaweedfs_tpu.ec.encoder import write_ec_files
from seaweedfs_tpu.ec.pipeline import (
    FusedShardSink,
    PyShardSink,
    make_shard_sink,
)
from seaweedfs_tpu.ec.rebuild import rebuild_ec_files

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "native")

# The new C ABI this PR introduces; a stale .so missing any of these
# must FAIL tests (silent loss of the whole native plane), not skip.
NEW_SYMBOLS = [
    "sn_batch_pread",
    "sn_fadvise_willneed",
    "sn_crc32c_combine",
    "sn_sink_create",
    "sn_sink_append",
    "sn_sink_finish",
    "sn_sink_destroy",
    # ISSUE 12: network byte plane + O_DIRECT sink observability. Same
    # contract — a stale .so missing these silently disables the whole
    # native plane (the bindings in utils/native.py resolve at import),
    # so the gate fails loudly here instead.
    "sn_send_file",
    "sn_sendv",
    "sn_recv_into",
    "sn_sink_direct_flags",
    # ISSUE 13: env-tunable overlapped-recv core gate probe
    "sn_recv_overlap_active",
]


# --------------------------------------------------------------- tier-1
# build-and-symbol gate


def test_native_builds_and_new_symbols_resolve():
    """`make -C native/` must succeed and the freshly built .so must
    export the data-plane ABI — a host without the toolchain, or a
    stale library, fails here instead of silently running pure
    Python."""
    proc = subprocess.run(
        ["make", "-s", "-C", NATIVE_DIR],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"native build failed:\n{proc.stderr[-2000:]}"
    )
    lib = ctypes.CDLL(os.path.join(NATIVE_DIR, "libseaweed_native.so"))
    for sym in NEW_SYMBOLS + ["sn_crc32c", "sn_rs_apply", "sn_shard_append"]:
        assert getattr(lib, sym, None) is not None, f"missing symbol {sym}"


def test_import_failure_is_importerror(tmp_path):
    """Load-contract satellite: a failing `make` (no toolchain / broken
    sources) must surface as ImportError — the only exception callers
    are documented to tolerate — never CalledProcessError."""
    bad = tmp_path / "native"
    bad.mkdir()
    (bad / "Makefile").write_text("all:\n\tfalse\n")
    code = (
        "import sys\n"
        "try:\n"
        "    import seaweedfs_tpu.utils.native\n"
        "except ImportError:\n"
        "    sys.exit(0)\n"
        "except BaseException as e:\n"
        "    print('WRONG exception:', type(e).__name__)\n"
        "    sys.exit(2)\n"
        "sys.exit(3)  # import unexpectedly succeeded\n"
    )
    env = dict(os.environ, SEAWEED_NATIVE_DIR=str(bad))
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_stale_detects_any_native_source(tmp_path, monkeypatch):
    """_stale derives the source list from the directory, so a NEW
    source file (not just seaweed_native.cpp) triggers a rebuild."""
    from seaweedfs_tpu.utils import native

    d = tmp_path / "native"
    d.mkdir()
    (d / "Makefile").write_text("all:\n")
    so = d / "libseaweed_native.so"
    so.write_bytes(b"x")
    monkeypatch.setattr(native, "_NATIVE_DIR", str(d))
    monkeypatch.setattr(native, "_SO_PATH", str(so))
    assert not native._stale()
    extra = d / "new_kernel.cpp"
    extra.write_text("// new source")
    os.utime(extra, (os.path.getmtime(so) + 5, os.path.getmtime(so) + 5))
    assert native._stale()


# ------------------------------------------------------- bit identity

CTX64 = ECContext(4, 2)


def _make_dat(tmp_path, name, nbytes, seed=7):
    rng = np.random.default_rng(seed)
    base = str(tmp_path / name)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, nbytes, dtype=np.uint8).tobytes())
    return base


@pytest.mark.parametrize("leaf_size", [0, 64 * 1024])
@pytest.mark.parametrize("tail", [0, 12345])
def test_encode_native_vs_python_bit_identical(
    tmp_path, monkeypatch, leaf_size, tail
):
    """Same .dat, native plane vs SEAWEED_EC_NATIVE=0: shard bytes,
    sizes, block CRCs and (v2) leaf CRCs must match bit for bit —
    across ragged tails and both sidecar versions."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    nbytes = (6 << 20) + tail
    base_n = _make_dat(tmp_path, "vn", nbytes)
    base_p = _make_dat(tmp_path, "vp", nbytes)
    be = CpuBackend(CTX64)

    monkeypatch.setenv("SEAWEED_EC_NATIVE", "1")
    prot_n = write_ec_files(base_n, CTX64, be, leaf_size=leaf_size)
    monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
    prot_p = write_ec_files(base_p, CTX64, be, leaf_size=leaf_size)

    assert prot_n.shard_sizes == prot_p.shard_sizes
    assert prot_n.shard_crcs == prot_p.shard_crcs
    assert prot_n.shard_leaf_crcs == prot_p.shard_leaf_crcs
    assert prot_n.leaf_size == prot_p.leaf_size == leaf_size
    for i in range(CTX64.total):
        a = open(base_n + CTX64.to_ext(i), "rb").read()
        b = open(base_p + CTX64.to_ext(i), "rb").read()
        assert a == b, f"shard {i} differs"


def test_rebuild_native_vs_python_bit_identical(tmp_path, monkeypatch):
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    base = _make_dat(tmp_path, "v", (3 << 20) + 999)
    be = CpuBackend(CTX64)
    prot = write_ec_files(base, CTX64, be)
    prot.save(base + ".ecsum")
    originals = {
        i: open(base + CTX64.to_ext(i), "rb").read() for i in (1, 5)
    }
    for env in ("1", "0"):
        monkeypatch.setenv("SEAWEED_EC_NATIVE", env)
        for i in originals:
            os.unlink(base + CTX64.to_ext(i))
        got = rebuild_ec_files(base, CTX64, backend=be)
        assert sorted(got) == sorted(originals)
        for i, want in originals.items():
            assert open(base + CTX64.to_ext(i), "rb").read() == want


def test_rebuild_native_inline_crc_excludes_rotten_source(
    tmp_path, monkeypatch
):
    """The fused read+CRC (native roller) must drive the same
    verify-and-exclude envelope as the Python _BlockCrcRoller: a
    bit-flipped source is confirmed from disk, reclassified, and the
    rebuild succeeds without it."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    monkeypatch.setenv("SEAWEED_EC_NATIVE", "1")
    base = _make_dat(tmp_path, "v", 2 << 20)
    be = CpuBackend(CTX64)
    prot = write_ec_files(base, CTX64, be)
    prot.save(base + ".ecsum")
    good = open(base + CTX64.to_ext(0), "rb").read()
    with open(base + CTX64.to_ext(0), "r+b") as f:
        f.seek(4321)
        f.write(b"\xba\xad")
    os.unlink(base + CTX64.to_ext(5))
    got = rebuild_ec_files(base, CTX64, backend=be)
    assert set(got) >= {0, 5}
    assert open(base + CTX64.to_ext(0), "rb").read() == good


def test_native_sink_preserves_file_position(tmp_path):
    """The stateful sink pwrite(2)s at tracked offsets: the Python file
    object's position must stay untouched (flush/fsync/close safe)."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    files = [
        open(tmp_path / f"s{i}", "wb", buffering=0) for i in range(3)
    ]
    try:
        sink = FusedShardSink(files, block_size=4096, leaf_size=1024)
        rows = np.random.default_rng(1).integers(
            0, 256, (3, 5000), np.uint8
        )
        sink.append_rows(list(rows))
        sink.append_rows(list(rows))
        assert [f.tell() for f in files] == [0, 0, 0]
        assert sink.sizes == [10000] * 3
        sink._finish()
        for i, f in enumerate(files):
            f.close()
            got = open(tmp_path / f"s{i}", "rb").read()
            assert got == rows[i].tobytes() * 2
        files = []
    finally:
        for f in files:
            f.close()


def test_native_sink_dual_level_matches_builder(tmp_path):
    """One-pass leaf rolling + block folding == the two-level
    ShardChecksumBuilder, including partial-tail granules."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    f = open(tmp_path / "s0", "wb", buffering=0)
    try:
        sink = FusedShardSink([f], block_size=8192, leaf_size=2048)
        builder = ShardChecksumBuilder(8192, 2048)
        rng = np.random.default_rng(2)
        for width in (8192, 3000, 2048, 57):
            row = rng.integers(0, 256, width, np.uint8)
            sink.append_rows([row])
            builder.write(row.tobytes())
        assert sink.block_crcs() == [builder.finish()]
        assert sink.leaf_crcs() == [builder.finish_leaves()]
    finally:
        f.close()


# ---------------------------------------------------- fault machinery


def test_armed_registry_routes_python_plane(tmp_path):
    """Byte-mutating fault points need materialized bytes: with the
    registry ARMED the encode produce and the shard sink must take the
    Python plane — and the output stays bit-identical to the native
    run (the fallback IS the reference implementation)."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    base_n = _make_dat(tmp_path, "vn", 1 << 20)
    base_c = _make_dat(tmp_path, "vc", 1 << 20)
    be = CpuBackend(CTX64)
    write_ec_files(base_n, CTX64, be)

    faults.inject("test.native_plane.noop", lambda ctx: None)  # arm only
    try:
        assert faults.active()
        assert isinstance(
            make_shard_sink(
                [open(os.devnull, "wb")], prefer_fused=not faults.active()
            ),
            PyShardSink,
        )
        write_ec_files(base_c, CTX64, be)
    finally:
        faults.clear()
    for i in range(CTX64.total):
        assert (
            open(base_n + CTX64.to_ext(i), "rb").read()
            == open(base_c + CTX64.to_ext(i), "rb").read()
        )


def test_encode_fault_points_fire_on_native_path(tmp_path):
    """PR 1 crash-window fire points still run on the native plane:
    a raising ec.encode.before_fsync aborts the encode (shards present,
    no sidecar published by write_ec_files' caller)."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    base = _make_dat(tmp_path, "v", 1 << 20)

    class Boom(RuntimeError):
        pass

    def handler(ctx):
        raise Boom("crash window")

    faults.inject("ec.encode.before_fsync", handler)
    try:
        with pytest.raises(Boom):
            write_ec_files(base, CTX64, CpuBackend(CTX64))
    finally:
        faults.clear()


def test_torn_write_through_native_sink_is_caught(tmp_path, monkeypatch):
    """Crash-consistency: shards written by the native sink, then a
    torn write (truncated tail — the mid-pwrite power-cut shape).
    Rebuild's size-vs-sidecar gate must reclassify and regenerate the
    torn shard bit-exactly."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    monkeypatch.setenv("SEAWEED_EC_NATIVE", "1")
    base = _make_dat(tmp_path, "v", 2 << 20)
    be = CpuBackend(CTX64)
    prot = write_ec_files(base, CTX64, be)
    prot.save(base + ".ecsum")
    shard = base + CTX64.to_ext(2)
    good = open(shard, "rb").read()
    os.truncate(shard, len(good) - 1000)
    got = rebuild_ec_files(base, CTX64, backend=be)
    assert 2 in got
    assert open(shard, "rb").read() == good


def test_native_sink_write_failure_fails_closed(tmp_path):
    """A dead fd mid-stream surfaces as an error (never a silent
    truncated-success): append_rows raises and no CRCs are minted for
    the failed batch."""
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    f = open(tmp_path / "s0", "wb", buffering=0)
    sink = FusedShardSink([f], block_size=4096)
    row = np.zeros(4096, np.uint8)
    sink.append_rows([row])
    f.close()  # the "crash"
    with pytest.raises(OSError):
        sink.append_rows([row])


# ------------------------------------------------------- skip-clean


def test_encode_skip_clean_without_native(tmp_path, monkeypatch):
    """With the .so unavailable (import raises), the whole byte path
    must run pure Python and still produce a correct volume — the
    native core is an accelerator, not a dependency."""
    base = _make_dat(tmp_path, "v", (1 << 20) + 777)
    # Simulate an unavailable native core for FRESH imports: drop the
    # already-bound package attribute AND poison sys.modules (a None
    # entry makes `import seaweedfs_tpu.utils.native` raise ImportError).
    import seaweedfs_tpu.utils as _utils

    monkeypatch.delattr(_utils, "native", raising=False)
    monkeypatch.setitem(sys.modules, "seaweedfs_tpu.utils.native", None)
    assert not native_io.enabled()
    sink = make_shard_sink([open(os.devnull, "wb")])
    assert isinstance(sink, PyShardSink)
    be = CpuBackend(CTX64)
    prot = write_ec_files(base, CTX64, be)
    prot.save(base + ".ecsum")
    assert not prot.verify_shard_file(base + CTX64.to_ext(0), 0)
    # degraded-path read helpers fall back too
    buf = np.empty(1024, np.uint8)
    fd = os.open(base + CTX64.to_ext(0), os.O_RDONLY)
    try:
        native_io.read_exact_into(fd, buf, 0)
    finally:
        os.close(fd)
    assert buf.tobytes() == open(base + CTX64.to_ext(0), "rb").read(1024)


# ------------------------------------------------- read-source pieces


def test_batch_pread_fused_crc_matches_python_roller(tmp_path):
    if not native_io.enabled():
        pytest.skip("native core unavailable")
    from seaweedfs_tpu.ec.rebuild import _BlockCrcRoller

    rng = np.random.default_rng(3)
    n = (1 << 18) + 333
    paths = []
    for i in range(3):
        p = tmp_path / f"f{i}"
        p.write_bytes(rng.integers(0, 256, n, np.uint8).tobytes())
        paths.append(p)
    fds = [os.open(p, os.O_RDONLY) for p in paths]
    try:
        block = 1 << 16
        state = np.zeros(3, np.uint32)
        filled = np.zeros(3, np.uint64)
        lists = [[] for _ in range(3)]
        rollers = [_BlockCrcRoller(block) for _ in range(3)]
        batch = 50_000
        out_crcs = np.empty((3, batch // block + 2), np.uint32)
        out_counts = np.empty(3, np.int32)
        for off in range(0, n, batch):
            width = min(batch, n - off)
            buf = np.empty((3, width), np.uint8)
            native_io.read_batch(
                fds, [off] * 3, buf, pad_eof=False, granule=block,
                crc_state=state, filled_state=filled,
                out_crcs=out_crcs, out_counts=out_counts,
            )
            for r in range(3):
                lists[r].extend(
                    int(x) for x in out_crcs[r, : out_counts[r]]
                )
                rollers[r].update(buf[r])
        for r in range(3):
            if filled[r]:
                lists[r].append(int(state[r]))
            assert lists[r] == rollers[r].finish()
    finally:
        for fd in fds:
            os.close(fd)


def test_buffer_pool_reuses_by_width():
    pool = native_io.BufferPool(rows=4)
    a = pool.get(1024)
    addr = a.ctypes.data
    assert addr % 4096 == 0
    pool.put(a)
    b = pool.get(1024)
    assert b.ctypes.data == addr  # same matrix back
    c = pool.get(2048)
    assert c.shape == (4, 2048) and c.ctypes.data % 4096 == 0
