"""Multi-filer tests: gRPC filer service, SubscribeMetadata streaming,
MetaAggregator convergence, manifest chunks.

Reference models: weed/pb/filer.proto service, meta_aggregator.go,
filechunk_manifest.go.
"""

import time

import grpc
import pytest

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.entry import new_entry
from seaweedfs_tpu.filer.meta_log import MetaLog
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from conftest import allocate_port as free_port


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mfvol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


def _mk_filer_server(cluster, tmp_path, name, peers=None):
    filer = Filer(
        MemoryStore(), master=f"localhost:{cluster}", chunk_size=16 * 1024
    )
    fs = FilerServer(
        filer,
        ip="localhost",
        port=free_port(),
        meta_log=MetaLog(str(tmp_path / f"metalog-{name}")),
        grpc_port=0,
        peers=peers or [],
    )
    fs.start()
    return fs


# ------------------------------------------------------------ gRPC service


def test_grpc_filer_crud(cluster, tmp_path):
    fs = _mk_filer_server(cluster, tmp_path, "crud")
    try:
        with grpc.insecure_channel(f"localhost:{fs.grpc_port}") as ch:
            stub = rpc.filer_stub(ch)
            # create
            e = fpb.Entry(name="hello.txt", content=b"grpc content")
            e.attributes.file_mode = 0o644
            e.attributes.mtime = int(time.time())
            r = stub.CreateEntry(
                fpb.CreateEntryRequest(directory="/docs", entry=e)
            )
            assert r.error == ""
            # lookup
            r = stub.LookupDirectoryEntry(
                fpb.LookupEntryRequest(directory="/docs", name="hello.txt")
            )
            assert r.error == "" and r.entry.content == b"grpc content"
            # list (parents auto-created)
            names = [
                resp.entry.name
                for resp in stub.ListEntries(
                    fpb.ListEntriesRequest(directory="/docs")
                )
            ]
            assert names == ["hello.txt"]
            # rename
            r = stub.AtomicRenameEntry(
                fpb.AtomicRenameEntryRequest(
                    old_directory="/docs",
                    old_name="hello.txt",
                    new_directory="/docs",
                    new_name="renamed.txt",
                )
            )
            assert r.error == ""
            assert fs.filer.exists("/docs/renamed.txt")
            # kv
            stub.KvPut(fpb.FilerKvPutRequest(key=b"k1", value=b"v1"))
            r = stub.KvGet(fpb.FilerKvGetRequest(key=b"k1"))
            assert r.found and r.value == b"v1"
            # delete
            r = stub.DeleteEntry(
                fpb.DeleteEntryRequest(
                    directory="/docs", name="renamed.txt", is_delete_data=True
                )
            )
            assert r.error == ""
            assert not fs.filer.exists("/docs/renamed.txt")
    finally:
        fs.stop()


def test_grpc_subscribe_metadata(cluster, tmp_path):
    fs = _mk_filer_server(cluster, tmp_path, "sub")
    try:
        fs.filer.write_file("/pre/one", b"1")
        with grpc.insecure_channel(f"localhost:{fs.grpc_port}") as ch:
            stub = rpc.filer_stub(ch)
            stream = stub.SubscribeMetadata(
                fpb.SubscribeMetadataRequest(client_name="t", since_ns=0)
            )
            got = []
            # history replay includes the pre-subscription write
            for ev in stream:
                got.append(ev)
                if any(
                    e.event.new_entry.name == "one" for e in got
                ):
                    break
            assert any(e.event.new_entry.name == "one" for e in got)
            # live follow
            fs.filer.write_file("/pre/two", b"2")
            for ev in stream:
                got.append(ev)
                if ev.event.new_entry.name == "two":
                    break
            assert got[-1].event.new_entry.name == "two"
    finally:
        fs.stop()


# ------------------------------------------------------------- aggregation


def test_two_filers_converge(cluster, tmp_path):
    """Writes landing on either filer appear on both (reference
    meta_aggregator.go two-way merge)."""
    fs_a = _mk_filer_server(cluster, tmp_path, "a")
    fs_b = _mk_filer_server(
        cluster, tmp_path, "b", peers=[f"localhost:{fs_a.grpc_port}"]
    )
    # wire a's aggregator to b after b exists (full mesh)
    from seaweedfs_tpu.filer.meta_aggregator import MetaAggregator

    agg_a = MetaAggregator(fs_a.filer, [f"localhost:{fs_b.grpc_port}"])
    agg_a.start()
    try:
        fs_a.filer.write_file("/shared/from-a", b"written on A")
        fs_b.filer.write_file("/shared/from-b", b"written on B")
        deadline = time.time() + 10
        while time.time() < deadline:
            if fs_a.filer.exists("/shared/from-b") and fs_b.filer.exists(
                "/shared/from-a"
            ):
                break
            time.sleep(0.1)
        # both namespaces converged; chunk reads work cross-filer since
        # the volume store is shared
        assert fs_a.filer.read_file("/shared/from-b") == b"written on B"
        assert fs_b.filer.read_file("/shared/from-a") == b"written on A"
        # deletes propagate too
        fs_a.filer.delete_entry("/shared/from-a")
        deadline = time.time() + 10
        while time.time() < deadline and fs_b.filer.exists("/shared/from-a"):
            time.sleep(0.1)
        assert not fs_b.filer.exists("/shared/from-a")
    finally:
        agg_a.stop()
        fs_b.stop()
        fs_a.stop()


def test_same_key_lww_convergence(cluster, tmp_path):
    """Both filers write the same key; they converge on the later
    write, not swap (last-writer-wins by meta timestamp)."""
    fs_a = _mk_filer_server(cluster, tmp_path, "lwa")
    fs_b = _mk_filer_server(
        cluster, tmp_path, "lwb", peers=[f"localhost:{fs_a.grpc_port}"]
    )
    from seaweedfs_tpu.filer.meta_aggregator import MetaAggregator

    agg_a = MetaAggregator(fs_a.filer, [f"localhost:{fs_b.grpc_port}"])
    agg_a.start()
    try:
        fs_a.filer.write_file("/k", b"first")
        time.sleep(0.01)
        fs_b.filer.write_file("/k", b"second")  # strictly later
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if (
                    fs_a.filer.read_file("/k") == b"second"
                    and fs_b.filer.read_file("/k") == b"second"
                ):
                    break
            except Exception:
                pass
            time.sleep(0.1)
        assert fs_a.filer.read_file("/k") == b"second"
        assert fs_b.filer.read_file("/k") == b"second"
    finally:
        agg_a.stop()
        fs_b.stop()
        fs_a.stop()


# --------------------------------------------------------- manifest chunks


def test_manifest_chunks_roundtrip(cluster):
    filer = Filer(
        MemoryStore(), master=f"localhost:{cluster}", chunk_size=1024
    )
    filer.manifest_threshold = 50
    try:
        data = bytes(i % 251 for i in range(100 * 1024))  # 100 chunks
        filer.write_file("/big/file.bin", data)
        entry = filer.find_entry("/big/file.bin")
        # stored form: manifest chunks, not 100 plain chunks
        assert len(entry.chunks) == 2
        assert all(c.is_chunk_manifest for c in entry.chunks)
        assert entry.file_size == len(data)
        # full + ranged reads resolve through the manifests
        assert filer.read_file("/big/file.bin") == data
        assert filer.read_file("/big/file.bin", 50_000, 2_000) == data[50_000:52_000]
        # GC expands manifests: deleting reclaims data + manifest blobs
        fids = [
            c.fid
            for c in filer.resolve_chunks(entry)
        ]
        assert len(fids) == 100
        filer.delete_entry("/big/file.bin")
        filer.flush_gc()
        import requests

        # the first data chunk must be gone from the volume store
        loc = filer.ops.master.lookup(int(fids[0].split(",")[0]))[0]
        r = requests.get(f"http://{loc.url}/{fids[0]}")
        assert r.status_code == 404
    finally:
        filer.close()


def test_10k_chunk_file_roundtrip(cluster):
    """VERDICT round-2 item: a 10k-chunk file round-trips.

    The 10,240-entry chunk list references 16 real uploaded blobs (10k
    distinct fsync'd uploads would dominate the suite's runtime without
    exercising anything extra — the manifest layer only sees fids)."""
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64)
    try:
        blobs = [bytes([b] * 64) for b in range(16)]
        fids = [filer.ops.upload(b) for b in blobs]
        chunks = []
        ts = time.time_ns()
        for i in range(10_240):
            chunks.append(
                fpb.FileChunk(
                    fid=fids[i % 16], offset=i * 64, size=64, modified_ts_ns=ts
                )
            )
        entry = new_entry("/huge")
        entry.chunks = chunks
        entry.attr.file_size = 10_240 * 64
        filer.create_entry(entry)
        stored = filer.find_entry("/huge")
        # 10,240 plain chunks collapse into 11 manifest chunks
        assert len(stored.chunks) == 11
        assert all(c.is_chunk_manifest for c in stored.chunks)
        data = b"".join(blobs[i % 16] for i in range(10_240))
        assert filer.read_file("/huge") == data
        # random ranged read through two manifest boundaries
        assert (
            filer.read_file("/huge", 63_990, 128_100)
            == data[63_990 : 63_990 + 128_100]
        )
        assert len(filer.resolve_chunks(stored)) == 10_240
    finally:
        filer.close()
