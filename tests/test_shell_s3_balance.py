"""volume.balance + the s3.* shell family.

Reference: weed/shell/command_volume_balance.go,
command_s3_configure.go and friends — the gateway reloads the
filer-persisted identity config live, so credentials minted in the
shell authenticate within the store's refresh TTL.
"""

import time

import pytest
import requests

from conftest import allocate_port as free_port
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.filer import Filer, MemoryStore

from seaweedfs_tpu.s3 import S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from test_s3 import sign_request


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


def test_volume_balance_migrates_to_empty_node(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs1 = VolumeServer(
        directories=[str(tmp_path / "v1")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs1.start()
    vs2 = None
    env = ops = None
    try:
        wait_for(lambda: master.topo.nodes, msg="node 1 registers")
        env = ShellEnv(f"localhost:{mport}")
        ops = Operations(f"localhost:{mport}")
        # create several volumes, all on node 1
        out = run_command(env, "volume.grow -count 4")
        assert "grew" in out or "volume" in out.lower()
        ops.upload(b"ballast" * 1000)

        # node 2 joins empty
        vs2 = VolumeServer(
            directories=[str(tmp_path / "v2")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs2.start()
        wait_for(lambda: len(master.topo.nodes) >= 2, msg="node 2 registers")

        # dry run first: a plan must exist and execute nothing
        plan = run_command(env, "volume.balance")
        assert "planned" in plan and "->" in plan
        topo = env.master.topology()
        counts = {n.id: len(n.volumes) for n in topo.nodes}
        assert min(counts.values()) == 0  # dry run moved nothing

        out = run_command(env, "volume.balance -apply")
        assert "error" not in out.splitlines()[0], out

        def balanced():
            topo = env.master.topology()
            counts = {n.id: len(n.volumes) for n in topo.nodes}
            return len(counts) == 2 and min(counts.values()) >= 1

        wait_for(balanced, msg="volumes migrated toward balance")
        # a second run converges
        assert "already balanced" in run_command(env, "volume.balance")
    finally:
        if ops:
            ops.close()
        if env:
            env.close()
        if vs2:
            vs2.stop()
        vs1.stop()
        master.stop()


@pytest.fixture
def s3_stack(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="vs registers")
    filer = Filer(MemoryStore(), master=f"localhost:{mport}")
    # a REAL FilerServer so the shell reaches the gRPC KV on the
    # conventional http_port + 10000
    from seaweedfs_tpu.server.filer_server import FilerServer

    fport = free_port()
    fsrv = FilerServer(
        filer, ip="localhost", port=fport, grpc_port=fport + 10000
    )
    fsrv.start()
    s3 = S3Server(filer, ip="localhost", port=free_port())
    s3.start()
    yield master, filer, s3, fport
    s3.stop()
    fsrv.stop()
    filer.close()
    vs.stop()
    master.stop()


def test_s3_accesskey_lifecycle(s3_stack):
    master, filer, s3, fport = s3_stack
    url = f"http://localhost:{s3.port}"
    env = ShellEnv(f"localhost:{master.port}", filer=f"localhost:{fport}")
    try:
        # open mode before any identity exists
        assert requests.put(f"{url}/openbkt").status_code == 200

        out = run_command(env, "s3.accesskey.create -user ops -actions Admin")
        assert "access_key=" in out, out
        kv = dict(
            line.split("=", 1) for line in out.splitlines() if "=" in line
        )
        ak, sk = kv["access_key"], kv["secret_key"]

        assert "ops" in run_command(env, "s3.user.list")

        # the identity store refresh TTL is 2s; the gateway flips to
        # authenticated mode and the new key pair signs requests
        def auth_enforced():
            return requests.put(f"{url}/denied").status_code == 403

        wait_for(auth_enforced, msg="gateway leaves open mode")
        h = sign_request("PUT", f"{url}/shellbkt", ak, sk)
        assert requests.put(f"{url}/shellbkt", headers=h).status_code == 200
        body = b"via shell-minted credentials"
        h = sign_request("PUT", f"{url}/shellbkt/k", ak, sk, body)
        assert (
            requests.put(f"{url}/shellbkt/k", data=body, headers=h).status_code
            == 200
        )
        h = sign_request("GET", f"{url}/shellbkt/k", ak, sk)
        assert requests.get(f"{url}/shellbkt/k", headers=h).content == body

        # attach a read-only policy: writes now denied, reads pass
        pol = (
            '{"Version":"2012-10-17","Statement":[{"Effect":"Allow",'
            '"Action":["s3:GetObject","s3:ListBucket"],'
            '"Resource":["arn:aws:s3:::*"]}]}'
        )
        out = run_command(env, f"s3.policy.put -user ops -policy '{pol}'")
        assert "attached" in out, out
        assert "s3:GetObject" in run_command(env, "s3.policy.get -user ops")

        def policy_applied():
            h = sign_request("PUT", f"{url}/shellbkt/deny", ak, sk, b"x")
            return (
                requests.put(
                    f"{url}/shellbkt/deny", data=b"x", headers=h
                ).status_code
                == 403
            )

        wait_for(policy_applied, msg="policy reload")
        h = sign_request("GET", f"{url}/shellbkt/k", ak, sk)
        assert requests.get(f"{url}/shellbkt/k", headers=h).content == body

        # bucket family + key deletion
        assert "shellbkt" in run_command(env, "s3.bucket.list")
        run_command(env, "s3.bucket.create -name fromshell")
        assert "fromshell" in run_command(env, "s3.bucket.list")
        out = run_command(env, "s3.bucket.delete -name fromshell")
        assert "deleted" in out

        out = run_command(env, f"s3.accesskey.delete -access_key {ak}")
        assert "deleted 1" in out

        def key_revoked():
            h = sign_request("GET", f"{url}/shellbkt/k", ak, sk)
            return (
                requests.get(f"{url}/shellbkt/k", headers=h).status_code == 403
            )

        wait_for(key_revoked, msg="revoked key stops working")
    finally:
        env.close()


def test_r4_ops_surface_batch(s3_stack):
    """fs.cp / fs.stat / fs.verify / cluster.lock.ring / volume.deleteEmpty."""
    import hashlib

    master, filer, s3, fport = s3_stack
    env = ShellEnv(f"localhost:{master.port}", filer=f"localhost:{fport}")
    try:
        data = b"shell surface" * 100
        filer.write_file("/ops/a.bin", data)

        out = run_command(env, "fs.cp /ops/a.bin /ops/b.bin")
        assert "copied" in out, out
        assert filer.read_file("/ops/b.bin") == data

        out = run_command(env, "fs.stat /ops/a.bin")
        assert f"size:      {len(data)}" in out and "type:      file" in out

        out = run_command(env, "fs.verify /ops/a.bin")
        assert hashlib.md5(data).hexdigest() in out
        assert f"{len(data)} bytes readable" in out

        # lock ring listing sees a live lease
        from seaweedfs_tpu.filer.lock_ring import DlmClient

        c = DlmClient([f"localhost:{fport + 10000}"])
        r = c.lock("jobs/x", owner="shell-test", ttl=30)
        assert r.ok
        out = run_command(env, "cluster.lock.ring")
        assert "jobs/x" in out and "shell-test" in out
        c.unlock("jobs/x", r.token)
        c.close()

        # empty volumes: grow some, then delete them
        run_command(env, "volume.grow -count 2")
        plan = run_command(env, "volume.deleteEmpty")
        assert "would delete" in plan
        out = run_command(env, "volume.deleteEmpty -force")
        assert "deleted empty volume" in out
    finally:
        env.close()


def test_s3_bucket_quota_flow(s3_stack):
    """Reference s3.bucket.quota family: set -> write over -> enforce
    flags the bucket -> gateway rejects writes -> delete + enforce
    unblocks."""
    master, filer, s3, fport = s3_stack
    url = f"http://localhost:{s3.port}"
    env = ShellEnv(f"localhost:{master.port}", filer=f"localhost:{fport}")
    try:
        assert requests.put(f"{url}/quotab").status_code == 200
        out = run_command(env, "s3.bucket.quota.set -name quotab -bytes 5000")
        assert "5,000" in out
        # under quota: writes pass, enforce says ok
        assert (
            requests.put(f"{url}/quotab/small", data=b"x" * 1000).status_code
            == 200
        )
        out = run_command(env, "s3.bucket.quota.enforce")
        assert "quotab: ok" in out, out
        # push over, enforce flags it
        assert (
            requests.put(f"{url}/quotab/big", data=b"y" * 6000).status_code
            == 200
        )
        out = run_command(env, "s3.bucket.quota.enforce")
        assert "OVER quota" in out, out
        r = requests.put(f"{url}/quotab/more", data=b"z")
        assert r.status_code == 403 and "QuotaExceeded" in r.text
        # reads still fine
        assert requests.get(f"{url}/quotab/small").status_code == 200
        # usage report
        out = run_command(env, "s3.bucket.quota.get -name quotab")
        assert "quota 5,000 bytes" in out
        # free space, enforce clears, writes resume
        assert requests.delete(f"{url}/quotab/big").status_code in (200, 204)
        out = run_command(env, "s3.bucket.quota.enforce")
        assert "quotab: ok" in out, out
        assert requests.put(f"{url}/quotab/more", data=b"z").status_code == 200
        # remove quota entirely
        out = run_command(env, "s3.bucket.quota.set -name quotab -bytes 0")
        assert "removed" in out
        assert "no quota" in run_command(env, "s3.bucket.quota.get -name quotab")
    finally:
        env.close()
