"""Spawned-process cluster test: real `python -m seaweedfs_tpu.server`
binaries on ephemeral ports, driven over HTTP + the shell CLI
(reference technique: test/volume_server/framework/cluster.go).
"""

import os
import signal
import subprocess
import sys
import time

import pytest
import requests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


from conftest import allocate_port as free_port


@pytest.fixture
def spawned(tmp_path):
    mport, vport = free_port(), free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "seaweedfs_tpu.server",
            "server",
            "-masterPort",
            str(mport),
            "-port",
            str(vport),
            "-dir",
            str(tmp_path / "data"),
            "-ec.backend",
            "cpu",
        ],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    deadline = time.time() + 30
    while True:
        try:
            r = requests.get(f"http://localhost:{mport}/cluster/status", timeout=1)
            if r.ok and r.json()["DataNodes"]:
                break
        except requests.RequestException:
            pass
        if time.time() > deadline or proc.poll() is not None:
            out = proc.stdout.read().decode(errors="replace") if proc.stdout else ""
            proc.kill()
            raise TimeoutError(f"server did not come up:\n{out}")
        time.sleep(0.2)
    yield mport, vport
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def shell(mport: int, cmd: str) -> str:
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "seaweedfs_tpu.shell",
            "-master",
            f"localhost:{mport}",
            "-c",
            cmd,
        ],
        cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stderr
    return r.stdout.strip()


def test_spawned_end_to_end(spawned):
    mport, vport = spawned
    # assign via master HTTP API
    a = requests.get(f"http://localhost:{mport}/dir/assign").json()
    assert "fid" in a, a
    data = os.urandom(100_000)
    r = requests.post(
        f"http://{a['url']}/{a['fid']}", files={"file": ("x.bin", data)}
    )
    assert r.status_code == 201, r.text
    lk = requests.get(
        f"http://localhost:{mport}/dir/lookup?volumeId={a['fid'].split(',')[0]}"
    ).json()
    url = lk["locations"][0]["url"]
    assert requests.get(f"http://{url}/{a['fid']}").content == data

    # shell: list, ec.encode the volume, read through EC
    vid = int(a["fid"].split(",")[0])
    out = shell(mport, "volume.list")
    assert f"volume {vid}" in out
    out = shell(mport, f"ec.encode -volumeId {vid} -backend cpu")
    assert "generation" in out
    deadline = time.time() + 10
    while True:
        out = shell(mport, "volume.list")
        if f"ec {vid}" in out:
            break
        assert time.time() < deadline, out
        time.sleep(0.3)
    assert requests.get(f"http://{url}/{a['fid']}").content == data
    out = shell(mport, "cluster.status")
    assert "node" in out
