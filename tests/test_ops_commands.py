"""Ops-plane tests: volume.move, volume.fix.replication, ec.balance,
/metrics endpoints (reference shell command tests + stats)."""

import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from seaweedfs_tpu.storage.file_id import FileId


from conftest import allocate_port as free_port


@pytest.fixture
def cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    while len(master.topo.nodes) < 2:
        time.sleep(0.05)
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


def test_volume_move(cluster):
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    try:
        data = b"move me" * 1000
        fid = ops.upload(data)
        vid = FileId.parse(fid).volume_id
        src = next(vs for vs in vols if vs.store.find_volume(vid) is not None)
        dst = next(vs for vs in vols if vs is not src)
        out = run_command(
            env, f"volume.move -volumeId {vid} -target localhost:{dst.grpc_port}"
        )
        assert "moved" in out, out
        wait_for(lambda: dst.store.find_volume(vid) is not None)
        assert src.store.find_volume(vid) is None
        wait_for(
            lambda: [l.url for l in master.topo.lookup(vid)]
            == [f"localhost:{dst.port}"]
        )
        assert ops.read(fid) == data
    finally:
        env.close()
        ops.close()


def test_fix_replication(cluster):
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    try:
        fid = ops.upload(b"replicate me", replication="001")
        vid = FileId.parse(fid).volume_id
        assert len(master.topo.lookup(vid)) == 2
        # kill one replica
        loser = next(vs for vs in vols if vs.store.find_volume(vid) is not None)
        loser.store.delete_volume(vid)
        loser.notify_deleted_volume(vid)
        wait_for(lambda: len(master.topo.lookup(vid)) == 1)
        out = run_command(env, "volume.fix.replication")
        assert f"volume {vid}" in out, out
        wait_for(lambda: len(master.topo.lookup(vid)) == 2)
        assert ops.read(fid) == b"replicate me"
    finally:
        env.close()
        ops.close()


def test_ec_balance(cluster):
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    rng = np.random.default_rng(3)
    try:
        blobs = {}
        for _ in range(15):
            d = rng.integers(0, 256, 40_000, np.uint8).tobytes()
            blobs[ops.upload(d)] = d
        vid = FileId.parse(next(iter(blobs))).volume_id
        run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        wait_for(
            lambda: any(vid in n.ec_shards for n in master.topo.nodes.values())
        )
        out = run_command(env, "ec.balance")
        assert "->" in out, out
        wait_for(
            lambda: sorted(
                sum(
                    len([i for i in range(32) if e.shard_bits & (1 << i)])
                    for e in n.ec_shards.values()
                )
                for n in master.topo.nodes.values()
            )
            == [7, 7],
            msg="shards should split 7/7",
        )
        for fid, d in blobs.items():
            assert ops.read(fid) == d, "reads after balance"
    finally:
        env.close()
        ops.close()


def test_master_auto_vacuum(cluster):
    """Garbage-heavy volumes are compacted by the master's sweep
    (reference topology_vacuum.go)."""
    master, vols = cluster
    ops = Operations(f"localhost:{master.port}")
    try:
        fids = [ops.upload(b"x" * 5000) for _ in range(10)]
        vid = FileId.parse(fids[0]).volume_id
        for fid in fids[:8]:
            if FileId.parse(fid).volume_id == vid:
                ops.delete(fid)
        holder = next(vs for vs in vols if vs.store.find_volume(vid))
        v = holder.store.find_volume(vid)
        assert v.garbage_ratio() > 0.3
        size_before = v.size
        # push fresh stats to the master, then force one sweep
        holder.notify_new_volume(vid)
        wait_for(
            lambda: any(
                n.volumes.get(vid) is not None
                and n.volumes[vid].deleted_bytes > 0
                for n in master.topo.nodes.values()
            )
        )
        assert vid in master.vacuum_once()
        assert holder.store.find_volume(vid).size < size_before
    finally:
        ops.close()


def test_check_disk_and_meta_save(cluster, tmp_path):
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    try:
        fid = ops.upload(b"replicated", replication="001")
        # replica stats converge via heartbeats; poll until consistent
        wait_for(
            lambda: "consistent" in run_command(env, "volume.check.disk"),
            msg="replicas should converge to consistent",
        )
        # diverge one replica directly on disk state
        vid = FileId.parse(fid).volume_id
        holder = next(vs for vs in vols if vs.store.find_volume(vid))
        from seaweedfs_tpu.storage.needle import Needle

        holder.store.find_volume(vid).write_needle(
            Needle(cookie=9, needle_id=999, data=b"phantom")
        )
        holder.notify_new_volume(vid)
        wait_for(
            lambda: len(
                {
                    n.volumes[vid].file_count
                    for n in master.topo.nodes.values()
                    if vid in n.volumes
                }
            )
            > 1
        )
        out = run_command(env, "volume.check.disk")
        assert "DIVERGED" in out, out
    finally:
        env.close()
        ops.close()


def test_batched_ec_encode_and_checks(cluster):
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    import numpy as np

    rng = np.random.default_rng(11)
    try:
        blobs = {}
        # force several volumes via distinct collections
        for col in ("alpha", "beta", "gamma"):
            for _ in range(4):
                d = rng.integers(0, 256, 30_000, np.uint8).tobytes()
                blobs[ops.upload(d, collection=col)] = d
        vids = sorted({FileId.parse(f).volume_id for f in blobs})
        assert len(vids) >= 3
        out = run_command(
            env,
            "ec.encode -volumeId "
            + ",".join(map(str, vids))
            + " -backend cpu -maxParallelization 3",
        )
        assert out.count("generation") == len(vids), out
        wait_for(
            lambda: all(
                any(v in n.ec_shards for n in master.topo.nodes.values())
                for v in vids
            )
        )
        for fid, d in blobs.items():
            assert ops.read(fid) == d
        out = run_command(env, "ec.check.replication")
        assert out.count("all 14 shards present") == len(vids), out
        out = run_command(env, "cluster.check")
        assert "all checks passed" in out, out
    finally:
        env.close()
        ops.close()


def test_admin_ui(cluster):
    master, vols = cluster
    ops = Operations(f"localhost:{master.port}")
    try:
        ops.upload(b"ui fodder")
        master.worker_control.submit("vacuum", 424242)
        r = requests.get(f"http://localhost:{master.port}/ui")
        assert r.status_code == 200
        assert "seaweed-tpu cluster" in r.text
        assert "<table" in r.text
        assert "maintenance fleet" in r.text
        assert "424242" in r.text  # queued task visible
    finally:
        ops.close()


def test_metrics_endpoints(cluster):
    master, vols = cluster
    ops = Operations(f"localhost:{master.port}")
    try:
        fid = ops.upload(b"metric fodder")
        ops.read(fid)
        r = requests.get(f"http://localhost:{vols[0].port}/metrics")
        assert r.status_code == 200
        text = r.text
        assert "sw_request_total" in text
        assert "sw_request_seconds_bucket" in text
        r = requests.get(f"http://localhost:{master.port}/metrics")
        assert r.status_code == 200
    finally:
        ops.close()
