"""EC pipeline tests: encode/locate/rebuild/decode/read round trips.

Modeled on the reference's scenario-dense EC suites
(weed/storage/erasure_coding: ec_roundtrip_test.go, ec_test.go,
ec_rebuild_safety_test.go, ec_bitrot_interop_test.go).
"""

import os
import time

import numpy as np
import pytest

from seaweedfs_tpu.ec import (
    BitrotProtection,
    CpuBackend,
    ECContext,
    ECError,
    EcNotFoundError,
    EcVolume,
    JaxBackend,
    VolumeInfo,
    ec_decode_volume,
    ec_encode_volume,
    find_dat_file_size,
    locate_data,
    rebuild_ec_files,
    write_dat_file,
    write_ec_files,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

CTX = ECContext(10, 4)


def make_volume(tmp_path, vid=1, needles=60, seed=0):
    """Fabricate a real volume the way test fixtures do in the reference
    (test/plugin_workers/volume_fixtures.go)."""
    rng = np.random.default_rng(seed)
    v = Volume(str(tmp_path), vid)
    payloads = {}
    for i in range(1, needles + 1):
        size = int(rng.integers(1, 60_000))
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        n = Needle(cookie=0x1000 + i, needle_id=i, data=data)
        if i % 4 == 0:
            n.set_name(f"f{i}".encode())
        v.write_needle(n)
        payloads[i] = data
    v.close()
    return Volume.base_file_name(str(tmp_path), "", vid), payloads


def test_encode_read_roundtrip(tmp_path):
    base, payloads = make_volume(tmp_path)
    ec_encode_volume(base, CTX)
    for i in range(CTX.total):
        assert os.path.exists(base + f".ec{i:02d}")
    assert os.path.exists(base + ".ecx")
    assert os.path.exists(base + ".ecsum")
    assert os.path.exists(base + ".vif")

    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    for i, data in payloads.items():
        n = ev.read_needle(i, cookie=0x1000 + i)
        assert n.data == data
    ev.close()


def test_read_with_missing_shards_recovers(tmp_path):
    base, payloads = make_volume(tmp_path)
    ec_encode_volume(base, CTX)
    # lose 4 shards (= parity count, worst survivable case)
    for i in (0, 3, 7, 12):
        os.unlink(base + CTX.to_ext(i))
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    for i, data in payloads.items():
        assert ev.read_needle(i).data == data
    ev.close()

    # losing a 5th makes intervals on missing shards unrecoverable
    os.unlink(base + CTX.to_ext(9))
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    with pytest.raises(ECError):
        for i in payloads:
            ev.read_needle(i)
    ev.close()


def test_ec_delete_journal(tmp_path):
    base, payloads = make_volume(tmp_path, needles=20)
    ec_encode_volume(base, CTX)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    assert ev.delete_needle(5) > 0
    assert ev.delete_needle(5) == 0  # idempotent
    with pytest.raises(EcNotFoundError):
        ev.read_needle(5)
    ev.close()
    # deletion survives remount via .ecj
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    with pytest.raises(EcNotFoundError):
        ev.read_needle(5)
    assert ev.read_needle(6).data == payloads[6]
    ev.close()


def test_rebuild_missing_shards_bit_exact(tmp_path):
    base, _ = make_volume(tmp_path)
    ec_encode_volume(base, CTX)
    originals = {}
    for i in (2, 11):
        with open(base + CTX.to_ext(i), "rb") as f:
            originals[i] = f.read()
        os.unlink(base + CTX.to_ext(i))
    regenerated = rebuild_ec_files(base, backend=CpuBackend(CTX))
    assert regenerated == [2, 11]
    for i in (2, 11):
        with open(base + CTX.to_ext(i), "rb") as f:
            assert f.read() == originals[i]


def test_rebuild_excludes_corrupt_shard_via_sidecar(tmp_path):
    base, _ = make_volume(tmp_path)
    ec_encode_volume(base, CTX)
    with open(base + CTX.to_ext(4), "rb") as f:
        original = f.read()
    # flip one byte: sidecar must catch it, rebuild must regenerate
    with open(base + CTX.to_ext(4), "r+b") as f:
        f.seek(12345)
        b = f.read(1)
        f.seek(12345)
        f.write(bytes([b[0] ^ 0x01]))
    regenerated = rebuild_ec_files(base, backend=CpuBackend(CTX))
    assert regenerated == [4]
    with open(base + CTX.to_ext(4), "rb") as f:
        assert f.read() == original


def test_rebuild_fails_closed_on_malformed_sidecar(tmp_path):
    base, _ = make_volume(tmp_path, needles=10)
    ec_encode_volume(base, CTX)
    with open(base + ".ecsum", "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff\xff")
    os.unlink(base + CTX.to_ext(1))
    with pytest.raises(ECError, match="malformed"):
        rebuild_ec_files(base, backend=CpuBackend(CTX))
    # explicit override proceeds
    assert rebuild_ec_files(
        base, backend=CpuBackend(CTX), unsafe_ignore_sidecar=True
    ) == [1]


def test_rebuild_wholesale_mismatch_guard(tmp_path):
    """A stale/wrong sidecar (mismatching > parity shards) means the
    sidecar is suspect; refuse rather than excluding good shards."""
    base, _ = make_volume(tmp_path, needles=10)
    ec_encode_volume(base, CTX)
    prot = BitrotProtection.load(base + ".ecsum")
    for i in range(6):  # poison 6 > parity(4) shard CRC lists
        prot.shard_crcs[i] = [c ^ 1 for c in prot.shard_crcs[i]]
    prot.save(base + ".ecsum")
    os.unlink(base + CTX.to_ext(13))
    with pytest.raises(ECError, match="suspect"):
        rebuild_ec_files(base, backend=CpuBackend(CTX))


def test_rebuild_not_enough_shards(tmp_path):
    base, _ = make_volume(tmp_path, needles=10)
    ec_encode_volume(base, CTX)
    for i in range(5):  # 9 < k remain
        os.unlink(base + CTX.to_ext(i))
    with pytest.raises(ECError, match="not enough"):
        rebuild_ec_files(base, backend=CpuBackend(CTX))


def test_decode_roundtrip(tmp_path):
    base, payloads = make_volume(tmp_path)
    with open(base + ".dat", "rb") as f:
        original_dat = f.read()
    ec_encode_volume(base, CTX)
    os.unlink(base + ".dat")
    os.unlink(base + ".idx")
    assert ec_decode_volume(base) is True
    with open(base + ".dat", "rb") as f:
        assert f.read() == original_dat
    v = Volume(str(tmp_path), 1, create=False)
    for i, data in payloads.items():
        assert v.read_needle(i).data == data
    v.close()


def test_decode_noop_when_all_deleted(tmp_path):
    """Runtime deletes (journaled in .ecj) are folded into .ecx by the
    decode entry point (reference RebuildEcxFile before decode), so a
    fully-deleted volume de-stripes to nothing."""
    base, payloads = make_volume(tmp_path, needles=5)
    ec_encode_volume(base, CTX)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    for i in payloads:
        ev.delete_needle(i)
    ev.close()
    os.unlink(base + ".dat")
    assert ec_decode_volume(base) is False
    assert not os.path.exists(base + ".dat")
    assert not os.path.exists(base + ".ecj")  # journal folded + dropped


def test_decode_after_partial_deletes_keeps_survivors(tmp_path):
    base, payloads = make_volume(tmp_path, needles=12, seed=9)
    ec_encode_volume(base, CTX)
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    for i in (1, 2, 3):
        ev.delete_needle(i)
    ev.close()
    os.unlink(base + ".dat")
    os.unlink(base + ".idx")
    assert ec_decode_volume(base) is True
    v = Volume(str(tmp_path), 1, create=False)
    for i in (1, 2, 3):
        assert not v.has_needle(i)
    for i in range(4, 13):
        assert v.read_needle(i).data == payloads[i]
    v.close()


def test_find_dat_file_size_matches_real(tmp_path):
    base, _ = make_volume(tmp_path)
    real = os.path.getsize(base + ".dat")
    ec_encode_volume(base, CTX)
    vi = VolumeInfo.load(base + ".vif")
    assert vi.dat_file_size == real
    assert find_dat_file_size(base, vi.version) == real


def test_locate_small_and_large_blocks():
    """Interval math against a brute-force striping model, tiny blocks."""
    k, large, small = 3, 64, 16
    # volume of 2 large rows + tail => shard layout: 2 large + smalls
    dat_size = 2 * k * large + 5 * small + 7
    shard_size = dat_size // k

    # brute force: byte x of dat -> (shard, offset)
    def brute(x):
        large_area = (shard_size // large) * large * k
        if x < large_area:
            block, inner = divmod(x, large)
            row, col = block // k, block % k
            return col, row * large + inner
        x -= large_area
        block, inner = divmod(x, small)
        row, col = block // k, block % k
        return col, (shard_size // large) * large + row * small + inner

    for off, size in [(0, 10), (60, 10), (63, 2), (190, 130), (383, 70), (400, 1)]:
        got = []
        for iv in locate_data(off, size, shard_size, k, large, small):
            sid, soff = iv.to_shard_and_offset(k, large, small)
            for j in range(iv.size):
                got.append((sid, soff + j))
        want = [brute(off + j) for j in range(size)]
        assert got == want, (off, size)


def test_write_dat_file_layout_ambiguity(tmp_path):
    """Shard size an exact large-block multiple + no encode-time size
    => fail closed (reference writeDatFile ambiguity guard)."""
    k, large, small = 2, 64, 16
    shard_paths = []
    for i in range(k):
        p = str(tmp_path / f"s{i}")
        with open(p, "wb") as f:
            f.write(b"\xaa" * (2 * large))  # exact multiple of large
        shard_paths.append(p)
    base = str(tmp_path / "vol")
    with pytest.raises(ECError, match="layout"):
        write_dat_file(base, 2 * large * k, 0, shard_paths, large, small)
    # with the encode-time size supplied it works
    write_dat_file(base, 2 * large * k, 2 * large * k, shard_paths, large, small)
    assert os.path.getsize(base + ".dat") == 2 * large * k


def test_cpu_and_jax_backends_bit_identical(tmp_path, rng):
    data = rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)
    cpu = CpuBackend(CTX)
    jx = JaxBackend(CTX, impl="xla")
    p_cpu = cpu.encode(data)
    p_jax = jx.encode(data)
    assert np.array_equal(p_cpu, p_jax)
    shards = np.concatenate([data, p_cpu], axis=0)
    present = {i: shards[i] for i in range(14) if i not in (1, 6, 10, 13)}
    r_cpu = cpu.reconstruct(dict(present))
    r_jax = jx.reconstruct(dict(present))
    for i in (1, 6, 10, 13):
        assert np.array_equal(r_cpu[i], shards[i])
        assert np.array_equal(r_jax[i], shards[i])


def test_encode_batch_size_invariance(tmp_path):
    """Different device batch sizes must produce identical shards."""
    base, _ = make_volume(tmp_path, needles=30, seed=3)
    write_ec_files(base, CTX, CpuBackend(CTX), batch_size=1 << 20)
    first = {}
    for i in range(CTX.total):
        with open(base + CTX.to_ext(i), "rb") as f:
            first[i] = f.read()
    write_ec_files(base, CTX, CpuBackend(CTX), batch_size=100_000)
    for i in range(CTX.total):
        with open(base + CTX.to_ext(i), "rb") as f:
            assert f.read() == first[i], f"shard {i} differs across batch sizes"


def test_encode_pipeline_error_propagates(tmp_path):
    """A failing backend must raise out of write_ec_files promptly (no
    pipeline deadlock) and leave no partially-registered state."""
    base, _ = make_volume(tmp_path, needles=20, seed=7)

    class BoomBackend(CpuBackend):
        def encode(self, data):
            raise RuntimeError("device exploded")

    t0 = time.time()
    with pytest.raises(RuntimeError, match="device exploded"):
        write_ec_files(base, CTX, BoomBackend(CTX))
    assert time.time() - t0 < 30, "error path must not hang"


def test_custom_ratio_roundtrip(tmp_path):
    ctx = ECContext(4, 2)
    base, payloads = make_volume(tmp_path, needles=15, seed=5)
    ec_encode_volume(base, ctx)
    os.unlink(base + ctx.to_ext(1))
    # ctx resolved from .vif, not the default
    assert rebuild_ec_files(base, backend=CpuBackend(ctx)) == [1]
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    assert ev.ctx == ctx
    for i, data in payloads.items():
        assert ev.read_needle(i).data == data
    ev.close()
