"""Flight-recorder (utils/trace.py) correctness + metrics-registry
hardening.

Covers the ISSUE-7 trace contracts: span-tree invariants over real EC
ops (children nested in the root's wall time, per-stage totals bounded
by the op duration), the disarmed no-allocation fast path, overlap-
efficiency math, Chrome trace_event export, the slow-op log, gRPC
metadata continuity, and the Prometheus text-format hardening
(label escaping roundtrip, duplicate-registration guard, package-wide
metric naming lint).
"""

from __future__ import annotations

import importlib
import json
import pkgutil
import re
import time

import os

import pytest

from seaweedfs_tpu.ec import CpuBackend, EcVolume, ec_encode_volume, rebuild_ec_files
from seaweedfs_tpu.utils import metrics as M
from seaweedfs_tpu.utils import request_id as rid
from seaweedfs_tpu.utils import trace

from test_ec_pipeline import CTX, make_volume


@pytest.fixture
def recorder():
    trace.configure(enabled=True, ring_size=256, slow_op_s=0.0)
    trace.reset()
    yield trace
    trace.configure(enabled=False, slow_op_s=0.0)
    trace.reset()


def walk(doc):
    yield doc
    for ch in doc["children"]:
        yield from walk(ch)


# ---------------------------------------------------------------- disarmed


def test_disarmed_fast_path_is_noop_singleton():
    """Span-enter/exit when disarmed must be one flag/is-None check and
    ZERO allocations: every helper returns the same singleton or None."""
    assert not trace.armed
    assert trace.start("ec.encode") is None
    assert trace.current() is None
    noop = trace.stage(None, "disk_read")
    assert noop is trace.stage(None, "h2d_dispatch")
    assert noop is trace.activate(None)
    with noop:
        pass
    # plain no-ops, no exceptions, nothing recorded
    trace.add_stage(None, "disk_read", 1.0)
    trace.event(None, "x", a=1)
    trace.finish(None)
    assert trace.traces() == []
    # disarmed + no active request id: nothing to carry on the wire
    rid.clear()
    assert trace.grpc_metadata() is None
    # ...but an active request id still rides (id propagation is not
    # gated on the tracer)
    rid.ensure("req-123")
    md = dict(trace.grpc_metadata())
    assert md == {trace.REQUEST_ID_KEY: "req-123"}
    rid.clear()


# ------------------------------------------------------- span invariants


def test_span_tree_invariants_on_real_ec_ops(recorder, tmp_path):
    """Encode + degraded read + rebuild under the armed recorder: every
    child span nests inside its root's wall time, every stage total is
    bounded by its span's duration, and the per-op histograms/gauges
    populate."""
    TOL = 0.25  # clock-read ordering slack, generous for slow CI boxes

    base, payloads = make_volume(tmp_path, needles=20)
    ec_encode_volume(base, CTX)

    for i in (0, 3):
        os.unlink(base + CTX.to_ext(i))
    ev = EcVolume(str(tmp_path), 1, backend_name="cpu")
    try:
        for i in list(payloads)[:3]:
            assert ev.read_needle(i).data == payloads[i]
    finally:
        ev.close()

    assert rebuild_ec_files(base, CTX, backend=CpuBackend(CTX)) == [0, 3]

    docs = trace.traces()
    by_op = {}
    for d in docs:
        by_op.setdefault(d["op"], []).append(d)
    assert "ec.encode_volume" in by_op
    assert "ec.degraded_read" in by_op
    assert "ec.rebuild" in by_op

    for root in docs:
        r_lo = root["start_ts"] - TOL
        r_hi = root["start_ts"] + root["duration_s"] + TOL
        for node in walk(root):
            assert node["trace_id"] == root["trace_id"]
            assert node["duration_s"] >= 0.0
            assert node["start_ts"] >= r_lo
            assert node["start_ts"] + node["duration_s"] <= r_hi
            for name, acc in node["stages"].items():
                assert acc["count"] >= 1, (root["op"], name)
                if name == "queue_wait":
                    # accumulated from BOTH pipeline threads (reader's
                    # read_q put + dispatcher's write_q put) — under
                    # two-sided backpressure its total may legitimately
                    # exceed the op wall
                    continue
                # every other stage accumulates non-overlapping timed
                # sections of one thread: total bounded by the op wall
                assert acc["seconds"] <= node["duration_s"] + TOL, (
                    root["op"], name, acc,
                )

    # encode: the volume root carries the pipeline child with the
    # canonical stage set
    enc = by_op["ec.encode_volume"][0]
    pipe = [n for n in walk(enc) if n["op"] == "ec.encode"]
    assert pipe and {"disk_read", "write_sink"} <= set(pipe[0]["stages"])
    # degraded read: sibling reads + sidecar verification attributed
    dr_stages = set()
    for d in by_op["ec.degraded_read"]:
        dr_stages |= set(d["stages"])
    assert "sibling_read" in dr_stages
    # rebuild: published via fsync/rename windows
    rb = by_op["ec.rebuild"][0]
    assert "fsync_publish" in rb["stages"]

    text = M.REGISTRY.render().decode()
    for op in ("ec.encode", "ec.degraded_read", "ec.rebuild"):
        assert f'op="{op}"' in text
    assert "sw_ec_stage_seconds_count" in text
    assert "sw_ec_overlap_efficiency" in text
    assert 'sw_ec_traces_total{op="ec.rebuild"}' in text


def test_ring_is_bounded(recorder):
    trace.configure(ring_size=4)
    for i in range(10):
        trace.finish(trace.start("ec.encode", name=f"op{i}"))
    docs = trace.traces()
    assert len(docs) == 4
    assert docs[-1]["name"] == "op9"  # newest kept, oldest dropped


# --------------------------------------------------------------- overlap


def _doc(dur, stages):
    return {
        "duration_s": dur,
        "stages": {
            k: {"seconds": v, "count": 1, "chip": ""}
            for k, v in stages.items()
        },
        "children": [],
    }


def test_overlap_efficiency_math():
    # fully serial: wall = host + device, every device second exposed
    assert trace.overlap_efficiency(_doc(2.0, {
        "disk_read": 1.0, "h2d_dispatch": 0.5, "device_drain": 0.5,
    })) == 0.0
    # fully overlapped: wall = host alone and the drain never blocked
    assert trace.overlap_efficiency(_doc(1.0, {
        "disk_read": 1.0, "h2d_dispatch": 0.5, "device_drain": 0.0,
    })) == 1.0
    # half hidden: residue and measured drain agree at device/2
    assert trace.overlap_efficiency(_doc(1.25, {
        "disk_read": 1.0, "h2d_dispatch": 0.25, "device_drain": 0.25,
    })) == pytest.approx(0.5)
    # host stages overlapping EACH OTHER (reader + sink threads): their
    # sum exceeds wall, zeroing the residue — but a 0.9s measured drain
    # is exposed by definition, so the gauge must NOT saturate at 1.0
    assert trace.overlap_efficiency(_doc(1.1, {
        "disk_read": 1.0, "write_sink": 1.0,
        "h2d_dispatch": 0.1, "device_drain": 0.9,
    })) == pytest.approx(0.1)
    # no device work: undefined, not 0 (an op class with no device time
    # must not drag the gauge)
    assert trace.overlap_efficiency(_doc(1.0, {"disk_read": 1.0})) is None


# ---------------------------------------------------------------- export


def test_chrome_trace_export_structure(recorder):
    sp = trace.start("ec.encode", name="vol1", base="/x/1")
    with trace.activate(sp):
        with trace.stage(sp, "disk_read"):
            pass
        child = trace.start("ec.peer_fetch", name="shard 2")
        trace.event(child, "placement", chip="chip0")
        trace.finish(child)
    trace.finish(sp)

    doc = trace.chrome_trace()
    json.loads(json.dumps(doc))  # serializable
    evs = doc["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in metas)
    assert any(e["name"] == "thread_name" for e in metas)
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"vol1", "shard 2"}
    for e in xs:
        assert e["dur"] > 0 and e["ts"] > 0
        assert {"pid", "tid", "cat", "args"} <= set(e)
    root_ev = next(e for e in xs if e["name"] == "vol1")
    assert root_ev["args"]["trace_id"] == sp.trace_id
    assert "disk_read" in root_ev["args"]["stages_ms"]
    assert any(e["ph"] == "i" and e["name"] == "placement" for e in evs)
    # filtering by an unknown trace id yields an empty event list
    assert trace.chrome_trace("feedfeedfeedfeed")["traceEvents"] == []


def test_grpc_metadata_continuity(recorder):
    """Client-side metadata -> server-side adoption keeps ONE trace id
    with parent/child linkage, the wire-format contract behind the
    cross-server tests in test_ec_cluster_chaos.py."""
    rid.ensure("req-xyz")
    try:
        sp = trace.start("ec.peer_rebuild", name="v7")
        with trace.activate(sp):
            md = dict(trace.grpc_metadata())
        assert md[trace.TRACE_ID_KEY] == sp.trace_id
        assert md[trace.PARENT_SPAN_KEY] == sp.span_id
        assert md[trace.REQUEST_ID_KEY] == "req-xyz"
        adopted = trace.start_from_metadata(
            "rpc.ec_shard_read", md, server="peer:8080"
        )
        assert adopted.trace_id == sp.trace_id
        assert adopted.parent_id == sp.span_id
        assert adopted.server == "peer:8080"
        trace.finish(adopted)
        trace.finish(sp)
        tid_docs = trace.traces(sp.trace_id)
        assert len(tid_docs) == 2  # two local roots, one logical trace
    finally:
        rid.clear()


def test_slow_op_log_fires_and_counts(recorder, capfd):
    trace.configure(slow_op_s=0.001)
    before = M.REGISTRY.render().decode()
    sp = trace.start("ec.rebuild", name="slowpoke")
    with trace.stage(sp, "disk_read"):
        time.sleep(0.01)
    trace.finish(sp)
    err = capfd.readouterr().err
    assert "slow op ec.rebuild" in err
    assert "slowpoke" in err and "disk_read" in err
    after = M.REGISTRY.render().decode()
    line = 'sw_ec_slow_ops_total{op="ec.rebuild"}'
    def count(text):
        for ln in text.splitlines():
            if ln.startswith(line):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0
    assert count(after) == count(before) + 1
    # below threshold: quiet
    trace.finish(trace.start("ec.rebuild", name="fast"))
    assert count(M.REGISTRY.render().decode()) == count(after)


# ------------------------------------------------- metrics hardening


def test_duplicate_metric_registration_raises():
    reg = M.Registry()
    reg.counter("sw_dup_total", "first", ("a",))
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("sw_dup_total", "second")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("sw_dup_total", "third")


_SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$'
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def test_exposition_escaping_roundtrip_adversarial_labels():
    """Scrape a registry holding hostile label values / help text and
    re-parse the text format: every line must lex, and the decoded
    label values must round-trip bit-exact."""
    evil = 'quote:" backslash:\\ newline:\nend'
    reg = M.Registry()
    c = reg.counter(
        "sw_esc_total", 'help with "quotes", \\slashes\n and newline',
        ("lbl",),
    )
    c.inc(lbl=evil)
    c.inc(2, lbl="plain")
    g = reg.gauge("sw_esc_gauge", "g", ("a", "b"))
    g.set(1.5, a="x\\", b='"\n"')
    text = reg.render().decode()

    parsed = {}
    for ln in text.splitlines():
        assert ln.strip(), "blank line inside exposition"
        if ln.startswith("#"):
            # comment lines must stay single-line comments
            assert ln.startswith("# HELP") or ln.startswith("# TYPE")
            continue
        m = _SAMPLE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        labels = {
            k: _unescape(v) for k, v in _LABEL.findall(m.group(2) or "")
        }
        parsed[(m.group(1), tuple(sorted(labels.items())))] = float(
            m.group(3)
        )

    assert parsed[("sw_esc_total", (("lbl", evil),))] == 1.0
    assert parsed[("sw_esc_total", (("lbl", "plain"),))] == 2.0
    assert parsed[("sw_esc_gauge", (("a", "x\\"), ("b", '"\n"')))] == 1.5


_STAGE_PATTERNS = [
    # trace.stage(sp, "name") — the first arg may be a call like
    # trace.current()
    re.compile(
        r'\bstage\(\s*[A-Za-z_][\w.\[\]]*(?:\(\))?\s*,\s*"([a-z0-9_.]+)"'
    ),
    # span.stage("name")
    re.compile(r'\.stage\(\s*"([a-z0-9_.]+)"'),
    # span.add_stage("name", secs) — possibly split across lines
    re.compile(r'add_stage\(\s*"([a-z0-9_.]+)"'),
    # trace.add_stage(span, "name", secs)
    re.compile(r'add_stage\(\s*[A-Za-z_][\w.]*\s*,\s*"([a-z0-9_.]+)"'),
    # pipeline stage-name kwargs
    re.compile(r'(?:read_stage|write_stage)\s*=\s*"([a-z0-9_.]+)"'),
]
_STAGE_TUPLE = re.compile(r"stage_names\s*=\s*\(([^)]*)\)")


def test_stage_name_registry_lint():
    """Every stage-name literal in the package must be in trace.STAGES:
    a typo'd label would silently fork a sw_ec_stage_seconds series
    (and vanish from the heartbeat EWMAs) instead of failing here."""
    import seaweedfs_tpu

    pkg_root = seaweedfs_tpu.__path__[0]
    found: dict[str, set] = {}
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                src = f.read()
            names = set()
            for pat in _STAGE_PATTERNS:
                names.update(pat.findall(src))
            for tup in _STAGE_TUPLE.findall(src):
                names.update(re.findall(r'"([a-z0-9_.]+)"', tup))
            for n in names:
                found.setdefault(n, set()).add(
                    os.path.relpath(path, pkg_root)
                )
    unknown = {
        n: sorted(files)
        for n, files in found.items()
        if n not in trace.STAGES
    }
    assert not unknown, (
        f"stage literals outside trace.STAGES (typo'd histogram "
        f"label?): {unknown}"
    )
    # the scan actually sees the fleet — a broken regex must not pass
    # vacuously (gateway + pipeline stages at minimum)
    assert len(found) >= 12, sorted(found)
    for required in (
        "s3.auth", "filer.lookup", "chunk.fetch", "volume.read",
        "disk_read", "h2d_dispatch", "admission_wait",
    ):
        assert required in found, required


def test_metrics_lint_package_wide():
    """Walk the package, import every module best-effort (optional deps
    may be absent in this container), then lint EVERY sw_* registration:
    unique names, `sw_<subsystem>_<name>` convention, non-empty help,
    counters end in _total, timing histograms in _seconds."""
    import seaweedfs_tpu

    for mod in pkgutil.walk_packages(
        seaweedfs_tpu.__path__, "seaweedfs_tpu."
    ):
        try:
            importlib.import_module(mod.name)
        except Exception:
            continue  # same tolerance as tier-1 collection

    metrics = list(M.REGISTRY._metrics)
    assert len(metrics) >= 15  # the walk actually registered the fleet
    names = [m.name for m in metrics]
    assert len(names) == len(set(names)), "duplicate metric names"
    pat = re.compile(r"^sw(_[a-z0-9]+)+$")
    for m in metrics:
        assert pat.match(m.name), f"bad metric name {m.name!r}"
        assert m.help and m.help.strip(), f"{m.name} has no help text"
        if isinstance(m, M.Counter):
            assert m.name.endswith("_total"), (
                f"counter {m.name} must end in _total"
            )
        if isinstance(m, M.Histogram):
            assert m.name.endswith("_seconds"), (
                f"timing histogram {m.name} must end in _seconds"
            )
