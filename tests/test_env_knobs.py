"""Env-knob registry lint (PR 14 satellite): every `SEAWEED_*`
environment variable referenced in code must be documented in the
README's "Env knob registry" — the `trace.STAGES` registry pattern
applied to configuration, so a knob can't ship invisible.

Scans quoted string literals in the package + bench.py (composed
f-string prefixes like f"SEAWEED_BENCH_{name}_ATTEMPTS" are covered by
the documented `SEAWEED_BENCH_<STAGE>_ATTEMPTS` wildcard and excluded
from the literal scan by construction — a prefix ending in `_` never
matches)."""

import os
import re

import seaweedfs_tpu

_KNOB = re.compile(r'["\'](SEAWEED_[A-Z0-9_]*[A-Z0-9])["\']')


def _scan_sources() -> dict[str, set[str]]:
    pkg_root = seaweedfs_tpu.__path__[0]
    repo_root = os.path.dirname(pkg_root)
    files = [os.path.join(repo_root, "bench.py")]
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        files += [
            os.path.join(dirpath, f)
            for f in filenames
            if f.endswith(".py")
        ]
    found: dict[str, set[str]] = {}
    for path in files:
        with open(path) as f:
            src = f.read()
        for name in _KNOB.findall(src):
            found.setdefault(name, set()).add(
                os.path.relpath(path, repo_root)
            )
    return found


def test_every_env_knob_is_documented_in_readme():
    found = _scan_sources()
    repo_root = os.path.dirname(seaweedfs_tpu.__path__[0])
    with open(os.path.join(repo_root, "README.md")) as f:
        readme = f.read()
    undocumented = {
        name: sorted(files)
        for name, files in found.items()
        if name not in readme
    }
    assert not undocumented, (
        f"SEAWEED_* knobs referenced in code but absent from README's "
        f"'Env knob registry': {undocumented}"
    )
    # the scan actually sees the fleet — a broken regex must not pass
    # vacuously (the long-standing families at minimum)
    assert len(found) >= 20, sorted(found)
    for required in (
        "SEAWEED_EC_NATIVE",
        "SEAWEED_S3_AUTH_MEMO",
        "SEAWEED_EC_STREAM_BLOCK_KB",
        "SEAWEED_EC_STREAM_MAX_LAG_MS",
        "SEAWEED_BENCH_VOLUME_MB",
    ):
        assert required in found, required


def test_stream_knobs_actually_engage(monkeypatch, tmp_path):
    """The SEAWEED_EC_STREAM_* family is read where documented: block
    sizing reaches the encoder, flush policy reaches the broker glue."""
    monkeypatch.setenv("SEAWEED_EC_STREAM_BLOCK_KB", "32")
    monkeypatch.setenv("SEAWEED_EC_STREAM_SMALL_KB", "8")
    monkeypatch.setenv("SEAWEED_EC_STREAM_FLUSH_KB", "128")
    monkeypatch.setenv("SEAWEED_EC_STREAM_MAX_LAG_MS", "77")
    monkeypatch.setenv("SEAWEED_EC_STREAM_ROTATE_MB", "3")
    monkeypatch.setenv("SEAWEED_EC_STREAM_SHARDS", "5+3")

    from seaweedfs_tpu.ec.backend import CpuBackend
    from seaweedfs_tpu.ec.context import ECContext
    from seaweedfs_tpu.ec.stream_encode import EcStreamEncoder
    from seaweedfs_tpu.mq.stream_parity import PartitionParity, parity_context

    ctx = ECContext(4, 2)
    enc = EcStreamEncoder(
        str(tmp_path / "s"), ctx, backend=CpuBackend(ctx)
    )
    assert enc.block_size == 32 << 10
    assert enc.small_block_size == 8 << 10
    enc.close()

    assert parity_context() == ECContext(5, 3)
    pp = PartitionParity(str(tmp_path / "p"), "ns", "t", 0)
    assert pp.flush_bytes == 128 << 10
    assert abs(pp.max_lag_s - 0.077) < 1e-9
    assert pp.rotate_bytes == 3 << 20
    assert pp.ctx == ECContext(5, 3)
    pp.close()

    # malformed geometry degrades to the documented default
    monkeypatch.setenv("SEAWEED_EC_STREAM_SHARDS", "bogus")
    assert parity_context() == ECContext(4, 2)
