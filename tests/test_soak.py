"""Concurrency soak: sustained mixed read/write/delete load while the
cluster simultaneously EC-encodes, balances, and vacuums underneath it.
A compressed version of the reference's mixed-load expectations
(BASELINE config 5: encode under live PUT load)."""

import random
import threading
import time

import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from seaweedfs_tpu.storage.file_id import FileId


def test_mixed_load_during_maintenance(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    while len(master.topo.nodes) < 2:
        time.sleep(0.05)
    env = ShellEnv(f"localhost:{mport}")
    stop = threading.Event()
    errors: list[str] = []
    written: dict[str, bytes] = {}
    wlock = threading.Lock()

    def writer(seed: int):
        ops = Operations(f"localhost:{mport}")
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                data = bytes(rng.getrandbits(8) for _ in range(rng.randint(100, 20000)))
                try:
                    fid = ops.upload(data)
                    with wlock:
                        written[fid] = data
                except Exception as e:
                    errors.append(f"write: {e}")
        finally:
            ops.close()

    def reader(seed: int):
        ops = Operations(f"localhost:{mport}")
        rng = random.Random(seed)
        try:
            while not stop.is_set():
                with wlock:
                    fid = rng.choice(list(written)) if written else None
                    expect = written.get(fid) if fid else None
                if fid is None:
                    time.sleep(0.02)  # outside the lock: writers proceed
                    continue
                try:
                    got = ops.read(fid)
                    if got != expect:
                        errors.append(f"MISMATCH on {fid}")
                except LookupError:
                    with wlock:
                        if fid in written:
                            errors.append(f"read lost {fid}")
                except Exception as e:
                    errors.append(f"read: {e}")
        finally:
            ops.close()

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    threads += [threading.Thread(target=reader, args=(100 + i,)) for i in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(2.0)  # build up volumes under load
        # EC-encode the first volume while traffic continues; keep the
        # source so concurrent writes to it don't fail mid-encode
        with wlock:
            vids = sorted({FileId.parse(f).volume_id for f in written})
        assert vids
        out = run_command(
            env, f"ec.encode -volumeId {vids[0]} -backend cpu -keepSource"
        )
        assert "generation" in out, out
        run_command(env, "ec.balance")
        time.sleep(1.0)
        # the worker fleet executes an ec_balance task under the same
        # live load (round-5: 6/6 reference task kinds)
        from conftest import wait_for

        from seaweedfs_tpu.worker import Worker

        w = Worker(master=f"localhost:{mport}", backend="cpu")
        threading.Thread(target=w.run, daemon=True).start()
        try:
            wait_for(
                lambda: w.worker_id in master.worker_control._workers,
                msg="worker registers",
            )
            tid = master.worker_control.submit("ec_balance", 0)
            task = master.worker_control._tasks[tid]
            wait_for(
                lambda: task.state in ("done", "failed"),
                timeout=60,
                msg="ec_balance task reaches a terminal state",
            )
            assert task.state == "done", task.error
        finally:
            w.stop()
        run_command(env, f"volume.vacuum -volumeId {vids[-1]}")
        time.sleep(1.0)
        # round-5 maintenance verbs under the same live load:
        # in-place replication rewrite, vacuum opt-out, cluster.ps
        out = run_command(
            env, f"volume.configure.replication -volumeId {vids[-1]} "
            "-replication 000"
        )
        assert "replication ->" in out, out
        out = run_command(env, f"volume.vacuum.disable -volumeId {vids[-1]}")
        assert "disabled" in out, out
        run_command(env, f"volume.vacuum.enable -volumeId {vids[-1]}")
        out = run_command(env, "cluster.ps")
        assert "volumeServer" in out, out
        time.sleep(0.5)
    finally:
        stop.set()
        # worst-case in-flight upload (retries + backoff) well under this
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "worker threads hung"
    assert not errors, errors[:10]
    # final consistency sweep over everything written
    ops = Operations(f"localhost:{mport}")
    try:
        bad = 0
        for fid, data in written.items():
            if ops.read(fid) != data:
                bad += 1
        assert bad == 0, f"{bad}/{len(written)} corrupted"
        assert len(written) > 50, "load generator should have produced volume"
    finally:
        ops.close()
        env.close()
        for vs in vols:
            vs.stop()
        master.stop()
