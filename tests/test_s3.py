"""S3 gateway tests: bucket/object CRUD, listings, multipart, SigV4 auth.

Reference models: weed/s3api/*_test.go + test/s3 suites. boto3 is not in
this image, so a hand-rolled SigV4 signer drives the auth path (which
doubles as an independent check of the server's signing math).
"""

import datetime
import hashlib
import hmac
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.s3 import Identity, IdentityStore, S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


from conftest import allocate_port as free_port


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3vol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def s3(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    srv = S3Server(filer, ip="localhost", port=free_port())
    srv.start()
    yield f"http://localhost:{srv.port}"
    srv.stop()
    filer.close()


def xml_find_all(text, tag):
    root = ET.fromstring(text)
    ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
    return [e.text for e in root.iter(f"{ns}{tag}")]


def test_bucket_lifecycle(s3):
    assert requests.put(f"{s3}/photos").status_code == 200
    assert requests.put(f"{s3}/photos").status_code == 409
    assert requests.head(f"{s3}/photos").status_code == 200
    assert "photos" in xml_find_all(requests.get(f"{s3}/").text, "Name")
    assert requests.delete(f"{s3}/photos").status_code == 204
    assert requests.head(f"{s3}/photos").status_code == 404


def test_object_crud_and_range(s3):
    requests.put(f"{s3}/b1")
    data = b"0123456789" * 20000  # 200KB -> multiple chunks
    r = requests.put(f"{s3}/b1/dir/obj.bin", data=data, headers={"Content-Type": "application/x-test"})
    assert r.status_code == 200
    etag = r.headers["ETag"]
    assert etag == f'"{hashlib.md5(data).hexdigest()}"'
    r = requests.get(f"{s3}/b1/dir/obj.bin")
    assert r.content == data and r.headers["Content-Type"] == "application/x-test"
    r = requests.get(f"{s3}/b1/dir/obj.bin", headers={"Range": "bytes=100-199"})
    assert r.status_code == 206 and r.content == data[100:200]
    h = requests.head(f"{s3}/b1/dir/obj.bin")
    assert int(h.headers["Content-Length"]) == len(data)
    # copy
    r = requests.put(
        f"{s3}/b1/copy.bin", headers={"x-amz-copy-source": "/b1/dir/obj.bin"}
    )
    assert r.status_code == 200
    assert requests.get(f"{s3}/b1/copy.bin").content == data
    # delete
    assert requests.delete(f"{s3}/b1/dir/obj.bin").status_code == 204
    assert requests.get(f"{s3}/b1/dir/obj.bin").status_code == 404


def test_list_objects_v2(s3):
    requests.put(f"{s3}/lst")
    for key in ("a.txt", "dir/one.txt", "dir/two.txt", "dir/sub/three.txt", "z.txt"):
        requests.put(f"{s3}/lst/{key}", data=b"x")
    r = requests.get(f"{s3}/lst?list-type=2")
    keys = xml_find_all(r.text, "Key")
    assert keys == ["a.txt", "dir/one.txt", "dir/sub/three.txt", "dir/two.txt", "z.txt"]
    # delimiter groups
    r = requests.get(f"{s3}/lst?list-type=2&delimiter=/")
    assert xml_find_all(r.text, "Key") == ["a.txt", "z.txt"]
    assert xml_find_all(r.text, "Prefix")[1:] == ["dir/"]
    # prefix
    r = requests.get(f"{s3}/lst?list-type=2&prefix=dir/&delimiter=/")
    assert xml_find_all(r.text, "Key") == ["dir/one.txt", "dir/two.txt"]
    assert "dir/sub/" in xml_find_all(r.text, "Prefix")
    # pagination
    r = requests.get(f"{s3}/lst?list-type=2&max-keys=2")
    assert len(xml_find_all(r.text, "Key")) == 2
    token = xml_find_all(r.text, "NextContinuationToken")[0]
    r = requests.get(
        f"{s3}/lst?list-type=2&max-keys=10&continuation-token={urllib.parse.quote(token)}"
    )
    assert xml_find_all(r.text, "Key") == [
        "dir/sub/three.txt",
        "dir/two.txt",
        "z.txt",
    ]


def test_delete_objects_batch(s3):
    requests.put(f"{s3}/batch")
    for i in range(3):
        requests.put(f"{s3}/batch/k{i}", data=b"v")
    body = (
        '<Delete><Object><Key>k0</Key></Object>'
        "<Object><Key>k2</Key></Object></Delete>"
    )
    r = requests.post(f"{s3}/batch?delete", data=body)
    assert r.status_code == 200
    assert sorted(xml_find_all(r.text, "Key")) == ["k0", "k2"]
    r = requests.get(f"{s3}/batch?list-type=2")
    assert xml_find_all(r.text, "Key") == ["k1"]


def test_multipart_upload(s3):
    requests.put(f"{s3}/mp")
    r = requests.post(f"{s3}/mp/large.bin?uploads")
    upload_id = xml_find_all(r.text, "UploadId")[0]
    parts = [b"A" * 150_000, b"B" * 150_000, b"C" * 70_000]
    etags = []
    for i, p in enumerate(parts, start=1):
        r = requests.put(
            f"{s3}/mp/large.bin?partNumber={i}&uploadId={upload_id}", data=p
        )
        assert r.status_code == 200
        etags.append(r.headers["ETag"])
    r = requests.get(f"{s3}/mp/large.bin?uploadId={upload_id}")
    assert [int(x) for x in xml_find_all(r.text, "PartNumber")] == [1, 2, 3]
    r = requests.post(f"{s3}/mp/large.bin?uploadId={upload_id}", data="<Complete/>")
    assert r.status_code == 200
    etag = xml_find_all(r.text, "ETag")[0]
    assert etag.endswith('-3"')
    got = requests.get(f"{s3}/mp/large.bin")
    assert got.content == b"".join(parts)
    # upload dir cleaned up; list shows only the object
    r = requests.get(f"{s3}/mp?list-type=2")
    assert xml_find_all(r.text, "Key") == ["large.bin"]


def test_bucket_collection_lifecycle(s3, cluster):
    """Objects land in a per-bucket collection; deleting the bucket
    drops the collection's volumes cluster-wide (reference bucket
    fast-delete)."""
    import grpc as grpc_mod

    from seaweedfs_tpu.client.master_client import MasterClient

    requests.put(f"{s3}/colbkt")
    requests.put(f"{s3}/colbkt/obj1", data=b"x" * 50_000)
    mc = MasterClient(f"localhost:{cluster}")
    try:
        assert "colbkt" in mc.collections()
        requests.delete(f"{s3}/colbkt/obj1")
        assert requests.delete(f"{s3}/colbkt").status_code == 204
        deadline = time.time() + 10
        while "colbkt" in mc.collections():
            assert time.time() < deadline, "collection volumes should be reaped"
            time.sleep(0.2)
    finally:
        mc.close()


def test_object_tagging_and_versioning_status(s3):
    requests.put(f"{s3}/tagb")
    requests.put(f"{s3}/tagb/obj", data=b"tagged")
    body = (
        "<Tagging><TagSet>"
        "<Tag><Key>env</Key><Value>prod</Value></Tag>"
        "<Tag><Key>team</Key><Value>storage</Value></Tag>"
        "</TagSet></Tagging>"
    )
    assert requests.put(f"{s3}/tagb/obj?tagging", data=body).status_code == 200
    r = requests.get(f"{s3}/tagb/obj?tagging")
    assert r.status_code == 200
    assert xml_find_all(r.text, "Key") == ["env", "team"]
    assert xml_find_all(r.text, "Value") == ["prod", "storage"]
    # tags survive unrelated reads; delete clears them
    assert requests.get(f"{s3}/tagb/obj").content == b"tagged"
    assert requests.delete(f"{s3}/tagb/obj?tagging").status_code == 204
    r = requests.get(f"{s3}/tagb/obj?tagging")
    assert xml_find_all(r.text, "Key") == []
    # invalid tag sets rejected outright, never stored partially
    bad = "<Tagging><TagSet>" + "".join(
        f"<Tag><Key>k{i}</Key><Value>v</Value></Tag>" for i in range(11)
    ) + "</TagSet></Tagging>"
    assert requests.put(f"{s3}/tagb/obj?tagging", data=bad).status_code == 400
    # versioning reports unconfigured until a status is set
    r = requests.get(f"{s3}/tagb?versioning")
    assert r.status_code == 200 and "VersioningConfiguration" in r.text
    assert "<Status>" not in r.text
    r = requests.put(
        f"{s3}/tagb?versioning",
        data="<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>",
    )
    assert r.status_code == 200
    r = requests.get(f"{s3}/tagb?versioning")
    assert "Enabled" in r.text


def test_bucket_cors(s3):
    requests.put(f"{s3}/corsb")
    requests.put(f"{s3}/corsb/o", data=b"cors body")
    # no config yet
    assert requests.get(f"{s3}/corsb?cors").status_code == 404
    cfg = (
        "<CORSConfiguration><CORSRule>"
        "<AllowedOrigin>https://app.example</AllowedOrigin>"
        "<AllowedMethod>GET</AllowedMethod>"
        "<AllowedHeader>x-custom</AllowedHeader>"
        "</CORSRule></CORSConfiguration>"
    )
    assert requests.put(f"{s3}/corsb?cors", data=cfg).status_code == 200
    assert "AllowedOrigin" in requests.get(f"{s3}/corsb?cors").text
    # preflight allowed
    r = requests.options(
        f"{s3}/corsb/o",
        headers={
            "Origin": "https://app.example",
            "Access-Control-Request-Method": "GET",
        },
    )
    assert r.status_code == 200
    assert r.headers["Access-Control-Allow-Origin"] == "https://app.example"
    assert "GET" in r.headers["Access-Control-Allow-Methods"]
    # preflight denied for other origins/methods
    r = requests.options(
        f"{s3}/corsb/o",
        headers={"Origin": "https://evil", "Access-Control-Request-Method": "GET"},
    )
    assert r.status_code == 403
    r = requests.options(
        f"{s3}/corsb/o",
        headers={
            "Origin": "https://app.example",
            "Access-Control-Request-Method": "DELETE",
        },
    )
    assert r.status_code == 403
    # actual GET carries the allow-origin header
    r = requests.get(f"{s3}/corsb/o", headers={"Origin": "https://app.example"})
    assert r.headers.get("Access-Control-Allow-Origin") == "https://app.example"
    assert r.content == b"cors body"
    # delete clears it
    assert requests.delete(f"{s3}/corsb?cors").status_code == 204
    assert requests.get(f"{s3}/corsb?cors").status_code == 404
    # malformed config rejected
    assert requests.put(f"{s3}/corsb?cors", data=b"<notxml").status_code == 400


def test_multipart_with_tiny_part(s3):
    """Parts at or below the filer inline threshold must still splice
    into the completed object (regression: inlined parts vanished)."""
    requests.put(f"{s3}/mptiny")
    r = requests.post(f"{s3}/mptiny/t.bin?uploads")
    upload_id = xml_find_all(r.text, "UploadId")[0]
    parts = [b"X" * 100_000, b"tiny-tail"]  # part 2 is 9 bytes
    for i, p in enumerate(parts, start=1):
        assert requests.put(
            f"{s3}/mptiny/t.bin?partNumber={i}&uploadId={upload_id}", data=p
        ).status_code == 200
    r = requests.post(f"{s3}/mptiny/t.bin?uploadId={upload_id}", data="<Complete/>")
    assert r.status_code == 200
    got = requests.get(f"{s3}/mptiny/t.bin")
    assert got.content == b"".join(parts)


def test_multipart_abort(s3):
    requests.put(f"{s3}/ab")
    r = requests.post(f"{s3}/ab/x?uploads")
    upload_id = xml_find_all(r.text, "UploadId")[0]
    requests.put(f"{s3}/ab/x?partNumber=1&uploadId={upload_id}", data=b"zzz")
    assert requests.delete(f"{s3}/ab/x?uploadId={upload_id}").status_code == 204
    r = requests.get(f"{s3}/ab/x?uploadId={upload_id}")
    assert r.status_code == 404


# ------------------------------------------------------------------- sigv4


def sign_request(method, url, access_key, secret, body=b"", region="us-east-1"):
    u = urllib.parse.urlparse(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {
        "host": u.netloc,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    pairs = urllib.parse.parse_qsl(u.query, keep_blank_values=True)
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(pairs)
    )
    creq = "\n".join(
        [method, urllib.parse.quote(u.path or "/", safe="/-_.~"), cq,
         canonical_headers, signed, payload_hash]
    )
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope, hashlib.sha256(creq.encode()).hexdigest()]
    )
    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()
    k = h(h(h(h(("AWS4" + secret).encode(), date), region), "s3"), "aws4_request")
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}"
    )
    return headers


@pytest.fixture
def s3_signed(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    idents = IdentityStore()
    idents.add(Identity("admin", "AKIDEXAMPLE", "secret123"))
    srv = S3Server(filer, ip="localhost", port=free_port(), identities=idents)
    srv.start()
    yield f"http://localhost:{srv.port}"
    srv.stop()
    filer.close()


def test_paginated_listing_with_common_prefixes(s3):
    """A page ending on a CommonPrefix must not drop the next key
    (regression for next-token pointing at an unemitted key)."""
    requests.put(f"{s3}/pg")
    for key in ("a/1", "b", "c/2", "d"):
        requests.put(f"{s3}/pg/{key}", data=b"x")
    seen = []
    token = ""
    for _ in range(10):
        url = f"{s3}/pg?list-type=2&delimiter=/&max-keys=1"
        if token:
            url += f"&continuation-token={urllib.parse.quote(token)}"
        r = requests.get(url)
        seen += xml_find_all(r.text, "Key")
        seen += xml_find_all(r.text, "Prefix")[1:]  # [0] is the query prefix
        toks = xml_find_all(r.text, "NextContinuationToken")
        if not toks:
            break
        token = toks[0]
    assert sorted(seen) == ["a/", "b", "c/", "d"]


def test_action_enforcement(cluster):
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}")
    idents = IdentityStore()
    idents.add(Identity("writer", "WKEY", "wsecret", actions=("Read", "Write", "List")))
    srv = S3Server(filer, ip="localhost", port=free_port(), identities=idents)
    srv.start()
    base = f"http://localhost:{srv.port}"
    try:
        # bucket create requires Admin
        h = sign_request("PUT", f"{base}/locked", "WKEY", "wsecret")
        r = requests.put(f"{base}/locked", headers=h)
        assert r.status_code == 403 and "AccessDenied" in r.text
    finally:
        srv.stop()
        filer.close()


def test_malformed_inputs_return_400(s3):
    requests.put(f"{s3}/bad")
    r = requests.put(f"{s3}/bad/k?partNumber=abc&uploadId=x", data=b"z")
    assert r.status_code == 400
    r = requests.post(f"{s3}/bad?delete", data=b"<notxml")
    assert r.status_code == 400


def presign_url(method, url, access_key, secret, expires=3600, region="us-east-1"):
    u = urllib.parse.urlparse(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    scope = f"{date}/{region}/s3/aws4_request"
    q = {
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": f"{access_key}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q.items())
    )
    creq = "\n".join(
        [
            method,
            urllib.parse.quote(u.path or "/", safe="/-_.~"),
            cq,
            f"host:{u.netloc}\n",
            "host",
            "UNSIGNED-PAYLOAD",
        ]
    )
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(creq.encode()).hexdigest(),
        ]
    )

    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = h(h(h(h(("AWS4" + secret).encode(), date), region), "s3"), "aws4_request")
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    return f"{url}?{cq}&X-Amz-Signature={sig}"


def test_presigned_urls(s3_signed):
    base = s3_signed
    h = sign_request("PUT", f"{base}/pres", "AKIDEXAMPLE", "secret123")
    assert requests.put(f"{base}/pres", headers=h).status_code == 200
    body = b"presigned content"
    h = sign_request("PUT", f"{base}/pres/obj", "AKIDEXAMPLE", "secret123", body)
    assert requests.put(f"{base}/pres/obj", data=body, headers=h).status_code == 200
    # a presigned GET works with no Authorization header at all
    url = presign_url("GET", f"{base}/pres/obj", "AKIDEXAMPLE", "secret123")
    r = requests.get(url)
    assert r.status_code == 200 and r.content == body
    # tampered signature rejected
    assert requests.get(url[:-4] + "beef").status_code == 403
    # expired presign rejected
    url = presign_url("GET", f"{base}/pres/obj", "AKIDEXAMPLE", "secret123", expires=-1)
    assert requests.get(url).status_code == 403


def test_sigv4_auth(s3_signed):
    base = s3_signed
    # unsigned requests are rejected
    assert requests.put(f"{base}/secure").status_code == 403
    # signed bucket create + object put/get
    h = sign_request("PUT", f"{base}/secure", "AKIDEXAMPLE", "secret123")
    assert requests.put(f"{base}/secure", headers=h).status_code == 200
    body = b"signed payload"
    h = sign_request("PUT", f"{base}/secure/k?X-test=1", "AKIDEXAMPLE", "secret123", body)
    assert requests.put(f"{base}/secure/k?X-test=1", data=body, headers=h).status_code == 200
    h = sign_request("GET", f"{base}/secure/k", "AKIDEXAMPLE", "secret123")
    r = requests.get(f"{base}/secure/k", headers=h)
    assert r.status_code == 200 and r.content == body
    # wrong secret -> SignatureDoesNotMatch
    h = sign_request("GET", f"{base}/secure/k", "AKIDEXAMPLE", "wrong")
    r = requests.get(f"{base}/secure/k", headers=h)
    assert r.status_code == 403 and "SignatureDoesNotMatch" in r.text
    # unknown access key
    h = sign_request("GET", f"{base}/secure/k", "NOBODY", "secret123")
    assert "InvalidAccessKeyId" in requests.get(f"{base}/secure/k", headers=h).text


def test_presigned_expires_required_and_capped(s3_signed):
    base = s3_signed
    h = sign_request("PUT", f"{base}/prex", "AKIDEXAMPLE", "secret123")
    assert requests.put(f"{base}/prex", headers=h).status_code == 200
    body = b"capped"
    h = sign_request("PUT", f"{base}/prex/obj", "AKIDEXAMPLE", "secret123", body)
    assert requests.put(f"{base}/prex/obj", data=body, headers=h).status_code == 200

    # over the 7-day AWS maximum: rejected even though correctly signed
    url = presign_url(
        "GET", f"{base}/prex/obj", "AKIDEXAMPLE", "secret123", expires=604801
    )
    r = requests.get(url)
    assert r.status_code == 403 and "AuthorizationQueryParametersError" in r.text

    # X-Amz-Expires stripped from an otherwise-valid URL: rejected, not
    # defaulted to 7 days
    url = presign_url("GET", f"{base}/prex/obj", "AKIDEXAMPLE", "secret123")
    stripped = "&".join(
        p for p in url.split("?", 1)[1].split("&")
        if not p.startswith("X-Amz-Expires=")
    )
    r = requests.get(url.split("?", 1)[0] + "?" + stripped)
    assert r.status_code == 403

    # boundary value still works
    url = presign_url(
        "GET", f"{base}/prex/obj", "AKIDEXAMPLE", "secret123", expires=604800
    )
    r = requests.get(url)
    assert r.status_code == 200 and r.content == body


def test_sigv4_body_hash_binding(s3_signed):
    """The signed x-amz-content-sha256 must match the actual body: a
    tampered payload under a valid signature is rejected."""
    base = s3_signed
    h = sign_request("PUT", f"{base}/bind", "AKIDEXAMPLE", "secret123")
    assert requests.put(f"{base}/bind", headers=h).status_code == 200

    body = b"original payload"
    h = sign_request("PUT", f"{base}/bind/obj", "AKIDEXAMPLE", "secret123", body)
    # on-path attacker swaps the body, keeps headers+signature
    r = requests.put(f"{base}/bind/obj", data=b"tampered payload", headers=h)
    assert r.status_code == 403 and "Mismatch" in r.text
    # untampered goes through
    r = requests.put(f"{base}/bind/obj", data=body, headers=h)
    assert r.status_code == 200


def test_conditional_reads_and_writes(s3):
    """AWS conditional requests: If-None-Match:* create-only PUT,
    If-Match compare-and-swap PUT, and 304/412 conditional GETs."""
    url = s3
    requests.put(f"{url}/cond")
    # create-only PUT succeeds once, 412s after
    r = requests.put(
        f"{url}/cond/k", data=b"v1", headers={"If-None-Match": "*"}
    )
    assert r.status_code == 200, r.text
    etag1 = r.headers["ETag"]
    r = requests.put(
        f"{url}/cond/k", data=b"v2", headers={"If-None-Match": "*"}
    )
    assert r.status_code == 412
    assert requests.get(f"{url}/cond/k").content == b"v1"
    # CAS: correct ETag swaps, stale ETag 412s
    r = requests.put(
        f"{url}/cond/k", data=b"v2", headers={"If-Match": etag1}
    )
    assert r.status_code == 200
    etag2 = r.headers["ETag"]
    r = requests.put(
        f"{url}/cond/k", data=b"v3", headers={"If-Match": etag1}
    )
    assert r.status_code == 412
    assert requests.get(f"{url}/cond/k").content == b"v2"
    # If-Match on a missing key: 412 (nothing to match)
    r = requests.put(
        f"{url}/cond/absent", data=b"x", headers={"If-Match": etag1}
    )
    assert r.status_code == 412

    # conditional GETs
    r = requests.get(f"{url}/cond/k", headers={"If-None-Match": etag2})
    assert r.status_code == 304
    lm = requests.head(f"{url}/cond/k").headers["Last-Modified"]
    r = requests.get(f"{url}/cond/k", headers={"If-Modified-Since": lm})
    assert r.status_code == 304
    r = requests.get(f"{url}/cond/k", headers={"If-Match": etag1})
    assert r.status_code == 412
    r = requests.get(f"{url}/cond/k", headers={"If-Match": etag2})
    assert r.status_code == 200 and r.content == b"v2"
    r = requests.get(
        f"{url}/cond/k",
        headers={"If-Unmodified-Since": "Thu, 01 Jan 1970 00:00:00 GMT"},
    )
    assert r.status_code == 412


def test_conditional_edge_semantics(s3):
    """Review r5: exact entity-tag list matching (no substring traps),
    If-Match:* on GET succeeds, malformed validator dates are IGNORED,
    and a versioned delete marker counts as absent for If-None-Match:*."""
    url = s3
    requests.put(f"{url}/cond2")
    r = requests.put(f"{url}/cond2/k", data=b"v1")
    etag = r.headers["ETag"].strip('"')
    # If-Match: * on an existing object -> 200 (never 412)
    r = requests.get(f"{url}/cond2/k", headers={"If-Match": "*"})
    assert r.status_code == 200
    # substring trap: a LONGER etag containing ours must NOT match
    r = requests.get(
        f"{url}/cond2/k", headers={"If-None-Match": f'"{etag}5"'}
    )
    assert r.status_code == 200  # no false 304
    r = requests.get(
        f"{url}/cond2/k",
        headers={"If-None-Match": f'"other", W/"{etag}"'},
    )
    assert r.status_code == 304  # list member + weak prefix match
    # malformed date validators are ignored, not 412
    r = requests.get(
        f"{url}/cond2/k", headers={"If-Unmodified-Since": "not-a-date"}
    )
    assert r.status_code == 200
    # versioned bucket: delete marker = logically absent
    requests.put(
        f"{url}/cond2?versioning",
        data=b"<VersioningConfiguration><Status>Enabled</Status>"
        b"</VersioningConfiguration>",
    )
    requests.put(f"{url}/cond2/vk", data=b"x")
    requests.delete(f"{url}/cond2/vk")
    assert requests.get(f"{url}/cond2/vk").status_code == 404
    r = requests.put(
        f"{url}/cond2/vk", data=b"fresh", headers={"If-None-Match": "*"}
    )
    assert r.status_code == 200, r.text
    assert requests.get(f"{url}/cond2/vk").content == b"fresh"


def test_copy_source_conditionals(s3):
    """x-amz-copy-source-if-* preconditions on CopyObject."""
    url = s3
    requests.put(f"{url}/csrc")
    r = requests.put(f"{url}/csrc/a", data=b"orig")
    etag = r.headers["ETag"]
    # matching if-match copies; stale if-match 412s
    r = requests.put(
        f"{url}/csrc/b",
        headers={
            "x-amz-copy-source": "/csrc/a",
            "x-amz-copy-source-if-match": etag,
        },
    )
    assert r.status_code == 200, r.text
    assert requests.get(f"{url}/csrc/b").content == b"orig"
    r = requests.put(
        f"{url}/csrc/c",
        headers={
            "x-amz-copy-source": "/csrc/a",
            "x-amz-copy-source-if-match": '"deadbeef"',
        },
    )
    assert r.status_code == 412
    assert requests.get(f"{url}/csrc/c").status_code == 404
    # if-none-match matching -> 412
    r = requests.put(
        f"{url}/csrc/d",
        headers={
            "x-amz-copy-source": "/csrc/a",
            "x-amz-copy-source-if-none-match": etag,
        },
    )
    assert r.status_code == 412
    # unmodified-since in the past -> 412; malformed -> ignored
    r = requests.put(
        f"{url}/csrc/e",
        headers={
            "x-amz-copy-source": "/csrc/a",
            "x-amz-copy-source-if-unmodified-since":
                "Thu, 01 Jan 1970 00:00:00 GMT",
        },
    )
    assert r.status_code == 412
    r = requests.put(
        f"{url}/csrc/f",
        headers={
            "x-amz-copy-source": "/csrc/a",
            "x-amz-copy-source-if-unmodified-since": "garbage",
        },
    )
    assert r.status_code == 200
