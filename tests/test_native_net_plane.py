"""Native network byte plane (ISSUE 12): shard net-plane egress/ingress
bit identity vs the Python plane, fused copy-in CRC verify-and-exclude,
mid-stream death and armed-chaos routing, the O_DIRECT sink fallback,
sendfile-vs-buffered HTTP body identity through a real PooledHTTPServer,
and the fastread loader's one-warning degrade.
"""

from __future__ import annotations

import os
import socket
import threading
import urllib.request

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import net_plane
from seaweedfs_tpu.ec.backend import CpuBackend
from seaweedfs_tpu.ec.bitrot import BitrotProtection, ShardChecksumBuilder
from seaweedfs_tpu.ec.context import ECContext, ECError
from seaweedfs_tpu.ec.peer_rebuild import (
    PeerFetchTransient,
    rebuild_from_peers,
    staging_dir,
)
from seaweedfs_tpu.utils import native
from seaweedfs_tpu.utils.crc import crc32c
from seaweedfs_tpu.utils.retry import RetryPolicy

CTX = ECContext(4, 2)
BLOCK = 4096
SHARD_SIZE = 3 * BLOCK + 57  # ragged: partial final granule on purpose

FAST = RetryPolicy(max_attempts=3, base_delay=0.0, max_delay=0.0, jitter=0.0)


# ------------------------------------------------------------ primitives


def test_sendv_recv_into_roundtrip_with_fused_crc():
    """Scatter-gather egress + direct-landing ingress are byte-exact,
    and the granule CRCs rolled DURING the copy-in match a separate
    CRC pass over the landed bytes."""
    a, b = socket.socketpair()
    try:
        parts = [
            b"x" * 3000,
            np.random.default_rng(0).integers(0, 256, 5000, dtype=np.uint8),
            memoryview(b"tail" * 25),
        ]
        total = sum(len(p) for p in parts)
        sent = native.sendv(a.fileno(), parts, timeout_ms=5000)
        assert sent == total
        dst = np.zeros(total, np.uint8)
        crc_state = np.zeros(1, np.uint32)
        filled = np.zeros(1, np.uint64)
        out_crcs = np.zeros(total // 1024 + 2, np.uint32)
        out_counts = np.zeros(1, np.int32)
        got = native.recv_into(
            b.fileno(), dst, total, timeout_ms=5000, granule=1024,
            crc_state=crc_state, filled_state=filled,
            out_crcs=out_crcs, out_counts=out_counts,
        )
        assert got == total
        ref = b"".join(bytes(p) for p in parts)
        assert dst.tobytes() == ref
        for i in range(int(out_counts[0])):
            assert int(out_crcs[i]) == crc32c(ref[i * 1024 : (i + 1) * 1024])
        tail = ref[int(out_counts[0]) * 1024 :]
        if tail:
            assert int(crc_state[0]) == crc32c(tail)
    finally:
        a.close()
        b.close()


def test_send_file_offset_and_eof_short(tmp_path):
    p = tmp_path / "f"
    payload = bytes(range(256)) * 8
    p.write_bytes(payload)
    fd = os.open(p, os.O_RDONLY)
    a, b = socket.socketpair()
    try:
        assert native.send_file(a.fileno(), fd, 100, 500, 5000) == 500
        assert b.recv(500, socket.MSG_WAITALL) == payload[100:600]
        # reading past EOF is a SHORT send, not an error (the torn-
        # stream contract the net plane inherits from the gRPC stream)
        sent = native.send_file(
            a.fileno(), fd, len(payload) - 10, 100, 5000
        )
        assert sent == 10
    finally:
        os.close(fd)
        a.close()
        b.close()


def test_recv_into_short_on_peer_close():
    a, b = socket.socketpair()
    a.sendall(b"abc")
    a.close()
    dst = np.zeros(10, np.uint8)
    got = native.recv_into(b.fileno(), dst, 10, timeout_ms=2000)
    b.close()
    assert got == 3 and dst[:3].tobytes() == b"abc"


# -------------------------------------------------------------- harness


def synth(tmp_path, local=(0, 1), seed=0, leaf=0, shard_size=SHARD_SIZE):
    """RS-consistent shard set + sidecar (v1 when leaf=0, v2 with a
    leaf level otherwise); only `local` shard files exist under
    tmp_path/local. Full copies live under tmp_path/peer (what the
    net-plane servers serve). Returns (base, peer_dir, blobs)."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (CTX.data_shards, shard_size), dtype=np.uint8)
    parity = CpuBackend(CTX).encode(data)
    shards = np.concatenate([data, parity], axis=0)
    blobs = {i: shards[i].tobytes() for i in range(CTX.total)}
    block = 4 * leaf if leaf else BLOCK  # v2: leaf must divide block
    builders = [ShardChecksumBuilder(block, leaf) for _ in range(CTX.total)]
    for i in range(CTX.total):
        builders[i].write(blobs[i])
    prot = BitrotProtection.from_builders(CTX, builders, generation=3)
    ldir = tmp_path / "local"
    pdir = tmp_path / "peer"
    ldir.mkdir(exist_ok=True)
    pdir.mkdir(exist_ok=True)
    base = str(ldir / "1")
    prot.save(base + ".ecsum")
    for i in local:
        with open(base + CTX.to_ext(i), "wb") as f:
            f.write(blobs[i])
    for i in range(CTX.total):
        with open(str(pdir / "1") + CTX.to_ext(i), "wb") as f:
            f.write(blobs[i])
    return base, str(pdir), blobs


class FilePlane:
    """A ShardNetPlane serving shard files out of a directory — the
    test stand-in for a peer volume server (generation fence included).
    """

    def __init__(self, directory, generation=3, plane_cls=None):
        self.directory = directory
        self.generation = generation
        self._fds: dict[int, int] = {}
        cls = plane_cls or net_plane.ShardNetPlane
        self.server = cls(
            "127.0.0.1", 0, self._resolve, server_label="test-peer"
        )
        self.server.start()
        self.addr = ("127.0.0.1", self.server.port)

    def _resolve(self, vid, sid, gen):
        if gen and gen != self.generation:
            raise net_plane.NetPlaneError("stale generation")
        fd = self._fds.get(sid)
        if fd is None:
            p = os.path.join(self.directory, f"{vid}" + CTX.to_ext(sid))
            if not os.path.exists(p):
                raise net_plane.NetPlaneError("shard not local")
            fd = os.open(p, os.O_RDONLY)
            self._fds[sid] = fd
        return fd, os.fstat(fd).st_size

    def close(self):
        self.server.stop()
        for fd in self._fds.values():
            os.close(fd)


@pytest.fixture
def planes_env():
    created = []

    def make(directory, **kw):
        fp = FilePlane(directory, **kw)
        created.append(fp)
        return fp

    clients = []

    def client():
        c = net_plane.NetPlaneClient(timeout=5.0, connect_timeout=1.0)
        clients.append(c)
        return c

    yield make, client
    for c in clients:
        c.close()
    for fp in created:
        fp.close()


def wire_transports(client, addr_by_peer, generation=3):
    """(fetch, fetch_into) pair over the SAME net-plane wire: fetch is
    the Python-plane bytes transport (also used for granule re-reads),
    fetch_into the native-plane landing transport."""

    def fetch(peer, sid, off, size):
        try:
            return client.read_bytes(
                addr_by_peer[peer], 1, sid, generation, off, size
            )
        except net_plane.NetPlaneUnavailable as e:
            raise PeerFetchTransient(str(e)) from e
        except net_plane.NetPlaneError as e:
            raise PeerFetchTransient(str(e)) from e

    fetch_into = net_plane.make_fetch_into(
        client, 1, generation, addr_of=lambda peer: addr_by_peer[peer]
    )
    return fetch, fetch_into


# ------------------------------------------------- bit identity (streams)


@pytest.mark.parametrize("leaf", [0, BLOCK])
def test_peer_rebuild_native_vs_python_bit_identical(
    tmp_path, monkeypatch, planes_env, leaf
):
    """The tentpole acceptance at test scale: a shard rebuilt from
    NATIVE-plane-fetched sources (sendfile egress -> recv-into pooled
    buffers, fused copy-in CRC) is byte-equal to one rebuilt from
    Python-plane fetches over the same wire, and both to the original.
    v1 and v2 sidecars, ragged tails, multi-chunk streams."""
    from seaweedfs_tpu.ec import peer_rebuild as pr

    monkeypatch.setattr(pr, "FETCH_CHUNK", 8192)  # force multi-chunk
    make, client = planes_env
    results = {}
    for tag in ("native", "python"):
        sub = tmp_path / tag
        sub.mkdir()
        base, pdir, blobs = synth(sub, local=(0,), leaf=leaf, seed=11)
        fp = make(pdir)
        c = client()
        fetch, fetch_into = wire_transports(c, {"p": fp.addr})
        rep = rebuild_from_peers(
            base,
            {1: ["p"], 2: ["p"], 3: ["p"], 4: ["p"]},
            fetch,
            ctx=CTX,
            targets=[5],
            backend=CpuBackend(CTX),
            policy=FAST,
            fetch_into=fetch_into if tag == "native" else None,
        )
        assert rep.rebuilt == [5]
        want_plane = tag
        assert set(rep.fetched_plane.values()) == {want_plane}
        results[tag] = (
            open(base + CTX.to_ext(5), "rb").read(), blobs[5]
        )
    got_n, orig = results["native"]
    got_p, _ = results["python"]
    assert got_n == got_p == orig


def test_shard_range_reads_native_vs_python_and_generation_fence(
    tmp_path, planes_env
):
    """Client-level: read_into lands exactly the requested range with
    correct fused CRCs; read_bytes over the same wire is byte-equal; a
    stale generation is a clean protocol refusal on both."""
    make, client = planes_env
    base, pdir, blobs = synth(tmp_path, local=())
    fp = make(pdir)
    c = client()
    for off, size in ((0, SHARD_SIZE), (BLOCK, 2 * BLOCK), (17, 301)):
        dst = np.zeros(size, np.uint8)
        crcs = c.read_into(fp.addr, 1, 2, 3, off, size, dst, granule=BLOCK)
        ref = blobs[2][off : off + size]
        assert dst.tobytes() == ref
        for i, lo in enumerate(range(0, size, BLOCK)):
            assert int(crcs[i]) == crc32c(ref[lo : lo + BLOCK])
        assert c.read_bytes(fp.addr, 1, 2, 3, off, size) == ref
    with pytest.raises(net_plane.NetPlaneError, match="stale generation"):
        c.read_bytes(fp.addr, 1, 2, 999, 0, 16)


# ------------------------------------------ chaos on the native ingress


class TruncatingPlane(net_plane.ShardNetPlane):
    """Advertises the full length, ships half the bytes, then kills the
    connection — a peer dying mid-sendfile."""

    def _serve_one(self, conn, vid, sid, gen, off, size):
        fd, fsize = self.resolve(vid, sid, gen)
        n = max(0, min(size, fsize - off))
        conn.sendall(net_plane._RESP.pack(0, n))
        conn.sendall(os.pread(fd, n // 2, off))
        return False


def test_native_ingress_mid_stream_death_no_partial_admit(
    tmp_path, planes_env
):
    """Mid-stream peer death on the native path: every attempt lands
    short, the holder is abandoned after retries, and with <k sources
    the rebuild REFUSES cleanly — staging wiped, no canonical shard
    file ever appears (no partial admit)."""
    make, client = planes_env
    base, pdir, blobs = synth(tmp_path, local=(0, 1))
    fp = make(pdir, plane_cls=TruncatingPlane)
    c = client()

    def fetch(peer, sid, off, size):  # peer is truly dead to python too
        raise PeerFetchTransient("peer down")

    fetch_into = net_plane.make_fetch_into(
        c, 1, 3, addr_of=lambda peer: fp.addr
    )
    with pytest.raises(ECError, match="refusing"):
        rebuild_from_peers(
            base,
            {2: ["p"], 3: ["p"]},
            fetch,
            ctx=CTX,
            targets=[5],
            backend=CpuBackend(CTX),
            policy=FAST,
            fetch_into=fetch_into,
        )
    assert not os.path.exists(base + CTX.to_ext(5))
    assert not os.path.exists(staging_dir(base))


def test_native_fused_crc_excludes_rotten_peer_and_replans(
    tmp_path, planes_env
):
    """A peer serving rot is caught by the COPY-IN CRCs (no extra byte
    pass), re-read once at granule width to rule out wire corruption,
    then excluded — and the plan re-routes to a clean holder. The
    rebuilt shard is still byte-exact."""
    make, client = planes_env
    base, pdir, blobs = synth(tmp_path, local=(0,))
    # rotten copy: same shards, one flipped byte mid-shard in shard 2
    rdir = tmp_path / "rot"
    rdir.mkdir()
    for i in range(CTX.total):
        blob = bytearray(blobs[i])
        if i == 2:
            blob[BLOCK + 17] ^= 0xFF
        with open(str(rdir / "1") + CTX.to_ext(i), "wb") as f:
            f.write(bytes(blob))
    bad = make(str(rdir))
    good = make(pdir)
    c = client()
    addr_by_peer = {"bad": bad.addr, "good": good.addr}
    fetch, fetch_into = wire_transports(c, addr_by_peer)
    rep = rebuild_from_peers(
        base,
        {1: ["good"], 2: ["bad", "good"], 3: ["good"], 4: ["good"]},
        fetch,
        ctx=CTX,
        targets=[5],
        backend=CpuBackend(CTX),
        policy=FAST,
        fetch_into=fetch_into,
    )
    assert rep.rebuilt == [5]
    assert "bad" in rep.excluded_peers
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_armed_chaos_routes_python_plane_bit_identical(
    tmp_path, planes_env
):
    """The armed-registry contract: with latency chaos armed, streams
    route through the Python plane even though fetch_into is wired (the
    byte-mutating seams need materialized bytes), and the result is
    byte-identical."""
    make, client = planes_env
    base, pdir, blobs = synth(tmp_path, local=(0,))
    fp = make(pdir)
    c = client()
    fetch, fetch_into = wire_transports(c, {"p": fp.addr})
    with faults.injected(
        "ec.peer_fetch.read", faults.latency(0.001), when=faults.every(3)
    ):
        rep = rebuild_from_peers(
            base,
            {1: ["p"], 2: ["p"], 3: ["p"]},
            fetch,
            ctx=CTX,
            targets=[5],
            backend=CpuBackend(CTX),
            policy=FAST,
            fetch_into=fetch_into,
        )
    assert rep.rebuilt == [5]
    assert set(rep.fetched_plane.values()) == {"python"}
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_peer_without_plane_falls_back_to_python_fetch(
    tmp_path, planes_env
):
    """A peer whose net-plane port refuses is a capability miss, not a
    failure: the stream rides the Python fetch, the rebuild succeeds,
    and the refusal is memoized (one connect attempt per peer)."""
    make, client = planes_env
    base, pdir, blobs = synth(tmp_path, local=(0,))
    fp = make(pdir)
    c = client()
    # plane address points at a dead port; python fetch uses the live one
    dead = ("127.0.0.1", 1)  # port 1: connect refused
    fetch, _ = wire_transports(c, {"p": fp.addr})
    fetch_into = net_plane.make_fetch_into(
        c, 1, 3, addr_of=lambda peer: dead
    )
    rep = rebuild_from_peers(
        base,
        {1: ["p"], 2: ["p"], 3: ["p"]},
        fetch,
        ctx=CTX,
        targets=[5],
        backend=CpuBackend(CTX),
        policy=FAST,
        fetch_into=fetch_into,
    )
    assert rep.rebuilt == [5]
    assert set(rep.fetched_plane.values()) == {"python"}
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_ec_native_disabled_skips_native_plane(
    tmp_path, planes_env, monkeypatch
):
    """SEAWEED_EC_NATIVE=0 forces the pure-Python plane end to end even
    with a live net plane and fetch_into wired."""
    monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
    make, client = planes_env
    base, pdir, blobs = synth(tmp_path, local=(0,))
    fp = make(pdir)
    c = client()
    fetch, fetch_into = wire_transports(c, {"p": fp.addr})
    rep = rebuild_from_peers(
        base,
        {1: ["p"], 2: ["p"], 3: ["p"]},
        fetch,
        ctx=CTX,
        targets=[5],
        backend=CpuBackend(CTX),
        policy=FAST,
        fetch_into=fetch_into,
    )
    assert set(rep.fetched_plane.values()) == {"python"}
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


# ----------------------------------------------------- O_DIRECT fallback


def test_odirect_sink_misaligned_tail_falls_back_bit_identical(
    tmp_path, monkeypatch
):
    """SEAWEED_EC_ODIRECT=1: aligned batches may ride O_DIRECT, the
    misaligned ragged tail transparently drops to buffered, and the
    bytes + BOTH sidecar CRC levels stay identical to the Python
    sink."""
    monkeypatch.setenv("SEAWEED_EC_ODIRECT", "1")
    from seaweedfs_tpu.ec.native_io import aligned_matrix
    from seaweedfs_tpu.ec.pipeline import FusedShardSink, PyShardSink

    widths = [4096 * 4, 4096 * 2, 1234]  # aligned, aligned, ragged tail
    batches = [
        np.random.default_rng(50 + i).integers(0, 256, (3, w), dtype=np.uint8)
        for i, w in enumerate(widths)
    ]
    out = {}
    for tag, cls in (("fused", FusedShardSink), ("py", PyShardSink)):
        files = [open(tmp_path / f"{tag}{i}", "w+b") for i in range(3)]
        sink = cls(files, block_size=8192, leaf_size=4096)
        for i, w in enumerate(widths):
            m = aligned_matrix(3, w)
            m[:] = batches[i]
            sink.append_rows([m[j] for j in range(3)])
        crcs, leaves = sink.block_crcs(), sink.leaf_crcs()
        if tag == "fused":
            # whatever the fs decided, the ragged tail must have
            # dropped O_DIRECT for every shard by stream end
            assert not sink.direct_flags().any()
        for f in files:
            f.flush()
            f.close()
        out[tag] = (
            [open(tmp_path / f"{tag}{i}", "rb").read() for i in range(3)],
            crcs,
            leaves,
        )
    assert out["fused"] == out["py"]


def test_odirect_encode_end_to_end_bit_identical(tmp_path, monkeypatch):
    """Full encode with the O_DIRECT knob on vs off: identical shard
    files and sidecar."""
    from seaweedfs_tpu.ec.encoder import write_ec_files

    rng = np.random.default_rng(9)
    payload = rng.integers(0, 256, 3 * 65536 + 999, dtype=np.uint8).tobytes()
    outs = {}
    for tag, flag in (("on", "1"), ("off", "0")):
        monkeypatch.setenv("SEAWEED_EC_ODIRECT", flag)
        d = tmp_path / tag
        d.mkdir()
        base = str(d / "1")
        with open(base + ".dat", "wb") as f:
            f.write(payload)
        write_ec_files(base, ctx=CTX, backend=CpuBackend(CTX))
        outs[tag] = {
            ext: open(base + ext, "rb").read()
            for ext in [CTX.to_ext(i) for i in range(CTX.total)]
        }
    assert outs["on"] == outs["off"]


# ------------------------------------------------ HTTP sendfile egress


def test_pooled_http_get_native_vs_buffered_byte_identity(monkeypatch):
    """The warm-gateway egress contract: a GET served through
    send_body's native scatter-gather sender is byte-identical to the
    SEAWEED_EC_NATIVE=0 wfile path, through a REAL PooledHTTPServer,
    and the native byte counter moves only on the native run."""
    from http.server import BaseHTTPRequestHandler

    from seaweedfs_tpu.utils import metrics as M
    from seaweedfs_tpu.utils.http_pool import PooledHTTPServer, send_body

    body = os.urandom(200 * 1024)

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            send_body(self, body)

    srv = PooledHTTPServer(("127.0.0.1", 0), H, workers=2)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        port = srv.socket.getsockname()[1]
        url = f"http://127.0.0.1:{port}/x"
        def delta(snap0, plane_name):
            cur = dict(M.net_bytes_sent_total.snapshot())
            return cur.get((plane_name, "read"), 0) - snap0.get(
                (plane_name, "read"), 0
            )

        before = dict(M.net_bytes_sent_total.snapshot())
        got_native = urllib.request.urlopen(url, timeout=10).read()
        assert _settle(lambda: delta(before, "native") == len(body))
        mid = dict(M.net_bytes_sent_total.snapshot())
        monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
        got_python = urllib.request.urlopen(url, timeout=10).read()
        assert got_native == got_python == body
        assert _settle(lambda: delta(mid, "python") == len(body))
    finally:
        srv.shutdown()
        srv.server_close()


# ------------------------------------------------- fastread loader gate


def test_fastread_failed_make_degrades_with_one_attempt(tmp_path, monkeypatch):
    """A failed sidecar build is cached: ImportError every call, make
    runs ONCE — the degrade is one warning, not per-call log spam."""
    from seaweedfs_tpu.utils import fastread

    bad = tmp_path / "native"
    bad.mkdir()
    (bad / "Makefile").write_text("all:\n\tfalse\n")
    (bad / "fastread.cpp").write_text("// never compiles via this Makefile")
    monkeypatch.setattr(fastread, "_NATIVE_DIR", str(bad))
    monkeypatch.setattr(fastread, "_lib", None)
    monkeypatch.setattr(fastread, "_lib_err", None)
    calls = []
    real_run = fastread.subprocess.run

    def counting_run(*a, **kw):
        calls.append(a)
        return real_run(*a, **kw)

    monkeypatch.setattr(fastread.subprocess, "run", counting_run)
    with pytest.raises(ImportError):
        fastread.lib()
    with pytest.raises(ImportError):
        fastread.lib()
    assert len(calls) == 1


def test_fastread_stale_on_shared_header_change(tmp_path, monkeypatch):
    """The sidecar shares sn_net.h with the core: a header newer than
    the .so must trigger a rebuild (the PR 10-era loader only checked
    existence and would happily serve a stale ABI)."""
    from seaweedfs_tpu.utils import fastread

    d = tmp_path / "native"
    d.mkdir()
    so = d / "libseaweed_fastread.so"
    so.write_bytes(b"x")
    (d / "fastread.cpp").write_text("//")
    (d / "sn_net.h").write_text("//")
    monkeypatch.setattr(fastread, "_NATIVE_DIR", str(d))
    old = os.path.getmtime(so)
    for p in (d / "fastread.cpp", d / "sn_net.h"):
        os.utime(p, (old - 5, old - 5))
    os.utime(d / "Makefile", (old - 5, old - 5)) if (
        d / "Makefile"
    ).exists() else None
    assert not fastread._stale(str(so))
    os.utime(d / "sn_net.h", (old + 5, old + 5))
    assert fastread._stale(str(so))


# ------------------------------------------- needle/chunk opcode (ISSUE 13)
# The warm gateway path's filer->volume chunk fetch over the same
# sidecar: whole-needle payloads spliced with sendfile, landed in
# pooled aligned buffers with the CRC fused into the copy-in.


def _settle(fn, timeout=5.0):
    """Egress byte counters land AFTER the last payload byte is on the
    wire, so a fast client can observe the full body before the serving
    thread runs its bookkeeping — poll briefly instead of racing it."""
    import time as _time

    deadline = _time.monotonic() + timeout
    while not fn() and _time.monotonic() < deadline:
        _time.sleep(0.005)
    return fn()


def _refuse_shards(vid, sid, gen):
    raise net_plane.NetPlaneError("no shards here")


def _needle_plane(tmp_path, payload, crc=None, resolve=None):
    p = tmp_path / "needle.dat"
    p.write_bytes(b"HDR!" + payload + b"TRAILER")
    want = crc32c(payload) if crc is None else crc

    def resolve_needle(vid, nid, cookie):
        assert (vid, nid, cookie) == (7, 0xABC, 0x55)
        fd = os.open(p, os.O_RDONLY)
        return fd, 4, len(payload), want, True

    srv = net_plane.ShardNetPlane(
        "127.0.0.1", 0, _refuse_shards,
        resolve_needle=resolve if resolve is not None else resolve_needle,
        server_label="needle-test",
    )
    srv.start()
    return srv


@pytest.mark.parametrize("plane", ["native", "python"])
def test_needle_read_roundtrip(tmp_path, monkeypatch, plane):
    """Whole-needle fetch over the chunk-read opcode is byte-exact on
    both landing planes, and the server counts the egress on the right
    plane (sendfile for native, pread+sendall for python)."""
    if plane == "python":
        monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
    payload = np.random.default_rng(3).integers(
        0, 256, 300_000, dtype=np.uint8
    ).tobytes()
    srv = _needle_plane(tmp_path, payload)
    client = net_plane.NetPlaneClient()
    try:
        got = client.read_needle(
            ("127.0.0.1", srv.port), 7, 0xABC, 0x55
        )
        assert got == payload
        assert srv.needle_requests == 1
        if plane == "native":
            assert _settle(lambda: srv.sendfile_bytes == len(payload))
            assert srv.python_bytes == 0
        else:
            assert _settle(lambda: srv.python_bytes == len(payload))
            assert srv.sendfile_bytes == 0
        # second read reuses the pooled connection
        assert client.read_needle(
            ("127.0.0.1", srv.port), 7, 0xABC, 0x55
        ) == payload
    finally:
        client.close()
        srv.stop()


def test_needle_read_crc_mismatch_refused(tmp_path):
    """A stored CRC that doesn't match the landed bytes (vacuum racing
    the locate, stale fd) surfaces as NetPlaneError — the caller falls
    back to the locked HTTP path — never as silent wrong bytes."""
    payload = b"q" * 70_000
    srv = _needle_plane(tmp_path, payload, crc=crc32c(payload) ^ 0xDEAD)
    client = net_plane.NetPlaneClient()
    try:
        with pytest.raises(net_plane.NetPlaneError, match="CRC mismatch"):
            client.read_needle(("127.0.0.1", srv.port), 7, 0xABC, 0x55)
    finally:
        client.close()
        srv.stop()


def test_needle_read_refusal_message(tmp_path):
    """Resolver refusals (not here / EC / TTL'd / cookie mismatch)
    travel as protocol errors with the message intact."""

    def refuse(vid, nid, cookie):
        raise net_plane.NetPlaneError("volume not here (or EC)")

    srv = _needle_plane(tmp_path, b"", resolve=refuse)
    client = net_plane.NetPlaneClient()
    try:
        with pytest.raises(net_plane.NetPlaneError, match="not here"):
            client.read_needle(("127.0.0.1", srv.port), 7, 0xABC, 0x55)
        # the connection survives a refusal: shard opcode still works
        with pytest.raises(net_plane.NetPlaneError, match="no shards"):
            client.read_bytes(("127.0.0.1", srv.port), 1, 0, 0, 0, 10)
    finally:
        client.close()
        srv.stop()


def test_needle_read_refused_when_faults_armed(tmp_path):
    """An ARMED registry refuses needle serving outright: byte-mutating
    chaos belongs to the Python-HTTP path's storage fault points, so
    the client's fallback (HTTP) is the chaos surface."""
    payload = b"z" * 10_000
    srv = _needle_plane(tmp_path, payload)
    client = net_plane.NetPlaneClient()
    try:
        with faults.injected(
            "unrelated.point", faults.latency(0.0), when=faults.always()
        ):
            assert faults.active()
            with pytest.raises(
                net_plane.NetPlaneError, match="registry armed"
            ):
                client.read_needle(("127.0.0.1", srv.port), 7, 0xABC, 0x55)
        # disarmed again: served
        assert client.read_needle(
            ("127.0.0.1", srv.port), 7, 0xABC, 0x55
        ) == payload
    finally:
        client.close()
        srv.stop()


def test_no_plane_memo_ttl_revival(tmp_path):
    """ISSUE 13 satellite: the peer-without-plane memo must NOT be
    forever — a sidecar that comes up later (late boot, rolling
    restart) is re-probed after the TTL and re-adopted."""
    import time as _time

    hold = socket.socket()
    hold.bind(("127.0.0.1", 0))
    port = hold.getsockname()[1]
    hold.close()  # nothing listens here now
    client = net_plane.NetPlaneClient(unavailable_ttl=0.3)
    payload = b"revive" * 1000
    try:
        with pytest.raises(net_plane.NetPlaneUnavailable):
            client.read_needle(("127.0.0.1", port), 7, 0xABC, 0x55)
        # memoized: immediate retry refuses without a connect
        with pytest.raises(net_plane.NetPlaneUnavailable):
            client.read_needle(("127.0.0.1", port), 7, 0xABC, 0x55)
        p = tmp_path / "needle.dat"
        p.write_bytes(b"HDR!" + payload + b"TRAILER")

        def resolve_needle(vid, nid, cookie):
            fd = os.open(p, os.O_RDONLY)
            return fd, 4, len(payload), crc32c(payload), True

        srv = net_plane.ShardNetPlane(
            "127.0.0.1", port, _refuse_shards,
            resolve_needle=resolve_needle,
        )
        srv.start()
        try:
            _time.sleep(0.35)  # past the TTL: the revived peer re-probes
            assert client.read_needle(
                ("127.0.0.1", port), 7, 0xABC, 0x55
            ) == payload
        finally:
            srv.stop()
    finally:
        client.close()


def test_no_plane_reset_hook(tmp_path):
    """reset() drops the memo immediately — no TTL wait."""
    hold = socket.socket()
    hold.bind(("127.0.0.1", 0))
    port = hold.getsockname()[1]
    hold.close()
    client = net_plane.NetPlaneClient(unavailable_ttl=3600.0)
    payload = b"rst" * 500
    try:
        with pytest.raises(net_plane.NetPlaneUnavailable):
            client.read_needle(("127.0.0.1", port), 7, 0xABC, 0x55)
        p = tmp_path / "needle.dat"
        p.write_bytes(b"HDR!" + payload + b"TRAILER")

        def resolve_needle(vid, nid, cookie):
            fd = os.open(p, os.O_RDONLY)
            return fd, 4, len(payload), crc32c(payload), True

        srv = net_plane.ShardNetPlane(
            "127.0.0.1", port, _refuse_shards,
            resolve_needle=resolve_needle,
        )
        srv.start()
        try:
            # hour-long TTL: still refused from the memo...
            with pytest.raises(net_plane.NetPlaneUnavailable):
                client.read_needle(("127.0.0.1", port), 7, 0xABC, 0x55)
            client.reset(("127.0.0.1", port))
            # ...until the operator hook clears it
            assert client.read_needle(
                ("127.0.0.1", port), 7, 0xABC, 0x55
            ) == payload
        finally:
            srv.stop()
    finally:
        client.close()


def test_recv_overlap_env_gate():
    """ISSUE 13 satellite: the overlapped recv+CRC core gate
    (>=4 hardware threads) is env-tunable for the multi-core
    re-measure recipe; the 256 KiB size floor always applies."""
    prev = os.environ.get("SEAWEED_EC_NET_OVERLAP")
    try:
        os.environ["SEAWEED_EC_NET_OVERLAP"] = "1"
        assert native.recv_overlap_active(1 << 20) is True
        assert native.recv_overlap_active(4096) is False  # size floor
        os.environ["SEAWEED_EC_NET_OVERLAP"] = "0"
        assert native.recv_overlap_active(1 << 20) is False
        os.environ.pop("SEAWEED_EC_NET_OVERLAP")
        auto = native.recv_overlap_active(1 << 20)
        assert auto is ((os.cpu_count() or 1) >= 4)
    finally:
        if prev is None:
            os.environ.pop("SEAWEED_EC_NET_OVERLAP", None)
        else:
            os.environ["SEAWEED_EC_NET_OVERLAP"] = prev


def test_overlap_forced_on_is_bit_identical():
    """Forcing the overlapped core on a small host must stay byte- and
    CRC-exact (it is a scheduling change, not a data-path change)."""
    prev = os.environ.get("SEAWEED_EC_NET_OVERLAP")
    a, b = socket.socketpair()
    try:
        os.environ["SEAWEED_EC_NET_OVERLAP"] = "1"
        payload = np.random.default_rng(9).integers(
            0, 256, 512 * 1024, dtype=np.uint8
        ).tobytes()

        def send():
            a.sendall(payload)

        t = threading.Thread(target=send)
        t.start()
        dst = np.zeros(len(payload), np.uint8)
        crc_state = np.zeros(1, np.uint32)
        filled = np.zeros(1, np.uint64)
        out_crcs = np.zeros(len(payload) // 65536 + 2, np.uint32)
        out_counts = np.zeros(1, np.int32)
        got = native.recv_into(
            b.fileno(), dst, len(payload), timeout_ms=10000,
            granule=65536, crc_state=crc_state, filled_state=filled,
            out_crcs=out_crcs, out_counts=out_counts,
        )
        t.join()
        assert got == len(payload)
        assert dst.tobytes() == payload
        for i in range(int(out_counts[0])):
            assert int(out_crcs[i]) == crc32c(
                payload[i * 65536 : (i + 1) * 65536]
            )
    finally:
        if prev is None:
            os.environ.pop("SEAWEED_EC_NET_OVERLAP", None)
        else:
            os.environ["SEAWEED_EC_NET_OVERLAP"] = prev
        a.close()
        b.close()


# -------------------------------------------- O_DIRECT on a real block fs
# ROADMAP carried item (d): this box's overlay/9p/tmpfs all reject or
# bypass O_DIRECT, so engagement (direct_flags()==1 through an aligned
# stream) could never be asserted here. Point
# SEAWEED_TEST_BLOCK_FS_DIR at a writable directory on a real
# block-backed filesystem (ext4/xfs/btrfs) to run the positive test.

_NO_DIRECT_FS = {
    "overlay", "9p", "tmpfs", "ramfs", "nfs", "nfs4", "fuse", "zfs",
}


def _fs_type(path: str) -> str:
    """Filesystem type serving `path` (longest /proc/mounts prefix)."""
    best, best_type = "", "unknown"
    try:
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 3 and path.startswith(parts[1]) and len(
                    parts[1]
                ) > len(best):
                    best, best_type = parts[1], parts[2]
    except OSError:
        pass
    return best_type


def test_odirect_engages_on_block_fs(monkeypatch):
    """On a real block-backed fs, an all-aligned stream must KEEP
    O_DIRECT on every shard fd end to end — the page-cache bypass
    actually engages instead of silently degrading to buffered."""
    target = os.environ.get("SEAWEED_TEST_BLOCK_FS_DIR", "")
    if not target:
        pytest.skip("SEAWEED_TEST_BLOCK_FS_DIR not set")
    fs = _fs_type(os.path.abspath(target))
    if fs in _NO_DIRECT_FS:
        pytest.skip(f"{target} is {fs}: O_DIRECT unsupported/bypassed")
    monkeypatch.setenv("SEAWEED_EC_ODIRECT", "1")
    import tempfile

    from seaweedfs_tpu.ec.native_io import aligned_matrix
    from seaweedfs_tpu.ec.pipeline import FusedShardSink

    with tempfile.TemporaryDirectory(dir=target) as d:
        widths = [4096 * 4, 4096 * 2, 4096]  # every batch 4096-aligned
        batches = [
            np.random.default_rng(70 + i).integers(
                0, 256, (3, w), dtype=np.uint8
            )
            for i, w in enumerate(widths)
        ]
        files = [open(os.path.join(d, f"s{i}"), "w+b") for i in range(3)]
        try:
            sink = FusedShardSink(files, block_size=8192, leaf_size=4096)
            for i, w in enumerate(widths):
                m = aligned_matrix(3, w)
                m[:] = batches[i]
                sink.append_rows([m[j] for j in range(3)])
                # an aligned stream must never drop to buffered
                assert sink.direct_flags().all(), (
                    f"O_DIRECT dropped mid-stream on {fs} after width {w}"
                )
            ref = np.concatenate(batches, axis=1)
            for i, f in enumerate(files):
                f.flush()
                with open(f.name, "rb") as rf:
                    assert rf.read() == ref[i].tobytes()
        finally:
            for f in files:
                f.close()


def test_needle_reads_fan_out_concurrently(tmp_path):
    """Warm GETs arrive from N HTTP workers: needle reads check OUT a
    connection per in-flight request (no one-socket serialization),
    every reader gets byte-exact payload, and the pool is bounded."""
    payload = np.random.default_rng(5).integers(
        0, 256, 120_000, dtype=np.uint8
    ).tobytes()
    srv = _needle_plane(tmp_path, payload)
    client = net_plane.NetPlaneClient()
    errs: list = []

    def rd():
        try:
            assert client.read_needle(
                ("127.0.0.1", srv.port), 7, 0xABC, 0x55
            ) == payload
        except Exception as e:  # pragma: no cover - fails the assert
            errs.append(e)

    try:
        threads = [threading.Thread(target=rd) for _ in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert srv.needle_requests == 12
        with client._lock:
            pooled = sum(len(v) for v in client._npool.values())
        assert 1 <= pooled <= client._npool_max
    finally:
        client.close()
        srv.stop()


def test_needle_pool_discards_idle_connections(tmp_path):
    """A pooled connection parked past the idle TTL is discarded at
    checkout (the server reaps idle peers at its request timeout) —
    the next GET dials fresh instead of burning its fast path on a
    dead socket."""
    payload = b"idle" * 2000
    srv = _needle_plane(tmp_path, payload)
    client = net_plane.NetPlaneClient()
    client._npool_idle_s = 0.05
    addr = ("127.0.0.1", srv.port)
    try:
        assert client.read_needle(addr, 7, 0xABC, 0x55) == payload
        # simulate the server reaping the parked conn while idle
        with client._lock:
            for s, _t in client._npool.get(addr, []):
                s.close()
        import time as _time

        _time.sleep(0.1)  # past the idle TTL: checkout must discard
        assert client.read_needle(addr, 7, 0xABC, 0x55) == payload
    finally:
        client.close()
        srv.stop()


def test_operations_negative_caches_volume_refusals(tmp_path):
    """A VOLUME-level plane refusal (EC/TTL'd/tiered) is negative-
    cached per vid: later chunk reads skip the refusal round trip and
    go straight to HTTP until the TTL expires."""
    import time as _time

    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.storage.file_id import FileId

    def refuse(vid, nid, cookie):
        raise net_plane.NetPlaneVolumeRefusal("volume not here (or EC)")

    srv = _needle_plane(tmp_path, b"", resolve=refuse)
    assert srv.port > 11023  # derive_port(g) must not wrap below
    ops = Operations(master="localhost:1")
    try:
        loc = type(
            "Loc", (), {"url": "127.0.0.1:80",
                        "grpc_port": srv.port - 10000}
        )()
        f = FileId(9, 0xABC, 0x55)
        assert ops._try_plane_read(loc, f) is None
        first = srv.requests
        assert first >= 1
        # negative-cached: no further round trips for this volume
        assert ops._try_plane_read(loc, f) is None
        assert srv.requests == first
        assert 9 in ops._plane_refused
        # TTL expiry re-probes (the volume may have converted back)
        ops._plane_refused[9] = _time.monotonic() - 3600
        assert ops._try_plane_read(loc, f) is None
        assert srv.requests == first + 1
    finally:
        ops.close()
        srv.stop()


def test_needle_level_refusal_not_negative_cached(tmp_path):
    """Per-needle refusals (not found / cookie mismatch, status 1) must
    NOT poison the per-volume negative cache — other needles on the
    volume may serve fine."""
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.storage.file_id import FileId

    def refuse(vid, nid, cookie):
        raise net_plane.NetPlaneError("needle abc not found")

    srv = _needle_plane(tmp_path, b"", resolve=refuse)
    assert srv.port > 11023
    ops = Operations(master="localhost:1")
    try:
        loc = type(
            "Loc", (), {"url": "127.0.0.1:80",
                        "grpc_port": srv.port - 10000}
        )()
        assert ops._try_plane_read(loc, FileId(9, 0xABC, 0x55)) is None
        assert 9 not in ops._plane_refused
        # the plane is re-probed for the next needle on the volume
        first = srv.requests
        assert ops._try_plane_read(loc, FileId(9, 0xDEF, 0x55)) is None
        assert srv.requests == first + 1
    finally:
        ops.close()
        srv.stop()


# ------------------------------------------ needle write opcode (ISSUE 18)
# The PUT path's native twin: client header + payload on a pooled
# connection, server lands into pooled buffers (CRC fused into the
# copy-in), resolver appends to the volume, ACK carries the STORED CRC.


def _write_plane(resolve_write=None, resolve_blob=None):
    srv = net_plane.ShardNetPlane(
        "127.0.0.1", 0, _refuse_shards,
        resolve_write=resolve_write, resolve_blob=resolve_blob,
        server_label="write-test",
    )
    srv.start()
    return srv


@pytest.mark.parametrize("plane", ["native", "python"])
def test_needle_write_roundtrip(monkeypatch, plane):
    """One needle over the write opcode on both landing planes: the
    resolver sees the exact payload + meta, the ACK certifies the
    stored CRC, and the server counts ingress on the right plane.
    Ragged payload (not a granule multiple) exercises the fused CRC's
    tail path."""
    if plane == "python":
        monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
    payload = np.random.default_rng(7).integers(
        0, 256, 300_001, dtype=np.uint8
    ).tobytes()
    stored = {}

    def resolve_write(vid, nid, cookie, data, md):
        stored[(vid, nid)] = (cookie, data, dict(md))
        return len(data), crc32c(data)

    srv = _write_plane(resolve_write)
    client = net_plane.NetPlaneClient()
    try:
        size, crc = client.write_needle(
            ("127.0.0.1", srv.port), 7, 0xABC, 0x55, payload,
            name=b"f.bin", mime=b"application/x-test", fsync=True,
        )
        assert size == len(payload) and crc == crc32c(payload)
        cookie, data, md = stored[(7, 0xABC)]
        assert cookie == 0x55 and data == payload
        assert md["x-sw-w-fsync"] == "1"
        assert net_plane._unb64(md["x-sw-w-name"]) == b"f.bin"
        assert net_plane._unb64(md["x-sw-w-mime"]) == b"application/x-test"
        assert srv.write_requests == 1
        if plane == "native":
            assert srv.write_native_bytes == len(payload)
            assert srv.write_python_bytes == 0
        else:
            assert srv.write_python_bytes == len(payload)
            assert srv.write_native_bytes == 0
        # second write reuses the pooled connection
        client.write_needle(("127.0.0.1", srv.port), 7, 0xDEF, 0x66, b"x")
        assert srv.write_requests == 2
    finally:
        client.close()
        srv.stop()


def test_needle_write_volume_refusal_negative_cachable():
    """A volume-level write refusal (status 2) surfaces with
    volume_refusal=True — clients negative-cache the vid — and the
    pooled connection SURVIVES (the server drains the payload before
    refusing)."""

    def refuse(vid, nid, cookie, data, md):
        raise net_plane.NetPlaneVolumeRefusal("volume not here")

    srv = _write_plane(refuse)
    client = net_plane.NetPlaneClient()
    try:
        with pytest.raises(net_plane.NetPlaneError, match="not here") as ei:
            client.write_needle(
                ("127.0.0.1", srv.port), 1, 2, 3, b"zz" * 5000
            )
        assert getattr(ei.value, "volume_refusal", False)
        with pytest.raises(net_plane.NetPlaneError, match="not here"):
            client.write_needle(("127.0.0.1", srv.port), 1, 9, 3, b"y")
        assert srv.write_requests == 2, "refusal killed the connection"
    finally:
        client.close()
        srv.stop()


def test_needle_write_without_resolver_refused():
    """A read-only sidecar (no resolve_write wired) refuses write
    frames in-protocol instead of dropping the connection."""
    srv = _write_plane(resolve_write=None)
    client = net_plane.NetPlaneClient()
    try:
        with pytest.raises(
            net_plane.NetPlaneError, match="not served here"
        ):
            client.write_needle(("127.0.0.1", srv.port), 1, 2, 3, b"data")
        assert srv.write_requests == 0
    finally:
        client.close()
        srv.stop()


def test_needle_write_stored_crc_mismatch_raises():
    """An ACK whose stored CRC disagrees with what the client sent is
    an error, not a silent accept — end-to-end bit certification."""

    def liar(vid, nid, cookie, data, md):
        return len(data), crc32c(data) ^ 0xBAD

    srv = _write_plane(liar)
    client = net_plane.NetPlaneClient()
    try:
        with pytest.raises(
            net_plane.NetPlaneError, match="stored CRC mismatch"
        ):
            client.write_needle(("127.0.0.1", srv.port), 1, 2, 3, b"abc")
    finally:
        client.close()
        srv.stop()


def test_write_plane_admissible_namespaces():
    """Write-path chaos (ec.net.write.*, volume.write.*) leaves the
    write plane admissible — the crash matrix rides the native path —
    while any OTHER armed point routes writes to the fallback."""
    assert net_plane.write_plane_admissible()
    with faults.injected(
        "ec.net.write.before_pwrite", faults.latency(0.0),
        when=faults.always(),
    ):
        assert net_plane.write_plane_admissible()
    with faults.injected(
        "volume.write.before_fsync", faults.latency(0.0),
        when=faults.always(),
    ):
        assert net_plane.write_plane_admissible()
    with faults.injected(
        "storage.disk.read_at", faults.latency(0.0), when=faults.always()
    ):
        assert not net_plane.write_plane_admissible()


def test_needle_write_refused_when_foreign_chaos_armed():
    """Server-side: an armed non-write fault registry refuses write
    frames (drained, in-protocol) so chaos runs against the gRPC/HTTP
    fallback; write-namespace chaos is served."""
    stored = {}

    def resolve_write(vid, nid, cookie, data, md):
        stored[nid] = data
        return len(data), crc32c(data)

    srv = _write_plane(resolve_write)
    client = net_plane.NetPlaneClient()
    try:
        with faults.injected(
            "unrelated.point", faults.latency(0.0), when=faults.always()
        ):
            with pytest.raises(
                net_plane.NetPlaneError, match="registry armed"
            ):
                client.write_needle(
                    ("127.0.0.1", srv.port), 1, 2, 3, b"k" * 100
                )
        with faults.injected(
            "ec.net.write.before_pwrite", faults.latency(0.0),
            when=faults.always(),
        ):
            client.write_needle(("127.0.0.1", srv.port), 1, 2, 3, b"served")
        assert stored[2] == b"served"
    finally:
        client.close()
        srv.stop()


@pytest.mark.parametrize("plane", ["native", "python"])
def test_blob_write_roundtrip_and_unlink(tmp_path, monkeypatch, plane):
    """kind=blob: extents land at their file offset (sn_recv_file on
    the native plane — socket to disk, CRC fused, zero Python byte
    handling), the ACK CRC matches the payload, and op=unlink removes
    the blob via the resolver."""
    if plane == "python":
        monkeypatch.setenv("SEAWEED_EC_NATIVE", "0")
    root = tmp_path / "blobs"

    def resolve_blob(path, op, md):
        p = root / path
        if op == "unlink":
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass
            return None
        p.parent.mkdir(parents=True, exist_ok=True)
        return os.open(p, os.O_CREAT | os.O_RDWR, 0o644)

    srv = _write_plane(resolve_blob=resolve_blob)
    client = net_plane.NetPlaneClient()
    addr = ("127.0.0.1", srv.port)
    data = np.random.default_rng(5).integers(
        0, 256, 123_457, dtype=np.uint8
    ).tobytes()
    try:
        assert client.write_blob(addr, "sub/s.ec00", 8, data) == len(data)
        raw = (root / "sub/s.ec00").read_bytes()
        assert raw[:8] == b"\0" * 8 and raw[8:] == data
        # append-extend the same blob at the watermark
        client.write_blob(addr, "sub/s.ec00", 8 + len(data), b"tail")
        assert (root / "sub/s.ec00").read_bytes()[8 + len(data):] == b"tail"
        if plane == "native":
            assert srv.write_native_bytes == len(data) + 4
        else:
            assert srv.write_python_bytes == len(data) + 4
        client.unlink_blob(addr, "sub/s.ec00")
        assert not (root / "sub/s.ec00").exists()
    finally:
        client.close()
        srv.stop()


# ------------------------------- write path end to end (cluster level)
# Bit identity across transports, sidecar-death fallback, and replica
# fan-out riding the plane — against real master + volume servers.


@pytest.fixture
def write_cluster(tmp_path):
    import time as _time

    from conftest import allocate_port as free_port
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    deadline = _time.time() + 10
    while len(master.topo.nodes) < 2:
        assert _time.time() < deadline, "volume servers did not register"
        _time.sleep(0.05)
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def _canon_record(raw: bytes) -> bytes:
    """Needle record bytes with the append timestamp normalized — the
    only field two transports may legitimately disagree on."""
    from seaweedfs_tpu.storage.needle import Needle

    n = Needle.from_bytes(bytes(raw))
    n.append_at_ns = 1
    return n.to_bytes()


def _latest_record(vs, vid: int, nid: int) -> bytes:
    from seaweedfs_tpu.storage.types import actual_offset

    vol = vs.store.find_volume(vid)
    assert vol is not None
    nv = vol.needle_map.get(nid)
    assert nv is not None
    return vol._pread_record(actual_offset(nv.offset), nv.size)


def _holder(vols, vid):
    for vs in vols:
        if vs.store.find_volume(vid) is not None:
            return vs
    raise AssertionError(f"volume {vid} on no server")


def test_write_bit_identity_plane_vs_http_vs_grpc(write_cluster):
    """ISSUE 18 satellite: the SAME fid written over the native write
    opcode, the HTTP multipart POST, and the gRPC WriteNeedle lands
    byte-identical needle records on disk (timestamp normalized) —
    ragged payload so the fused CRC's tail path is in the loop."""
    import requests as _requests

    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.pb import cluster_pb2 as pb
    from seaweedfs_tpu.storage.file_id import FileId

    master, vols = write_cluster
    ops = Operations(f"localhost:{master.port}")
    payload = np.random.default_rng(11).integers(
        0, 256, 123_457, dtype=np.uint8
    ).tobytes()
    try:
        before = sum(v.net_plane.write_requests for v in vols)
        fid = ops.upload(payload, name="same.bin", mime="application/x-test")
        assert sum(v.net_plane.write_requests for v in vols) == before + 1, (
            "upload did not ride the native write plane"
        )
        f = FileId.parse(fid)
        vs = _holder(vols, f.volume_id)
        raw_plane = _latest_record(vs, f.volume_id, f.needle_id)

        # HTTP multipart to the same fid (the bit-identical fallback)
        loc = ops.master.lookup(f.volume_id)[0]
        r = _requests.post(
            f"http://{loc.url}/{fid}",
            files={"file": ("same.bin", payload, "application/x-test")},
        )
        assert r.status_code == 201, r.text
        raw_http = _latest_record(vs, f.volume_id, f.needle_id)

        # in-process gRPC servicer call
        resp = vs.service.WriteNeedle(
            pb.WriteNeedleRequest(
                volume_id=f.volume_id, needle_id=f.needle_id,
                cookie=f.cookie, data=payload, name="same.bin",
                mime="application/x-test", is_replicate=True,
            ),
            None,
        )
        assert not resp.error
        raw_grpc = _latest_record(vs, f.volume_id, f.needle_id)

        assert _canon_record(raw_plane) == _canon_record(raw_http)
        assert _canon_record(raw_http) == _canon_record(raw_grpc)
        assert len(raw_plane) == len(raw_http) == len(raw_grpc)
        assert ops.read(fid) == payload
    finally:
        ops.close()


def test_write_dead_sidecar_falls_back_to_http(write_cluster):
    """Sidecar down (crashed, old binary): the PUT rides HTTP with the
    plane probe memoized — uploads keep succeeding, bytes unchanged."""
    from seaweedfs_tpu.client.operations import Operations

    master, vols = write_cluster
    for vs in vols:
        vs.net_plane.stop()
    ops = Operations(f"localhost:{master.port}")
    try:
        data = b"no-sidecar-today" * 500
        fid = ops.upload(data, name="f.bin")
        assert ops.read(fid) == data
        assert all(v.net_plane.write_requests == 0 for v in vols)
        # second upload: memoized no-plane peer, still fine
        fid2 = ops.upload(data)
        assert ops.read(fid2) == data
    finally:
        ops.close()


def test_write_chaos_routes_to_http_unless_write_namespace(write_cluster):
    """Armed non-write chaos routes PUTs to the HTTP path (where the
    storage fault points live); armed write-path chaos stays on the
    plane so the crash matrix exercises the native path."""
    from seaweedfs_tpu.client.operations import Operations

    master, vols = write_cluster
    ops = Operations(f"localhost:{master.port}")
    data = b"routed-write" * 300
    try:
        with faults.injected(
            "storage.disk.read_at", faults.latency(0.0),
            when=faults.always(),
        ):
            fid = ops.upload(data)
        assert sum(v.net_plane.write_requests for v in vols) == 0
        assert ops.read(fid) == data
        with faults.injected(
            "ec.net.write.before_pwrite", faults.latency(0.0),
            when=faults.always(),
        ):
            fid2 = ops.upload(data)
        assert sum(v.net_plane.write_requests for v in vols) == 1
        assert ops.read(fid2) == data
    finally:
        ops.close()


def test_replica_fanout_rides_plane_bit_identical(write_cluster):
    """replication=001: the primary fans out to its replica over the
    native plane (pooled connection, replicate=False leg) and both
    copies are byte-identical on disk."""
    import requests as _requests

    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.storage.file_id import FileId

    master, vols = write_cluster
    ops = Operations(f"localhost:{master.port}")
    payload = np.random.default_rng(13).integers(
        0, 256, 90_001, dtype=np.uint8
    ).tobytes()
    try:
        fid = ops.upload(payload, name="rep.bin", replication="001")
        f = FileId.parse(fid)
        locs = ops.master.lookup(f.volume_id)
        assert len(locs) == 2, "001 => 2 copies"
        # client->primary leg + primary->replica leg, both on the plane
        assert sum(v.net_plane.write_requests for v in vols) == 2
        raws = [
            _latest_record(vs, f.volume_id, f.needle_id) for vs in vols
        ]
        assert _canon_record(raws[0]) == _canon_record(raws[1])
        for loc in locs:
            r = _requests.get(f"http://{loc.url}/{fid}")
            assert r.status_code == 200 and r.content == payload
    finally:
        ops.close()
