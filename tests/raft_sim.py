"""Deterministic in-process raft fault harness.

Reference model: test/multi_master/failover_test.go drives real
processes; this harness goes further the simulation-testing way — N
RaftNodes in one process wired through an injectable transport that can
drop, delay, duplicate, and partition RPCs under a SEEDED RNG, plus
crash (drop volatile state, keep the persisted journal) and restart any
node. Invariants are checked structurally (election safety, log
matching, applied-prefix consistency) rather than by sleeping and
hoping.
"""

from __future__ import annotations

import os
import random
import threading
import time

from seaweedfs_tpu.server import raft as R
from seaweedfs_tpu.server.raft import TransportError


class SimTransport:
    def __init__(self, net: "SimNet", src: str):
        self.net = net
        self.src = src

    def call(self, peer: str, method: str, request, timeout: float):
        return self.net.deliver(self.src, peer, method, request)


class SimNet:
    """Shared fault fabric. All knobs are live; the RNG is seeded so a
    failing schedule replays exactly."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        self.nodes: dict[str, R.RaftNode] = {}
        self.drop = 0.0  # per-message loss probability (each direction)
        self.dup = 0.0  # duplicate-delivery probability
        self.delay = (0.0, 0.0)  # uniform seconds before delivery
        self.cut: set[frozenset] = set()  # partitioned pairs
        self.down: set[str] = set()  # crashed nodes
        self.delivered = 0

    # ------------------------------------------------------------ faults

    def partition(self, *groups: list[str]) -> None:
        """Cut every link between nodes of different groups."""
        with self.lock:
            self.cut = {
                frozenset((a, b))
                for i, ga in enumerate(groups)
                for gb in groups[i + 1 :]
                for a in ga
                for b in gb
            }

    def heal(self) -> None:
        with self.lock:
            self.cut = set()

    def set_faults(self, drop=None, dup=None, delay=None) -> None:
        with self.lock:
            if drop is not None:
                self.drop = drop
            if dup is not None:
                self.dup = dup
            if delay is not None:
                self.delay = delay

    # ---------------------------------------------------------- delivery

    def deliver(self, src: str, dst: str, method: str, request):
        with self.lock:
            target = self.nodes.get(dst)
            unreachable = (
                target is None
                or src in self.down
                or dst in self.down
                or frozenset((src, dst)) in self.cut
            )
            drop_req = self.rng.random() < self.drop
            dup_req = self.rng.random() < self.dup
            drop_resp = self.rng.random() < self.drop
            delay = self.rng.uniform(*self.delay) if self.delay[1] else 0.0
        if unreachable:
            raise TransportError(f"{src}->{dst} unreachable")
        if drop_req:
            raise TransportError(f"{src}->{dst} {method} dropped")
        if delay:
            time.sleep(delay)
        resp = getattr(target, method)(request, None)
        if dup_req:  # network re-delivery: the handler runs again
            getattr(target, method)(request, None)
        with self.lock:
            self.delivered += 1
        if drop_resp:
            raise TransportError(f"{dst}->{src} {method} response lost")
        return resp


class Cluster:
    """N raft nodes over one SimNet with crash/restart support."""

    def __init__(self, n: int, base_dir: str, seed: int = 0, **node_kw):
        self.net = SimNet(seed)
        self.base_dir = base_dir
        self.ids = [f"n{i}:70{i:02d}" for i in range(n)]
        self.node_kw = dict(
            election_timeout=node_kw.pop("election_timeout", (0.15, 0.3)),
            heartbeat_interval=node_kw.pop("heartbeat_interval", 0.04),
            **node_kw,
        )
        self.applied: dict[str, list] = {i: [] for i in self.ids}
        # replicated KV state machine: survives crash via the raft
        # snapshot hooks, so a restarted node's STATE (not its replay
        # trace) is what convergence checks compare
        self.state: dict[str, dict] = {i: {} for i in self.ids}
        self.nodes: dict[str, R.RaftNode] = {}
        for nid in self.ids:
            self._spawn(nid)

    def _apply(self, nid: str, kind: str, value: int) -> int:
        self.applied[nid].append((kind, value))
        self.state[nid][f"k{value % 16}"] = value
        if kind == "op":
            # cumulative op set INSIDE the state machine: ops folded
            # into a snapshot never re-run through apply_fn after a
            # restart, so at-least-once must be checked against state,
            # not the volatile applied trace. Stored as a sorted LIST
            # (raft snapshots are json.dumps'd — a set would TypeError
            # inside the commit path once compaction triggers) and
            # REPLACED, never mutated, so snapshot_fn's shallow dict()
            # copy cannot alias a list we later append to.
            cur = self.state[nid].get("ops") or []
            self.state[nid]["ops"] = sorted(set(cur) | {value})
        return value

    def _spawn(self, nid: str) -> R.RaftNode:
        d = os.path.join(self.base_dir, nid.replace(":", "_"))
        os.makedirs(d, exist_ok=True)
        node = R.RaftNode(
            nid,
            [p for p in self.ids if p != nid],
            d,
            apply_fn=lambda kind, value, _n=nid: self._apply(_n, kind, value),
            snapshot_fn=lambda _n=nid: dict(self.state[_n]),
            restore_fn=lambda st, _n=nid: self.state.__setitem__(_n, dict(st)),
            transport_factory=lambda n: SimTransport(self.net, n.node_id),
            **self.node_kw,
        )
        self.nodes[nid] = node
        self.net.nodes[nid] = node
        node.start()
        return node

    # ------------------------------------------------------------- admin

    def crash(self, nid: str) -> None:
        """SIGKILL model: stop threads, drop the object, keep disk."""
        with self.net.lock:
            self.net.down.add(nid)
        node = self.nodes.pop(nid)
        self.net.nodes.pop(nid, None)
        node.stop()

    def restart(self, nid: str) -> R.RaftNode:
        # volatile trace resets; the KV state rebuilds from snapshot +
        # journal replay on boot
        self.applied[nid] = []
        self.state[nid] = {}
        node = self._spawn(nid)
        with self.net.lock:
            self.net.down.discard(nid)
        return node

    def stop(self) -> None:
        for node in list(self.nodes.values()):
            node.stop()

    # --------------------------------------------------------- inspection

    def leaders(self) -> list[R.RaftNode]:
        return [n for n in self.nodes.values() if n.role == R.LEADER]

    def wait_leader(self, timeout: float = 10.0) -> R.RaftNode:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            up = [n for n in self.nodes.values()]
            ls = [n for n in up if n.role == R.LEADER]
            # a REAL leader must be able to commit: its term must be
            # the max visible term (a deposed leader in a minority
            # partition can linger at a stale term)
            if ls:
                maxterm = max(n.current_term for n in up)
                live = [l for l in ls if l.current_term == maxterm]
                if len(live) == 1:
                    return live[0]
            time.sleep(0.02)
        raise TimeoutError("no settled leader")

    # --------------------------------------------------------- invariants

    def check_election_safety(self) -> None:
        """At most one leader per term — (role, term) snapshotted under
        each node's lock so a step-down between attribute reads cannot
        mis-attribute a leader to a stale term."""
        by_term: dict[int, list[str]] = {}
        for n in self.nodes.values():
            with n._lock:
                role, term = n.role, n.current_term
            if role == R.LEADER:
                by_term.setdefault(term, []).append(n.node_id)
        for term, who in by_term.items():
            assert len(who) == 1, f"two leaders in term {term}: {who}"

    def check_log_matching(self) -> None:
        """Committed prefixes agree pairwise (Raft Log Matching): for
        every pair, entries up to min(commit) are identical."""
        nodes = list(self.nodes.values())
        for a in nodes:
            for b in nodes:
                if a.node_id >= b.node_id:
                    continue
                upto = min(a.commit_index, b.commit_index)
                for idx in range(
                    max(a.snap_index, b.snap_index) + 1, upto + 1
                ):
                    ea, eb = a._entry_at(idx), b._entry_at(idx)
                    assert (ea.term, ea.kind, ea.value) == (
                        eb.term, eb.kind, eb.value,
                    ), (
                        f"log mismatch at {idx}: "
                        f"{a.node_id}={ea} {b.node_id}={eb}"
                    )

    def check_applied_prefix(self, expect: list | None = None) -> None:
        """Every node's applied sequence is a prefix of the longest one
        (no divergence, no reordering, no duplication)."""
        seqs = {
            nid: [v for k, v in ops if k == "op"]
            for nid, ops in self.applied.items()
            if nid in self.nodes
        }
        longest = max(seqs.values(), key=len, default=[])
        for nid, seq in seqs.items():
            assert seq == longest[: len(seq)], (
                f"{nid} applied {seq[:20]}... not a prefix of "
                f"{longest[:20]}..."
            )
        if expect is not None:
            assert longest == expect, (longest[:20], expect[:20])
