"""Peer-fetch rebuild core (ec/peer_rebuild.py) under an injected byte
transport: verify-and-exclude across the wire, retry/exclusion/replan,
clean refusal with no partial publish, and idempotent re-runs across
crash windows. The server/gRPC layer on top is covered by
tests/test_ec_cluster_chaos.py.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.ec import (
    CpuBackend,
    ECContext,
    ECError,
    PeerCorruptError,
    PeerFetchTransient,
    rebuild_from_peers,
)
from seaweedfs_tpu.ec.bitrot import BitrotProtection, ShardChecksumBuilder
from seaweedfs_tpu.ec.peer_rebuild import staging_dir
from seaweedfs_tpu.utils.retry import RetryPolicy

CTX = ECContext(4, 2)
BLOCK = 4096
SHARD_SIZE = 3 * BLOCK + 57  # partial final granule on purpose

# zero-sleep policy: retry schedules run in no wall time
FAST = RetryPolicy(max_attempts=3, base_delay=0.0, max_delay=0.0, jitter=0.0)


def synth(tmp_path, local=(0, 1), seed=0):
    """RS-consistent shard set + v1 sidecar; only `local` shard files
    exist on disk. Returns (base, shard_bytes: sid -> bytes)."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (CTX.data_shards, SHARD_SIZE), dtype=np.uint8)
    parity = CpuBackend(CTX).encode(data)
    shards = np.concatenate([data, parity], axis=0)
    blobs = {i: shards[i].tobytes() for i in range(CTX.total)}
    builders = [ShardChecksumBuilder(BLOCK) for _ in range(CTX.total)]
    for i in range(CTX.total):
        builders[i].write(blobs[i])
    base = str(tmp_path / "1")
    BitrotProtection.from_builders(CTX, builders, generation=3).save(
        base + ".ecsum"
    )
    for i in local:
        with open(base + CTX.to_ext(i), "wb") as f:
            f.write(blobs[i])
    return base, blobs


def serving_fetch(blobs, log=None):
    def fetch(peer, sid, off, size):
        if log is not None:
            log.append((peer, sid, off, size))
        return blobs[sid][off : off + size]

    return fetch


ALL_PEERS = {sid: ["peerB"] for sid in range(CTX.total)}


def test_peer_fetch_rebuild_bit_identical(tmp_path):
    base, blobs = synth(tmp_path, local=(0, 1))
    calls = []
    rep = rebuild_from_peers(
        base,
        {2: ["peerB"], 3: ["peerB"], 4: ["peerB"]},
        serving_fetch(blobs, calls),
        targets=[5],
        backend=CpuBackend(CTX),
        policy=FAST,
    )
    assert rep.rebuilt == [5]
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]
    # fetched exactly k - local = 2 shards, lowest candidate ids first
    assert sorted(rep.fetched) == [2, 3]
    assert rep.local_sources == [0, 1] and not rep.excluded_peers
    assert not os.path.exists(staging_dir(base)), "staging not cleaned"
    # sources were never published locally (no duplicate minting)
    for sid in (2, 3, 4):
        assert not os.path.exists(base + CTX.to_ext(sid))


def test_enough_local_sources_fetches_nothing(tmp_path):
    base, blobs = synth(tmp_path, local=(0, 1, 2, 3))
    calls = []
    rep = rebuild_from_peers(
        base, ALL_PEERS, serving_fetch(blobs, calls),
        targets=[4], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.rebuilt == [4] and not rep.fetched and not calls
    assert open(base + CTX.to_ext(4), "rb").read() == blobs[4]


def test_transient_failure_retries_then_succeeds(tmp_path):
    base, blobs = synth(tmp_path, local=(0, 1))
    state = {"failed": 0}

    def flaky(peer, sid, off, size):
        # first attempt of every (sid, off) dies mid-stream
        if (sid, off) not in state:
            state[(sid, off)] = True
            state["failed"] += 1
            raise PeerFetchTransient("connection reset mid-stream")
        return blobs[sid][off : off + size]

    rep = rebuild_from_peers(
        base, {2: ["peerB"], 3: ["peerB"]}, flaky,
        targets=[5], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.rebuilt == [5] and state["failed"] >= 2
    assert not rep.excluded_peers, "transient failures must not exclude"
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_retry_exhaustion_on_every_sibling_refuses_clean(tmp_path):
    base, blobs = synth(tmp_path, local=(0, 1))

    def dead(peer, sid, off, size):
        raise PeerFetchTransient("peer down")

    with pytest.raises(ECError, match="refusing"):
        rebuild_from_peers(
            base, ALL_PEERS, dead,
            targets=[5], backend=CpuBackend(CTX), policy=FAST,
        )
    # clean refusal: nothing published, staging wiped, locals untouched
    assert not os.path.exists(base + CTX.to_ext(5))
    assert not os.path.exists(staging_dir(base))
    for sid in (0, 1):
        assert open(base + CTX.to_ext(sid), "rb").read() == blobs[sid]


def test_corrupt_peer_excluded_and_replanned(tmp_path):
    """A holder serving rot for ONE shard is excluded wholesale; the
    plan re-routes that shard to another holder of the same sid."""
    base, blobs = synth(tmp_path, local=(0, 1))

    def fetch(peer, sid, off, size):
        chunk = blobs[sid][off : off + size]
        if peer == "rotten" and sid == 2:
            return bytes([chunk[0] ^ 0xFF]) + chunk[1:]  # persistent rot
        return chunk

    rep = rebuild_from_peers(
        base,
        {2: ["rotten", "clean"], 3: ["clean"]},
        fetch,
        targets=[5],
        backend=CpuBackend(CTX),
        policy=FAST,
    )
    assert rep.rebuilt == [5] and rep.excluded_peers == ["rotten"]
    assert rep.fetched == {2: "clean", 3: "clean"}
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_corrupt_exclusion_below_k_refuses_no_partial_publish(tmp_path):
    """Every reachable holder serves rot: exclusion leaves < k sources
    and the rebuild refuses cleanly instead of publishing anything."""
    base, blobs = synth(tmp_path, local=(0, 1))

    def rotten(peer, sid, off, size):
        chunk = blobs[sid][off : off + size]
        return bytes([chunk[0] ^ 0x01]) + chunk[1:]

    with pytest.raises(ECError, match="refusing"):
        rebuild_from_peers(
            base, ALL_PEERS, rotten,
            targets=[5], backend=CpuBackend(CTX), policy=FAST,
        )
    assert not os.path.exists(base + CTX.to_ext(5))
    assert not os.path.exists(staging_dir(base))


def test_transient_wire_corruption_rereads_without_exclusion(tmp_path):
    """One corrupt read that verifies clean on the immediate re-read is
    wire noise, not a rotten peer: the holder stays in the plan."""
    base, blobs = synth(tmp_path, local=(0, 1))
    state = {"flipped": False}

    def once_flipped(peer, sid, off, size):
        chunk = blobs[sid][off : off + size]
        if not state["flipped"]:
            state["flipped"] = True
            return bytes([chunk[0] ^ 0x80]) + chunk[1:]
        return chunk

    rep = rebuild_from_peers(
        base, {2: ["peerB"], 3: ["peerB"]}, once_flipped,
        targets=[5], backend=CpuBackend(CTX), policy=FAST,
    )
    assert state["flipped"] and rep.rebuilt == [5]
    assert not rep.excluded_peers
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_reread_fetches_only_the_bad_granule(tmp_path):
    """Wire corruption in one granule re-reads ONLY that granule's byte
    range — the already-verified rest of the chunk comes from the first
    buffer (a whole-chunk redo both wastes wire traffic and used to risk
    splicing the redo's own unchecked corruption into staging)."""
    base, blobs = synth(tmp_path, local=(0, 1))
    state = {"calls": []}

    def flip_at(chunk, pos):
        return chunk[:pos] + bytes([chunk[pos] ^ 0x80]) + chunk[pos + 1 :]

    def shifty(peer, sid, off, size):
        chunk = blobs[sid][off : off + size]
        if sid == 2:
            state["calls"].append((off, size))
            if len(state["calls"]) == 1:
                return flip_at(chunk, BLOCK + 7)  # granule 1 bad
        return chunk

    rep = rebuild_from_peers(
        base, {2: ["peerB"], 3: ["peerB"], 4: ["peerB"]}, shifty,
        targets=[5], backend=CpuBackend(CTX), policy=FAST,
    )
    assert len(state["calls"]) == 2, "granule mismatch should force a re-read"
    redo_off, redo_size = state["calls"][1]
    assert (redo_off, redo_size) == (BLOCK, BLOCK), (
        "re-read must cover exactly the failed granule, not the chunk"
    )
    assert rep.rebuilt == [5] and not rep.excluded_peers
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]


def test_corrupt_local_source_excluded_and_replaced(tmp_path):
    """A present-but-corrupt local shard is never fed to Reed-Solomon
    (another peer source covers it) AND is regenerated in place — the
    verify-and-exclude contract, peer edition."""
    base, blobs = synth(tmp_path, local=(0, 1, 2))
    with open(base + CTX.to_ext(2), "r+b") as f:
        f.seek(100)
        f.write(b"\xff\xff")
    rep = rebuild_from_peers(
        base, {3: ["peerB"], 4: ["peerB"], 5: ["peerB"]},
        serving_fetch(blobs),
        targets=[], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.corrupt_local == [2]
    assert 2 in rep.rebuilt
    assert open(base + CTX.to_ext(2), "rb").read() == blobs[2]


def test_refuses_without_sidecar(tmp_path):
    base, blobs = synth(tmp_path, local=(0, 1))
    os.unlink(base + ".ecsum")
    with pytest.raises(ECError, match="ecsum"):
        rebuild_from_peers(
            base, ALL_PEERS, serving_fetch(blobs),
            targets=[5], backend=CpuBackend(CTX), policy=FAST,
        )


def test_crash_between_publishes_rerun_converges(tmp_path):
    """Crash after the first target publish: the re-run regenerates the
    remaining targets idempotently; already-published ones verify good
    and are untouched."""
    base, blobs = synth(tmp_path, local=(0, 1))
    with faults.injected(
        "ec.peer_rebuild.after_publish", faults.crash(), when=faults.nth_call(1)
    ):
        with pytest.raises(faults.InjectedCrash):
            rebuild_from_peers(
                base, ALL_PEERS, serving_fetch(blobs),
                targets=[4, 5], backend=CpuBackend(CTX), policy=FAST,
            )
    published = [
        sid for sid in (4, 5) if os.path.exists(base + CTX.to_ext(sid))
    ]
    assert len(published) == 1, "crash fired after exactly one publish"
    # stale staging from the crash is swept by the re-run
    rep = rebuild_from_peers(
        base, ALL_PEERS, serving_fetch(blobs),
        targets=[4, 5], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.rebuilt == [sid for sid in (4, 5) if sid not in published]
    for sid in (4, 5):
        assert open(base + CTX.to_ext(sid), "rb").read() == blobs[sid]
    assert not os.path.exists(staging_dir(base))


def test_stale_staging_leftovers_are_swept(tmp_path):
    base, blobs = synth(tmp_path, local=(0, 1))
    sdir = staging_dir(base)
    os.makedirs(sdir)
    with open(os.path.join(sdir, "1.ec05.fetching"), "wb") as f:
        f.write(b"junk from a crashed run")
    rep = rebuild_from_peers(
        base, ALL_PEERS, serving_fetch(blobs),
        targets=[5], backend=CpuBackend(CTX), policy=FAST,
    )
    assert rep.rebuilt == [5]
    assert open(base + CTX.to_ext(5), "rb").read() == blobs[5]
    assert not os.path.exists(sdir)


def test_peer_corrupt_error_carries_context():
    e = PeerCorruptError("p1", 7, 3)
    assert e.peer == "p1" and e.shard == 7 and "granule 3" in str(e)
