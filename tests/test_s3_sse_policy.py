"""S3 server-side encryption (SSE-C / SSE-S3), bucket policies,
POST-policy uploads, and canned ACLs.

Reference surfaces: weed/s3api/s3_sse_c.go, weed/kms/,
weed/s3api/s3api_bucket_policy_handlers.go,
weed/s3api/s3api_object_handlers_postpolicy.go.
"""

import base64
import datetime
import hashlib
import hmac
import json
import time

import pytest
import requests

from conftest import allocate_port as free_port
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.s3 import Identity, IdentityStore, S3Server
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from test_s3 import sign_request

# the gateway imports without `cryptography` (sse.py gates it); the SSE
# ciphers themselves still need it — skip only those tests in slim
# containers instead of failing the whole module's policy/ACL coverage
try:
    import cryptography  # noqa: F401

    _HAS_CRYPTO = True
except ImportError:
    _HAS_CRYPTO = False

needs_crypto = pytest.mark.skipif(
    not _HAS_CRYPTO,
    reason="SSE ciphers require the optional 'cryptography' package",
)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3ssevol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    yield mport
    vs.stop()
    master.stop()


@pytest.fixture
def s3(cluster):
    """Open-mode gateway (no identities)."""
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    srv = S3Server(filer, ip="localhost", port=free_port())
    srv.start()
    yield f"http://localhost:{srv.port}", srv
    srv.stop()
    filer.close()


@pytest.fixture
def s3_two_users(cluster):
    """Signed gateway with two identities: alice (admin) and bob
    (read-only coarse actions)."""
    filer = Filer(MemoryStore(), master=f"localhost:{cluster}", chunk_size=64 * 1024)
    idents = IdentityStore()
    idents.add(Identity("alice", "AKALICE", "alicesecret"))
    idents.add(Identity("bob", "AKBOB", "bobsecret", actions=("Read", "List")))
    srv = S3Server(filer, ip="localhost", port=free_port(), identities=idents)
    srv.start()
    yield f"http://localhost:{srv.port}", srv
    srv.stop()
    filer.close()


def ssec_headers(key: bytes, prefix="x-amz-server-side-encryption-customer-"):
    return {
        prefix + "algorithm": "AES256",
        prefix + "key": base64.b64encode(key).decode(),
        prefix + "key-MD5": base64.b64encode(hashlib.md5(key).digest()).decode(),
    }


# ------------------------------------------------------------------ SSE-C


@needs_crypto
def test_ssec_roundtrip_and_key_enforcement(s3):
    url, srv = s3
    requests.put(f"{url}/sec")
    key = b"K" * 31 + b"1"
    data = b"customer-encrypted payload " * 1000

    r = requests.put(f"{url}/sec/obj", data=data, headers=ssec_headers(key))
    assert r.status_code == 200
    assert (
        r.headers["x-amz-server-side-encryption-customer-algorithm"] == "AES256"
    )

    # GET without the key: fail closed
    assert requests.get(f"{url}/sec/obj").status_code == 400
    # GET with a wrong key: denied
    wrong = b"W" * 32
    assert (
        requests.get(f"{url}/sec/obj", headers=ssec_headers(wrong)).status_code
        == 403
    )
    # GET with the right key
    r = requests.get(f"{url}/sec/obj", headers=ssec_headers(key))
    assert r.status_code == 200 and r.content == data
    # HEAD advertises the encryption
    r = requests.head(f"{url}/sec/obj", headers=ssec_headers(key))
    assert (
        r.headers["x-amz-server-side-encryption-customer-algorithm"] == "AES256"
    )

    # ciphertext at rest differs from plaintext
    entry = srv.filer.find_entry("/buckets/sec/obj")
    assert srv.filer.read_entry(entry) != data

    # range read decrypts mid-stream (unaligned offsets)
    r = requests.get(
        f"{url}/sec/obj",
        headers={**ssec_headers(key), "Range": "bytes=1003-2010"},
    )
    assert r.status_code == 206 and r.content == data[1003:2011]


def test_ssec_bad_key_md5_rejected(s3):
    url, _ = s3
    requests.put(f"{url}/sec2")
    h = ssec_headers(b"K" * 32)
    h["x-amz-server-side-encryption-customer-key-MD5"] = base64.b64encode(
        hashlib.md5(b"other").digest()
    ).decode()
    r = requests.put(f"{url}/sec2/obj", data=b"x", headers=h)
    assert r.status_code == 400


# ------------------------------------------------------------------ SSE-S3


@needs_crypto
def test_sse_s3_roundtrip(s3):
    url, srv = s3
    requests.put(f"{url}/managed")
    data = b"keyring-encrypted " * 500
    r = requests.put(
        f"{url}/managed/obj",
        data=data,
        headers={"x-amz-server-side-encryption": "AES256"},
    )
    assert r.status_code == 200
    assert r.headers["x-amz-server-side-encryption"] == "AES256"
    # transparent decrypt on GET, header advertised
    r = requests.get(f"{url}/managed/obj")
    assert r.content == data
    assert r.headers["x-amz-server-side-encryption"] == "AES256"
    # at rest: ciphertext
    entry = srv.filer.find_entry("/buckets/managed/obj")
    assert srv.filer.read_entry(entry) != data
    # range GET
    r = requests.get(f"{url}/managed/obj", headers={"Range": "bytes=7-99"})
    assert r.status_code == 206 and r.content == data[7:100]


@needs_crypto
def test_bucket_default_encryption(s3):
    url, srv = s3
    requests.put(f"{url}/dflt")
    conf = (
        "<ServerSideEncryptionConfiguration><Rule>"
        "<ApplyServerSideEncryptionByDefault><SSEAlgorithm>AES256"
        "</SSEAlgorithm></ApplyServerSideEncryptionByDefault>"
        "</Rule></ServerSideEncryptionConfiguration>"
    )
    assert requests.put(f"{url}/dflt?encryption", data=conf).status_code == 200
    r = requests.get(f"{url}/dflt?encryption")
    assert r.status_code == 200 and "AES256" in r.text
    # plain PUT now encrypts at rest
    data = b"default-encrypted"
    requests.put(f"{url}/dflt/obj", data=data)
    entry = srv.filer.find_entry("/buckets/dflt/obj")
    assert srv.filer.read_entry(entry) != data
    assert requests.get(f"{url}/dflt/obj").content == data
    # delete the config: new PUTs are plaintext again
    assert requests.delete(f"{url}/dflt?encryption").status_code == 204
    assert requests.get(f"{url}/dflt?encryption").status_code == 404
    requests.put(f"{url}/dflt/obj2", data=data)
    e2 = srv.filer.find_entry("/buckets/dflt/obj2")
    assert srv.filer.read_entry(e2) == data


@needs_crypto
def test_sse_copy_reencrypts(s3):
    url, srv = s3
    requests.put(f"{url}/cpy")
    key = b"C" * 32
    data = b"copy me securely" * 100
    requests.put(f"{url}/cpy/src", data=data, headers=ssec_headers(key))
    # copy SSE-C source -> SSE-S3 destination
    r = requests.put(
        f"{url}/cpy/dst",
        headers={
            "x-amz-copy-source": "/cpy/src",
            **ssec_headers(
                key, prefix="x-amz-copy-source-server-side-encryption-customer-"
            ),
            "x-amz-server-side-encryption": "AES256",
        },
    )
    assert r.status_code == 200
    r = requests.get(f"{url}/cpy/dst")
    assert r.content == data
    assert r.headers["x-amz-server-side-encryption"] == "AES256"


def _multipart_upload(url, bucket, key, parts, headers=None):
    """Run a full multipart upload; returns the complete response."""
    import xml.etree.ElementTree as _ET

    h = headers or {}
    r = requests.post(f"{url}/{bucket}/{key}?uploads", headers=h)
    assert r.status_code == 200, r.text
    root = _ET.fromstring(r.text)
    ns = root.tag[: root.tag.index("}") + 1] if root.tag.startswith("{") else ""
    upload_id = root.findtext(f"{ns}UploadId")
    etags = []
    for i, data in enumerate(parts, start=1):
        pr = requests.put(
            f"{url}/{bucket}/{key}?partNumber={i}&uploadId={upload_id}",
            data=data,
            headers=h,
        )
        assert pr.status_code == 200, pr.text
        etags.append(pr.headers["ETag"])
    body = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
        for i, e in enumerate(etags, start=1)
    ) + "</CompleteMultipartUpload>"
    return requests.post(
        f"{url}/{bucket}/{key}?uploadId={upload_id}", data=body
    )


@needs_crypto
def test_sse_s3_multipart_roundtrip(s3):
    """Multipart + SSE-S3: parts are independent CTR streams under one
    envelope key; ranged reads seek across part boundaries."""
    url, srv = s3
    requests.put(f"{url}/mp")
    # odd part sizes: part boundaries NOT 16-byte aligned
    parts = [b"A" * 100_003, b"B" * 70_001, b"C" * 33]
    plain = b"".join(parts)
    r = _multipart_upload(
        url, "mp", "big.enc", parts,
        headers={"x-amz-server-side-encryption": "AES256"},
    )
    assert r.status_code == 200, r.text

    # transparent full read + SSE header advertised
    g = requests.get(f"{url}/mp/big.enc")
    assert g.headers.get("x-amz-server-side-encryption") == "AES256"
    assert g.content == plain
    # ciphertext at rest differs
    entry = srv.filer.find_entry("/buckets/mp/big.enc")
    assert srv.filer.read_entry(entry) != plain
    # ranges: inside part 1, spanning parts 1-2, tail crossing 2-3
    for lo, hi in [(5, 900), (100_000, 100_050), (169_990, 170_036)]:
        rr = requests.get(
            f"{url}/mp/big.enc", headers={"Range": f"bytes={lo}-{hi}"}
        )
        assert rr.status_code == 206
        assert rr.content == plain[lo : hi + 1], (lo, hi)


@needs_crypto
def test_ssec_multipart_roundtrip(s3):
    """Multipart + SSE-C: the customer key rides every part request and
    every read; a wrong key on a part is rejected."""
    url, _ = s3
    requests.put(f"{url}/mpc")
    key = b"M" * 32
    parts = [b"x" * 50_001, b"y" * 24_007]
    plain = b"".join(parts)
    r = _multipart_upload(url, "mpc", "cust.enc", parts, headers=ssec_headers(key))
    assert r.status_code == 200, r.text
    # read requires the key; wrong key denied
    assert requests.get(f"{url}/mpc/cust.enc").status_code == 400
    assert (
        requests.get(
            f"{url}/mpc/cust.enc", headers=ssec_headers(b"W" * 32)
        ).status_code
        == 403
    )
    g = requests.get(f"{url}/mpc/cust.enc", headers=ssec_headers(key))
    assert g.content == plain
    rr = requests.get(
        f"{url}/mpc/cust.enc",
        headers={**ssec_headers(key), "Range": "bytes=49999-50010"},
    )
    assert rr.content == plain[49999:50011]

    # a part PUT with the WRONG key is rejected mid-upload
    import xml.etree.ElementTree as _ET

    r = requests.post(f"{url}/mpc/o2?uploads", headers=ssec_headers(key))
    root = _ET.fromstring(r.text)
    ns = root.tag[: root.tag.index("}") + 1]
    uid = root.findtext(f"{ns}UploadId")
    bad = requests.put(
        f"{url}/mpc/o2?partNumber=1&uploadId={uid}",
        data=b"z",
        headers=ssec_headers(b"W" * 32),
    )
    assert bad.status_code == 403
    nokey = requests.put(
        f"{url}/mpc/o2?partNumber=1&uploadId={uid}", data=b"z"
    )
    assert nokey.status_code == 400


# ----------------------------------------------------------- bucket policy


def _policy(bucket, effect="Allow", principal="*", actions=None, condition=None):
    stmt = {
        "Effect": effect,
        "Principal": principal,
        "Action": actions or ["s3:GetObject"],
        "Resource": [f"arn:aws:s3:::{bucket}/*"],
    }
    if condition:
        stmt["Condition"] = condition
    return json.dumps({"Version": "2012-10-17", "Statement": [stmt]})


def test_bucket_policy_crud_and_status(s3):
    url, _ = s3
    requests.put(f"{url}/polb")
    assert requests.get(f"{url}/polb?policy").status_code == 404
    assert (
        requests.put(f"{url}/polb?policy", data=_policy("polb")).status_code
        == 204
    )
    r = requests.get(f"{url}/polb?policy")
    assert r.status_code == 200
    assert json.loads(r.text)["Statement"][0]["Effect"] == "Allow"
    r = requests.get(f"{url}/polb?policyStatus")
    assert r.status_code == 200 and "<IsPublic>true</IsPublic>" in r.text
    # policy for another bucket's ARN is rejected
    assert (
        requests.put(f"{url}/polb?policy", data=_policy("other")).status_code
        == 400
    )
    assert requests.delete(f"{url}/polb?policy").status_code == 204
    assert requests.get(f"{url}/polb?policy").status_code == 404


def test_bucket_policy_grants_anonymous_read(s3_two_users):
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/pub", "AKALICE", "alicesecret")
    assert requests.put(f"{url}/pub", headers=h).status_code == 200
    body = b"public object"
    h = sign_request("PUT", f"{url}/pub/o.txt", "AKALICE", "alicesecret", body)
    assert requests.put(f"{url}/pub/o.txt", data=body, headers=h).status_code == 200

    # anonymous read denied before the policy
    assert requests.get(f"{url}/pub/o.txt").status_code == 403
    pol = _policy("pub")
    h = sign_request(
        "PUT", f"{url}/pub?policy", "AKALICE", "alicesecret", pol.encode()
    )
    assert (
        requests.put(f"{url}/pub?policy", data=pol, headers=h).status_code
        == 204
    )
    # now anonymous read succeeds; anonymous write still denied
    assert requests.get(f"{url}/pub/o.txt").content == body
    assert requests.put(f"{url}/pub/x", data=b"nope").status_code == 403


def test_bucket_policy_denies_cross_identity(s3_two_users):
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/denyb", "AKALICE", "alicesecret")
    requests.put(f"{url}/denyb", headers=h)
    body = b"secret"
    h = sign_request("PUT", f"{url}/denyb/k", "AKALICE", "alicesecret", body)
    requests.put(f"{url}/denyb/k", data=body, headers=h)

    # bob (Read actions) can read before the deny
    h = sign_request("GET", f"{url}/denyb/k", "AKBOB", "bobsecret")
    assert requests.get(f"{url}/denyb/k", headers=h).status_code == 200

    pol = _policy(
        "denyb",
        effect="Deny",
        principal={"AWS": ["arn:aws:iam:::user/bob"]},
        actions=["s3:GetObject"],
    )
    h = sign_request(
        "PUT", f"{url}/denyb?policy", "AKALICE", "alicesecret", pol.encode()
    )
    assert (
        requests.put(f"{url}/denyb?policy", data=pol, headers=h).status_code
        == 204
    )
    # explicit bucket-policy Deny overrides bob's identity permissions
    h = sign_request("GET", f"{url}/denyb/k", "AKBOB", "bobsecret")
    assert requests.get(f"{url}/denyb/k", headers=h).status_code == 403
    # alice is unaffected
    h = sign_request("GET", f"{url}/denyb/k", "AKALICE", "alicesecret")
    assert requests.get(f"{url}/denyb/k", headers=h).status_code == 200


# ------------------------------------------------------------- POST policy


def _post_form(url, bucket, key, data, access_key, secret, conditions=None,
               expire_s=300, extra_fields=None, region="us-east-1",
               cover_extras=True):
    now = datetime.datetime.now(datetime.timezone.utc)
    date = now.strftime("%Y%m%d")
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    cred = f"{access_key}/{date}/{region}/s3/aws4_request"
    exp = (now + datetime.timedelta(seconds=expire_s)).strftime(
        "%Y-%m-%dT%H:%M:%S.000Z"
    )
    # every submitted form field must be covered by a condition (the
    # server enforces this); `conditions` adds EXTRA constraints and
    # `extra_fields` are auto-covered with eq conditions
    base_conditions = [
        {"bucket": bucket},
        ["starts-with", "$key", ""],
        {"x-amz-credential": cred},
        {"x-amz-algorithm": "AWS4-HMAC-SHA256"},
        {"x-amz-date": amz_date},
    ] + ([{k: v} for k, v in (extra_fields or {}).items()] if cover_extras else [])
    policy = {
        "expiration": exp,
        "conditions": base_conditions + (conditions or []),
    }
    policy_b64 = base64.b64encode(json.dumps(policy).encode()).decode()

    def h(k, msg):
        return hmac.new(k, msg.encode(), hashlib.sha256).digest()

    sk = h(h(h(h(("AWS4" + secret).encode(), date), region), "s3"), "aws4_request")
    sig = hmac.new(sk, policy_b64.encode(), hashlib.sha256).hexdigest()
    fields = {
        "key": key,
        "policy": policy_b64,
        "x-amz-algorithm": "AWS4-HMAC-SHA256",
        "x-amz-credential": cred,
        "x-amz-date": amz_date,
        "x-amz-signature": sig,
        **(extra_fields or {}),
    }
    return requests.post(
        url + "/" + bucket, data=fields, files={"file": ("up.bin", data)}
    )


def test_post_policy_upload(s3_two_users):
    url, srv = s3_two_users
    h = sign_request("PUT", f"{url}/forms", "AKALICE", "alicesecret")
    requests.put(f"{url}/forms", headers=h)

    data = b"browser upload bytes"
    r = _post_form(url, "forms", "up/${filename}", data, "AKALICE", "alicesecret")
    assert r.status_code == 204, r.text
    h = sign_request("GET", f"{url}/forms/up/up.bin", "AKALICE", "alicesecret")
    assert requests.get(f"{url}/forms/up/up.bin", headers=h).content == data

    # bad signature
    r = _post_form(url, "forms", "k2", data, "AKALICE", "wrongsecret")
    assert r.status_code == 403
    # expired policy
    r = _post_form(
        url, "forms", "k3", data, "AKALICE", "alicesecret", expire_s=-10
    )
    assert r.status_code == 403
    # content-length-range violation
    r = _post_form(
        url,
        "forms",
        "k4",
        data,
        "AKALICE",
        "alicesecret",
        conditions=[["content-length-range", 1, 4]],
    )
    assert r.status_code == 400
    # success_action_status 201 returns the XML body
    r = _post_form(
        url,
        "forms",
        "k5",
        data,
        "AKALICE",
        "alicesecret",
        extra_fields={"success_action_status": "201"},
    )
    assert r.status_code == 201 and "<Key>k5</Key>" in r.text


# -------------------------------------------------------------------- ACLs


def test_canned_acls_public_read(s3_two_users):
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/aclb", "AKALICE", "alicesecret")
    requests.put(f"{url}/aclb", headers=h)
    body = b"acl object"
    h = sign_request("PUT", f"{url}/aclb/k", "AKALICE", "alicesecret", body)
    requests.put(
        f"{url}/aclb/k",
        data=body,
        headers={**h, "x-amz-acl": "public-read"},
    )
    # anonymous GET allowed by the object's canned ACL
    assert requests.get(f"{url}/aclb/k").content == body
    # GET ?acl renders the grants
    h = sign_request("GET", f"{url}/aclb/k?acl", "AKALICE", "alicesecret")
    r = requests.get(f"{url}/aclb/k?acl", headers=h)
    assert r.status_code == 200 and "AllUsers" in r.text

    # bucket-level public-read-write allows anonymous PUT
    h = sign_request("PUT", f"{url}/aclb?acl", "AKALICE", "alicesecret")
    assert (
        requests.put(
            f"{url}/aclb?acl",
            headers={**h, "x-amz-acl": "public-read-write"},
        ).status_code
        == 200
    )
    assert requests.put(f"{url}/aclb/anon", data=b"w").status_code == 200
    h = sign_request("GET", f"{url}/aclb?acl", "AKALICE", "alicesecret")
    assert "AllUsers" in requests.get(f"{url}/aclb?acl", headers=h).text


# ----------------------------------------------- review-finding regressions


def test_acl_never_grants_anonymous_control_plane(s3_two_users):
    """public-read-write grants data-plane only: anonymous bucket
    delete / policy write / acl write must still be denied."""
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/openb", "AKALICE", "alicesecret")
    requests.put(f"{url}/openb", headers=h)
    h = sign_request("PUT", f"{url}/openb?acl", "AKALICE", "alicesecret")
    requests.put(
        f"{url}/openb?acl", headers={**h, "x-amz-acl": "public-read-write"}
    )
    # data plane open
    assert requests.put(f"{url}/openb/k", data=b"x").status_code == 200
    assert requests.get(f"{url}/openb/k").content == b"x"
    assert requests.delete(f"{url}/openb/k").status_code in (200, 204)
    # control plane closed
    assert requests.delete(f"{url}/openb").status_code == 403
    assert (
        requests.put(f"{url}/openb?policy", data=_policy("openb")).status_code
        == 403
    )
    assert requests.put(
        f"{url}/openb?acl", headers={"x-amz-acl": "private"}
    ).status_code == 403
    assert requests.get(f"{url}/openb?policy").status_code == 403


def test_identity_deny_overrides_bucket_allow(s3_two_users):
    """Explicit identity-policy Deny wins over a bucket-policy Allow."""
    url, srv = s3_two_users
    h = sign_request("PUT", f"{url}/ovr", "AKALICE", "alicesecret")
    requests.put(f"{url}/ovr", headers=h)
    pol = _policy("ovr", actions=["s3:*"])
    h = sign_request(
        "PUT", f"{url}/ovr?policy", "AKALICE", "alicesecret", pol.encode()
    )
    assert requests.put(f"{url}/ovr?policy", data=pol, headers=h).status_code == 204

    from seaweedfs_tpu.s3 import Identity

    srv.identities.add(
        Identity(
            "carol",
            "AKCAROL",
            "carolsecret",
            policies=(
                {
                    "Statement": [
                        {
                            "Effect": "Deny",
                            "Action": "s3:GetObject",
                            "Resource": "arn:aws:s3:::ovr/*",
                        }
                    ]
                },
            ),
        )
    )
    body = b"v"
    h = sign_request("PUT", f"{url}/ovr/k", "AKALICE", "alicesecret", body)
    requests.put(f"{url}/ovr/k", data=body, headers=h)
    h = sign_request("GET", f"{url}/ovr/k", "AKCAROL", "carolsecret")
    assert requests.get(f"{url}/ovr/k", headers=h).status_code == 403


def test_post_policy_requires_write_permission(s3_two_users):
    """A read-only credential signing its own POST policy must not be
    able to write (authn != authz)."""
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/ro", "AKALICE", "alicesecret")
    requests.put(f"{url}/ro", headers=h)
    r = _post_form(url, "ro", "sneak", b"data", "AKBOB", "bobsecret")
    assert r.status_code == 403


def test_post_policy_preserves_trailing_newlines(s3_two_users):
    """Multipart parser must not strip payload CR/LF bytes."""
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/nl", "AKALICE", "alicesecret")
    requests.put(f"{url}/nl", headers=h)
    data = b"line one\nline two\r\n\n"
    r = _post_form(url, "nl", "text.txt", data, "AKALICE", "alicesecret")
    assert r.status_code == 204
    h = sign_request("GET", f"{url}/nl/text.txt", "AKALICE", "alicesecret")
    assert requests.get(f"{url}/nl/text.txt", headers=h).content == data


@needs_crypto
def test_multipart_on_default_encrypted_bucket_encrypts(s3):
    """Bucket default encryption applies to multipart uploads too —
    plaintext must never land in an AES256-default bucket."""
    url, srv = s3
    requests.put(f"{url}/mpenc")
    conf = (
        "<ServerSideEncryptionConfiguration><Rule>"
        "<ApplyServerSideEncryptionByDefault><SSEAlgorithm>AES256"
        "</SSEAlgorithm></ApplyServerSideEncryptionByDefault>"
        "</Rule></ServerSideEncryptionConfiguration>"
    )
    requests.put(f"{url}/mpenc?encryption", data=conf)
    parts = [b"default" * 3000, b"enc" * 5000]
    plain = b"".join(parts)
    r = _multipart_upload(url, "mpenc", "auto.enc", parts)
    assert r.status_code == 200, r.text
    entry = srv.filer.find_entry("/buckets/mpenc/auto.enc")
    assert srv.filer.read_entry(entry) != plain  # ciphertext at rest
    assert requests.get(f"{url}/mpenc/auto.enc").content == plain


def test_post_policy_rejects_uncovered_fields(s3_two_users):
    """A form field the signed policy does not cover must be rejected:
    otherwise the holder of a signed form could append e.g. an acl
    grant the signer never authorized."""
    url, _ = s3_two_users
    h = sign_request("PUT", f"{url}/cov", "AKALICE", "alicesecret")
    requests.put(f"{url}/cov", headers=h)
    r = _post_form(
        url, "cov", "k", b"data", "AKALICE", "alicesecret",
        extra_fields={"acl": "public-read-write"}, cover_extras=False,
    )
    assert r.status_code == 403 and "not covered" in r.text
    # the same field WITH a covering condition is accepted
    r = _post_form(
        url, "cov", "k", b"data", "AKALICE", "alicesecret",
        extra_fields={"acl": "public-read"},
    )
    assert r.status_code == 204


# -------------------------------------------------------------- S3 Select


def _parse_event_stream(body: bytes) -> dict:
    """Minimal AWS event-stream reader: {event_type: payload}."""
    import struct as _struct
    import zlib as _zlib

    out = {}
    pos = 0
    while pos < len(body):
        total, hlen = _struct.unpack_from(">II", body, pos)
        prelude_crc = _struct.unpack_from(">I", body, pos + 8)[0]
        assert _zlib.crc32(body[pos : pos + 8]) == prelude_crc
        headers_raw = body[pos + 12 : pos + 12 + hlen]
        payload = body[pos + 12 + hlen : pos + total - 4]
        msg_crc = _struct.unpack_from(">I", body, pos + total - 4)[0]
        assert _zlib.crc32(body[pos : pos + total - 4]) == msg_crc
        headers = {}
        hp = 0
        while hp < len(headers_raw):
            nlen = headers_raw[hp]
            name = headers_raw[hp + 1 : hp + 1 + nlen].decode()
            hp += 1 + nlen
            assert headers_raw[hp] == 7  # string
            vlen = _struct.unpack_from(">H", headers_raw, hp + 1)[0]
            headers[name] = headers_raw[hp + 3 : hp + 3 + vlen].decode()
            hp += 3 + vlen
        out[headers.get(":event-type", "?")] = payload
        pos += total
    return out


def test_s3_select_csv_and_json(s3):
    url, _ = s3
    requests.put(f"{url}/sel")
    csv_data = "city,pop\nparis,2100000\nlyon,520000\nnice,340000\n"
    requests.put(f"{url}/sel/cities.csv", data=csv_data.encode())

    def select(key, expression, input_xml, output_xml="<JSON/>"):
        req = (
            '<?xml version="1.0"?><SelectObjectContentRequest>'
            f"<Expression>{expression}</Expression>"
            "<ExpressionType>SQL</ExpressionType>"
            f"<InputSerialization>{input_xml}</InputSerialization>"
            f"<OutputSerialization>{output_xml}</OutputSerialization>"
            "</SelectObjectContentRequest>"
        )
        return requests.post(
            f"{url}/sel/{key}?select&amp;select-type=2".replace("&amp;", "&"),
            data=req,
        )

    r = select(
        "cities.csv",
        "SELECT s.city FROM S3Object s WHERE s.pop &gt; 500000"
        .replace("&gt;", ">"),
        "<CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV>",
    )
    assert r.status_code == 200, r.text
    events = _parse_event_stream(r.content)
    assert "End" in events and "Stats" in events
    rows = [json.loads(x) for x in events["Records"].split(b"\n") if x]
    assert rows == [{"city": "paris"}, {"city": "lyon"}]

    # positional columns (no header), CSV output
    r = select(
        "cities.csv",
        "SELECT s._1 FROM S3Object s WHERE s._2 = 340000",
        "<CSV><FileHeaderInfo>IGNORE</FileHeaderInfo></CSV>",
        "<CSV/>",
    )
    events = _parse_event_stream(r.content)
    assert events["Records"].strip() == b"nice"

    # JSON lines + aggregate
    jl = "\n".join(
        json.dumps({"n": i, "grp": "a" if i % 2 else "b"}) for i in range(10)
    )
    requests.put(f"{url}/sel/data.jsonl", data=jl.encode())
    r = select(
        "data.jsonl",
        "SELECT COUNT(*) AS c, MAX(n) AS m FROM S3Object s WHERE s.grp = 'a'",
        "<JSON><Type>LINES</Type></JSON>",
    )
    events = _parse_event_stream(r.content)
    row = json.loads(events["Records"].split(b"\n")[0])
    assert row == {"c": 5, "m": 9}

    # gzip input
    import gzip as _gz

    requests.put(f"{url}/sel/z.csv.gz", data=_gz.compress(csv_data.encode()))
    r = select(
        "z.csv.gz",
        "SELECT COUNT(*) AS n FROM S3Object",
        "<CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV>"
        "<CompressionType>GZIP</CompressionType>",
    )
    events = _parse_event_stream(r.content)
    assert json.loads(events["Records"].split(b"\n")[0]) == {"n": 3}

    # invalid SQL -> clean 400
    r = select("cities.csv", "DROP TABLE x", "<CSV/>")
    assert r.status_code == 400


def test_s3_select_group_by(s3):
    """GROUP BY + HAVING + ORDER BY through SelectObjectContent (the
    round-5 engine features surface on every SQL entry point)."""
    url, _ = s3
    requests.put(f"{url}/selg")
    csv_data = (
        "city,pop\nparis,100\nparis,200\nlyon,50\nlyon,60\nnice,10\n"
    )
    requests.put(f"{url}/selg/c.csv", data=csv_data.encode())
    req = (
        '<?xml version="1.0"?><SelectObjectContentRequest>'
        "<Expression>SELECT s.city, COUNT(*) AS n, SUM(s.pop) AS total "
        "FROM S3Object s GROUP BY s.city HAVING n &gt;= 2 "
        "ORDER BY total DESC</Expression>"
        "<ExpressionType>SQL</ExpressionType>"
        "<InputSerialization><CSV><FileHeaderInfo>USE</FileHeaderInfo>"
        "</CSV></InputSerialization>"
        "<OutputSerialization><JSON/></OutputSerialization>"
        "</SelectObjectContentRequest>"
    ).replace("&gt;", ">")
    r = requests.post(f"{url}/selg/c.csv?select&select-type=2", data=req)
    assert r.status_code == 200, r.text
    events = _parse_event_stream(r.content)
    rows = [json.loads(x) for x in events["Records"].split(b"\n") if x]
    assert rows == [
        {"city": "paris", "n": 2, "total": 300.0},
        {"city": "lyon", "n": 2, "total": 110.0},
    ]
