"""Group commit + write-path crash windows (ISSUE 18 satellite).

The durability contract under test: a write acked with fsync=True has
survived SIGKILL at every kill point — covered either by its own fsync
(window 0, the default) or by a group-commit window fsync
(SEAWEED_VOLUME_GROUP_COMMIT_MS > 0) — and a kill BEFORE the ack
leaves the volume cleanly replayable, the unacked needle either fully
present or absent, never acked-but-lost. The forked children mirror
tests/test_ec_chaos.py's crash-window idiom: `hard_exit` armed at one
fault point, the parent asserting on the replayed on-disk state.
"""

from __future__ import annotations

import multiprocessing
import os
import threading
import time

import pytest

from seaweedfs_tpu import faults
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import NotFoundError, Volume

DATA1 = b"first-acked-" * 200
DATA2 = b"second-dies-" * 200


# ------------------------------------------------------- group commit


def test_group_commit_batches_fsyncs(tmp_path, monkeypatch):
    """N concurrent durable writers inside one window cost a handful
    of fsyncs, not 2N (.dat + .idx per needle) — and every acked write
    reads back."""
    monkeypatch.setenv("SEAWEED_VOLUME_GROUP_COMMIT_MS", "30")
    v = Volume(str(tmp_path), 1, create=True)
    real_fsync = os.fsync
    count = [0]

    def counting_fsync(fd):
        count[0] += 1
        real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    n_writers = 16
    errs = []

    def write(i):
        try:
            v.write_needle(
                Needle(cookie=0x10 + i, needle_id=100 + i, data=DATA1),
                fsync=True,
            )
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [
        threading.Thread(target=write, args=(i,)) for i in range(n_writers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    batched = count[0]
    # fsync-per-needle would cost 2 * n_writers syncs; a 30ms window
    # over near-simultaneous writers covers them in a few commits
    assert batched < 2 * n_writers, f"no batching: {batched} fsyncs"
    for i in range(n_writers):
        assert v.read_needle(100 + i).data == DATA1
    # window -> 0 mid-life: the committer is torn down and the next
    # durable write fsyncs inline (the bench's off phase)
    monkeypatch.setenv("SEAWEED_VOLUME_GROUP_COMMIT_MS", "0")
    before = count[0]
    v.write_needle(Needle(cookie=1, needle_id=999, data=DATA1), fsync=True)
    assert v._committer is None
    assert count[0] >= before + 1
    v.close()


def test_group_commit_fsync_error_fails_whole_window(tmp_path, monkeypatch):
    """A failed window fsync certifies NOTHING: every writer waiting on
    that window gets the error instead of a false durability ack."""
    monkeypatch.setenv("SEAWEED_VOLUME_GROUP_COMMIT_MS", "20")
    v = Volume(str(tmp_path), 1, create=True)
    real = Volume._fsync_all
    monkeypatch.setattr(
        Volume, "_fsync_all",
        lambda self: (_ for _ in ()).throw(OSError("disk gone")),
    )
    errs = []

    def write(i):
        try:
            v.write_needle(
                Needle(cookie=i, needle_id=200 + i, data=DATA1), fsync=True
            )
        except OSError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=write, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(errs) == 3
    assert all("group commit fsync failed" in e for e in errs)
    # healed disk: the same committer serves the next window
    monkeypatch.setattr(Volume, "_fsync_all", real)
    v.write_needle(Needle(cookie=9, needle_id=300, data=DATA1), fsync=True)
    assert v.read_needle(300).data == DATA1
    v.close()


def test_window_zero_is_fsync_per_needle(tmp_path, monkeypatch):
    """The default (window 0) keeps the old contract exactly: each
    durable write fsyncs .dat and flushes the idx inline, no committer
    thread exists."""
    monkeypatch.delenv("SEAWEED_VOLUME_GROUP_COMMIT_MS", raising=False)
    v = Volume(str(tmp_path), 1, create=True)
    real_fsync = os.fsync
    count = [0]
    monkeypatch.setattr(
        os, "fsync", lambda fd: (count.__setitem__(0, count[0] + 1),
                                 real_fsync(fd))[1]
    )
    v.write_needle(Needle(cookie=1, needle_id=1, data=DATA1), fsync=True)
    assert v._committer is None
    assert count[0] >= 2  # .dat + .idx
    v.close()


# ------------------------------------------- volume write crash matrix


def _volume_crash_child(dirpath, point, window_ms, conn):
    os.environ["SEAWEED_VOLUME_GROUP_COMMIT_MS"] = str(window_ms)
    v = Volume(dirpath, 1, create=True)
    v.write_needle(Needle(cookie=0x11, needle_id=1, data=DATA1), fsync=True)
    conn.send(("acked", 1))
    faults.inject(point, faults.hard_exit(137))
    v.write_needle(Needle(cookie=0x22, needle_id=2, data=DATA2), fsync=True)
    conn.send(("acked", 2))  # pragma: no cover - only on fault miss
    os._exit(0)  # pragma: no cover


@pytest.mark.parametrize("window_ms", [0, 15])
@pytest.mark.parametrize(
    "point",
    [
        "volume.write.before_fsync",
        "volume.write.after_fsync",
        "volume.write.before_ack",
    ],
)
def test_volume_write_crash_acked_is_durable(tmp_path, point, window_ms):
    """SIGKILL at each write-path kill point, per fsync mode: the
    acked needle replays intact; the mid-write needle is fully present
    or cleanly absent; a kill AFTER the durability step (but before
    the ack) still finds the bytes on disk."""
    mp = multiprocessing.get_context("fork")
    parent, child = mp.Pipe()
    p = mp.Process(
        target=_volume_crash_child,
        args=(str(tmp_path), point, window_ms, child),
    )
    p.start()
    p.join(timeout=120)
    assert not p.is_alive(), "crash child hung"
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"
    msgs = []
    while parent.poll():
        msgs.append(parent.recv())
    assert ("acked", 1) in msgs
    assert ("acked", 2) not in msgs, "child survived past the crash point"
    v = Volume(str(tmp_path), 1, create=False)
    try:
        assert v.read_needle(1).data == DATA1, "ACKED write lost"
        if point in ("volume.write.after_fsync", "volume.write.before_ack"):
            # the durability step completed before the kill
            assert v.read_needle(2).data == DATA2
        else:
            # unacked: fully there or absent — never torn, never wrong
            try:
                assert v.read_needle(2).data == DATA2
            except NotFoundError:
                pass
    finally:
        v.close()


# -------------------------------------- net-plane write crash matrix


def _refuse_shards(vid, sid, gen):
    from seaweedfs_tpu.ec import net_plane

    raise net_plane.NetPlaneError("no shards here")


def _plane_crash_child(dirpath, point, conn):
    from seaweedfs_tpu.ec import net_plane

    v = Volume(dirpath, 1, create=True)

    def resolve_write(vid, nid, cookie, data, md):
        n = Needle(cookie=cookie, needle_id=nid, data=data)
        _, size = v.write_needle(n, fsync=True)
        return size, n.checksum

    srv = net_plane.ShardNetPlane(
        "127.0.0.1", 0, _refuse_shards, resolve_write=resolve_write
    )
    srv.start()
    # second write dies at the armed point; the first must serve
    # normally even though write-path chaos is armed (the write plane
    # stays admissible under its own namespaces)
    faults.inject(point, faults.hard_exit(137), when=faults.nth_call(2))
    conn.send(srv.port)
    time.sleep(120)  # pragma: no cover - killed by the fault
    os._exit(1)  # pragma: no cover


@pytest.mark.parametrize(
    "point", ["ec.net.write.before_pwrite", "ec.net.write.after_pwrite"]
)
def test_net_plane_write_crash_acked_is_durable(tmp_path, point):
    """SIGKILL the volume-server side of a native-plane write: the
    previously ACKED needle replays intact; the in-flight one is on
    disk iff the kill came after the pwrite+fsync — and the client saw
    no ack either way."""
    from seaweedfs_tpu.ec import net_plane

    mp = multiprocessing.get_context("fork")
    parent, child = mp.Pipe()
    p = mp.Process(
        target=_plane_crash_child, args=(str(tmp_path), point, child)
    )
    p.start()
    assert parent.poll(30), "child never published its port"
    port = parent.recv()
    client = net_plane.NetPlaneClient()
    try:
        addr = ("127.0.0.1", port)
        size, crc = client.write_needle(addr, 1, 1, 0x11, DATA1)
        assert size > 0
        with pytest.raises(net_plane.NetPlaneError):
            client.write_needle(addr, 1, 2, 0x22, DATA2)
    finally:
        client.close()
    p.join(timeout=120)
    assert not p.is_alive(), "crash child hung"
    assert p.exitcode == 137, f"expected hard crash, got {p.exitcode}"
    v = Volume(str(tmp_path), 1, create=False)
    try:
        assert v.read_needle(1).data == DATA1, "ACKED plane write lost"
        if point == "ec.net.write.after_pwrite":
            assert v.read_needle(2).data == DATA2
        else:
            with pytest.raises(NotFoundError):
                v.read_needle(2)
    finally:
        v.close()
