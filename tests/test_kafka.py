"""Kafka wire-protocol gateway (mq/kafka/).

Mirrors the reference's test/kafka suites: codec golden vectors, then a
live gateway driven over real sockets by the in-repo client — produce/
fetch round trips, offset management, consumer-group rebalances, and
version negotiation.
"""

import struct
import threading
import time

import pytest

from conftest import allocate_port
from seaweedfs_tpu.mq.broker import MqBrokerServer
from seaweedfs_tpu.mq.kafka import protocol as kp
from seaweedfs_tpu.mq.kafka.client import (
    KafkaClient,
    KafkaError,
    assign_range,
    parse_assignment,
)
from seaweedfs_tpu.mq.kafka.protocol import Reader, write_varint
from seaweedfs_tpu.mq.kafka.records import (
    Record,
    decode_batches,
    encode_batch,
)

# ------------------------------------------------------------- codec


def test_zigzag_varint_golden_vectors():
    # protobuf/Kafka zigzag encoding, spec values
    assert write_varint(0) == b"\x00"
    assert write_varint(-1) == b"\x01"
    assert write_varint(1) == b"\x02"
    assert write_varint(-2) == b"\x03"
    assert write_varint(150) == b"\xac\x02"
    r = Reader(b"\xac\x02")
    assert r.varint() == 150
    for v in (0, -1, 7, -300, 2**31, -(2**40)):
        assert Reader(write_varint(v)).varint() == v


def test_crc32c_check_value_anchor():
    # RFC 3720 CRC32C check string — anchors the batch CRC field
    from seaweedfs_tpu.utils.crc import crc32c

    assert crc32c(b"123456789") == 0xE3069283


def test_record_batch_golden_layout():
    """Byte-level layout of a one-record batch against the Kafka spec."""
    batch = encode_batch(
        [Record(key=None, value=b"A", timestamp_ms=1000, offset=5)],
        base_offset=5,
    )
    base_offset, batch_len, leader_epoch, magic = struct.unpack_from(
        ">qiib", batch, 0
    )
    assert base_offset == 5
    assert magic == 2
    assert leader_epoch == -1
    assert len(batch) == 12 + batch_len
    # post-crc block: attributes..recordCount
    (attrs, last_delta, base_ts, max_ts, pid, pepoch, bseq, count) = (
        struct.unpack_from(">hiqqqhii", batch, 21)
    )
    assert (attrs, last_delta, count) == (0, 0, 1)
    assert base_ts == max_ts == 1000
    assert (pid, pepoch, bseq) == (-1, -1, -1)
    # the single record, spec-encoded: len=7(zigzag 0x0E), attrs, tsΔ,
    # offΔ, keyLen=-1, valLen=1, 'A', headerCount=0
    assert batch[61:] == b"\x0e\x00\x00\x00\x01\x02\x41\x00"


def test_record_batch_round_trip_and_crc():
    recs = [
        Record(key=b"k1", value=b"v1", timestamp_ms=111, offset=7),
        Record(
            key=None,
            value=b"v2",
            timestamp_ms=222,
            offset=8,
            headers=[("h", b"x"), ("n", None)],
        ),
        Record(key=b"k3", value=None, timestamp_ms=333, offset=9),
    ]
    blob = encode_batch(recs, base_offset=7)
    out = decode_batches(blob)
    assert [(r.key, r.value, r.timestamp_ms, r.offset) for r in out] == [
        (b"k1", b"v1", 111, 7),
        (None, b"v2", 222, 8),
        (b"k3", None, 333, 9),
    ]
    assert out[1].headers == [("h", b"x"), ("n", None)]
    # CRC tamper: flip one payload byte
    bad = bytearray(blob)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        decode_batches(bytes(bad))
    # truncated trailing batch tolerated (Kafka permits partial fetches)
    assert decode_batches(blob + blob[: len(blob) // 2]) == out


def test_gzip_compressed_batch_decodes():
    import gzip as _gzip

    recs = [Record(key=b"k", value=b"v" * 100, timestamp_ms=5, offset=0)]
    blob = bytearray(encode_batch(recs, base_offset=0))
    # rebuild as a gzip batch: compress the records section, set codec=1
    post = bytes(blob[21:])  # attributes..end
    attrs_etc = post[:40]
    payload = _gzip.compress(post[40:])
    new_post = struct.pack(">h", 1) + attrs_etc[2:] + payload
    from seaweedfs_tpu.utils.crc import crc32c

    head = struct.pack(">qiib", 0, 4 + 1 + 4 + len(new_post), -1, 2)
    rebuilt = head + struct.pack(">I", crc32c(new_post)) + new_post
    out = decode_batches(rebuilt)
    assert out[0].value == b"v" * 100


# ----------------------------------------------------------- gateway


@pytest.fixture
def gateway():
    srv = MqBrokerServer(
        ip="127.0.0.1", grpc_port=allocate_port(), kafka_port=0
    )
    srv.start()
    yield srv
    srv.stop()


def _client(gw) -> KafkaClient:
    return KafkaClient("127.0.0.1", gw.kafka.port)


def test_api_versions_and_unsupported_fallback(gateway):
    c = _client(gateway)
    try:
        assert kp.PRODUCE in c.api_versions
        assert c.api_versions[kp.FETCH] == (4, 11)
        # an out-of-range ApiVersions must return v0 body + error 35
        r = c._call(kp.API_VERSIONS, 9, b"")
        assert r.i16() == kp.UNSUPPORTED_VERSION
        ranges = {r.i16(): (r.i16(), r.i16()) for _ in range(r.i32())}
        assert ranges[kp.METADATA] == (0, 8)
        # an out-of-range Produce gets the plain error body
        r = c._call(kp.PRODUCE, 99, b"")
        assert r.i16() == kp.UNSUPPORTED_VERSION
    finally:
        c.close()


def test_metadata_auto_create_and_create_topics(gateway):
    c = _client(gateway)
    try:
        md = c.metadata(["fresh-topic"])
        assert md["topics"]["fresh-topic"]["error"] == kp.NONE
        assert len(md["topics"]["fresh-topic"]["partitions"]) == 1
        assert c.create_topic("made", partitions=4) == kp.NONE
        assert c.create_topic("made", partitions=4) == kp.TOPIC_ALREADY_EXISTS
        assert c.create_topic("bad name!") == kp.INVALID_TOPIC_EXCEPTION
        md = c.metadata(["made"])
        assert len(md["topics"]["made"]["partitions"]) == 4
        assert md["brokers"][0][2] == gateway.kafka.port
        assert c.delete_topic("made") == kp.NONE
        assert c.delete_topic("made") == kp.UNKNOWN_TOPIC_OR_PARTITION
    finally:
        c.close()


def test_produce_fetch_round_trip(gateway):
    c = _client(gateway)
    try:
        c.create_topic("t1", partitions=2)
        base = c.produce(
            "t1",
            0,
            [
                Record(key=b"a", value=b"one", timestamp_ms=int(time.time() * 1000)),
                Record(key=b"b", value=b"two", timestamp_ms=int(time.time() * 1000)),
            ],
        )
        assert base == 0
        base2 = c.produce("t1", 0, [Record(key=None, value=b"three")])
        assert base2 == 2
        hw, recs = c.fetch("t1", 0, 0)
        assert hw == 3
        assert [r.value for r in recs] == [b"one", b"two", b"three"]
        assert [r.offset for r in recs] == [0, 1, 2]
        assert recs[0].key == b"a" and recs[2].key is None
        # fetch from mid-stream
        _, recs = c.fetch("t1", 0, 2)
        assert [r.value for r in recs] == [b"three"]
        # other partition untouched
        hw_p1, recs_p1 = c.fetch("t1", 1, 0)
        assert hw_p1 == 0 and recs_p1 == []
        # unknown topic/partition errors
        with pytest.raises(KafkaError) as ei:
            c.fetch("nope", 0, 0)
        assert ei.value.code == kp.UNKNOWN_TOPIC_OR_PARTITION
        with pytest.raises(KafkaError) as ei:
            c.fetch("t1", 0, 99)  # past the high watermark
        assert ei.value.code == kp.OFFSET_OUT_OF_RANGE
    finally:
        c.close()


def test_tombstones_and_empty_values_survive(gateway):
    """null vs empty keys/values must round-trip exactly — a null value
    is a compaction tombstone, not an empty message."""
    c = _client(gateway)
    try:
        c.create_topic("ts", partitions=1)
        c.produce(
            "ts",
            0,
            [
                Record(key=b"k", value=None),  # tombstone
                Record(key=b"", value=b""),  # empty, non-null
                Record(key=None, value=b"v"),
            ],
        )
        _, recs = c.fetch("ts", 0, 0)
        assert [(r.key, r.value) for r in recs] == [
            (b"k", None),
            (b"", b""),
            (None, b"v"),
        ]
    finally:
        c.close()


def test_fetch_partition_max_bytes_truncates(gateway):
    c = _client(gateway)
    try:
        c.create_topic("big", partitions=1)
        big = b"x" * 10_000
        c.produce("big", 0, [Record(key=None, value=big) for _ in range(20)])
        # small budget: fewer records come back, but at least one
        hw, recs = c.fetch("big", 0, 0, max_bytes=25_000)
        assert hw == 20
        assert 1 <= len(recs) <= 3
        assert recs[0].value == big
        # progress continues from where we left off
        _, recs2 = c.fetch("big", 0, recs[-1].offset + 1, max_bytes=25_000)
        assert recs2[0].offset == recs[-1].offset + 1
    finally:
        c.close()


def test_fetch_long_poll_wakes_on_produce(gateway):
    c = _client(gateway)
    p = _client(gateway)
    try:
        c.create_topic("lp", partitions=1)

        def produce_later():
            time.sleep(0.15)
            p.produce("lp", 0, [Record(key=None, value=b"wake")])

        t = threading.Thread(target=produce_later)
        t0 = time.monotonic()
        t.start()
        hw, recs = c.fetch("lp", 0, 0, max_wait_ms=5000)
        elapsed = time.monotonic() - t0
        t.join()
        assert [r.value for r in recs] == [b"wake"]
        assert elapsed < 3.0, "long-poll should wake on produce, not timeout"
    finally:
        c.close()
        p.close()


def test_list_offsets_and_committed_offsets(gateway):
    c = _client(gateway)
    try:
        c.create_topic("off", partitions=1)
        now = int(time.time() * 1000)
        for i in range(5):
            c.produce(
                "off", 0, [Record(key=None, value=b"x%d" % i, timestamp_ms=now + i * 10)]
            )
        assert c.list_offset("off", 0, -2) == 0  # earliest
        assert c.list_offset("off", 0, -1) == 5  # latest
        assert c.list_offset("off", 0, now + 25) == 3  # first at/after ts
        # committed offsets round-trip (and isolation per group)
        assert c.commit_offset("g1", "off", 0, 3) == kp.NONE
        assert c.fetch_offset("g1", "off", 0) == 3
        assert c.fetch_offset("g2", "off", 0) == -1
        host, port = c.find_coordinator("g1")
        assert port == gateway.kafka.port
    finally:
        c.close()


def test_consumer_group_rebalance_two_members(gateway):
    ca, cb = _client(gateway), _client(gateway)
    try:
        ca.create_topic("gt", partitions=4)
        results = {}

        def member(name, cli):
            j = cli.join_group("grp", topics=["gt"])
            if j["member_id"] == j["leader"]:
                assigns = assign_range(j["members"], {"gt": 4})
                blob = cli.sync_group(
                    "grp", j["generation"], j["member_id"], assigns
                )
            else:
                blob = cli.sync_group("grp", j["generation"], j["member_id"])
            results[name] = (j, parse_assignment(blob))

        ta = threading.Thread(target=member, args=("a", ca))
        tb = threading.Thread(target=member, args=("b", cb))
        ta.start(), tb.start()
        ta.join(20), tb.join(20)
        assert set(results) == {"a", "b"}
        ja, aa = results["a"]
        jb, ab = results["b"]
        assert ja["generation"] == jb["generation"]
        # the 4 partitions are split 2/2 with no overlap
        pa, pb = set(aa.get("gt", [])), set(ab.get("gt", []))
        assert pa | pb == {0, 1, 2, 3}
        assert pa & pb == set()
        assert len(pa) == len(pb) == 2
        # heartbeats accepted at the current generation
        assert ca.heartbeat("grp", ja["generation"], ja["member_id"]) == kp.NONE
        # stale generation rejected
        assert (
            ca.heartbeat("grp", ja["generation"] - 1, ja["member_id"])
            == kp.ILLEGAL_GENERATION
        )
        # leaving triggers a rebalance for the survivor
        assert cb.leave_group("grp", jb["member_id"]) == kp.NONE
        code = ca.heartbeat("grp", ja["generation"], ja["member_id"])
        assert code in (kp.REBALANCE_IN_PROGRESS, kp.NONE)
        j2 = ca.join_group(
            "grp", member_id=ja["member_id"], topics=["gt"]
        )
        assert j2["generation"] > ja["generation"]
        assert j2["leader"] == j2["member_id"]  # sole survivor leads
        blob = ca.sync_group(
            "grp",
            j2["generation"],
            j2["member_id"],
            assign_range(j2["members"], {"gt": 4}),
        )
        assert set(parse_assignment(blob)["gt"]) == {0, 1, 2, 3}
    finally:
        ca.close()
        cb.close()


def test_gateway_via_spawned_process(tmp_path):
    """The launcher serves Kafka on -kafkaPort (reference
    `weed mq.kafka.gateway`)."""
    import subprocess
    import sys

    gport, kport = allocate_port(), allocate_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "seaweedfs_tpu.server", "mq.broker",
            "-ip", "127.0.0.1", "-port", str(gport),
            "-kafkaPort", str(kport),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        c = None
        for _ in range(100):
            try:
                c = KafkaClient("127.0.0.1", kport)
                break
            except OSError:
                time.sleep(0.1)
        assert c is not None, "gateway never came up"
        c.create_topic("spawned", partitions=1)
        c.produce("spawned", 0, [Record(key=b"k", value=b"live")])
        hw, recs = c.fetch("spawned", 0, 0)
        assert hw == 1 and recs[0].value == b"live"
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_compressed_produce_all_codecs(gateway):
    """snappy/lz4/zstd/gzip batches decode on the produce path (codec
    ids 1-4); the reference's API_VERSION_MATRIX gates on this."""
    from seaweedfs_tpu.mq.kafka import records as kr

    c = _client(gateway)
    try:
        c.create_topic("codecs", partitions=1)
        payloads = {}
        for codec in (
            kr.COMPRESSION_GZIP,
            kr.COMPRESSION_SNAPPY,
            kr.COMPRESSION_LZ4,
            kr.COMPRESSION_ZSTD,
        ):
            val = f"compressed-{codec}".encode() * 50
            base = c.produce(
                "codecs",
                0,
                [Record(key=b"k", value=val)],
                compression=codec,
            )
            payloads[base] = val
        hw, recs = c.fetch("codecs", 0, 0)
        assert hw == 4
        for r in recs:
            assert r.value == payloads[r.offset]
    finally:
        c.close()


def test_produce_version_matrix(gateway):
    """The same round-trip must hold at every advertised Produce and
    Fetch version (old non-flexible clients keep working)."""
    c = _client(gateway)
    try:
        c.create_topic("vmx", partitions=1)
        expect = []
        for v in (3, 5, 7, 8, 9):
            off = c.produce(
                "vmx", 0, [Record(key=None, value=f"v{v}".encode())],
                version=v,
            )
            expect.append((off, f"v{v}".encode()))
        for fv in (4, 5, 7, 9, 11):
            hw, recs = c.fetch("vmx", 0, 0, version=fv)
            assert hw == len(expect)
            assert [(r.offset, r.value) for r in recs] == expect, fv
    finally:
        c.close()


def test_xerial_snappy_produce(gateway):
    """Java clients frame snappy with the xerial header — build one by
    hand and push it through a raw v7 produce."""
    import struct as _struct

    from seaweedfs_tpu.mq.kafka import codecs as kc
    from seaweedfs_tpu.mq.kafka import records as kr
    from seaweedfs_tpu.mq.kafka.protocol import Writer as W

    c = _client(gateway)
    try:
        c.create_topic("xer", partitions=1)
        batch = encode_batch([Record(key=None, value=b"xerial-payload")])
        # rebuild the batch with xerial-framed snappy payload
        plain = kr.decode_batches(batch)
        recs_section = batch[61:]  # after the 61-byte v2 batch header
        block = kc.snappy_compress(recs_section)
        xerial = (
            b"\x82SNAPPY\x00" + b"\x00" * 8
            + _struct.pack(">i", len(block)) + block
        )
        post_crc = (
            kr._POST_CRC.pack(
                kr.COMPRESSION_SNAPPY, 0,
                plain[0].timestamp_ms, plain[0].timestamp_ms,
                -1, -1, -1, 1,
            )
            + xerial
        )
        from seaweedfs_tpu.utils.crc import crc32c

        rebuilt = (
            kr._HEADER.pack(0, 4 + 1 + 4 + len(post_crc), -1, kr.MAGIC_V2)
            + _struct.pack(">I", crc32c(post_crc))
            + post_crc
        )
        w = W()
        w.nullable_string(None)
        w.i16(-1).i32(10_000)
        w.array(
            [("xer", 0, rebuilt)],
            lambda ww, tp: ww.string(tp[0]).array(
                [tp], lambda w3, tp2: w3.i32(tp2[1]).bytes_(tp2[2])
            ),
        )
        r = c._call(kp.PRODUCE, 7, w.done())
        r.i32(); r.string(); r.i32(); r.i32()
        assert r.i16() == kp.NONE
        _, recs = c.fetch("xer", 0, 0)
        assert recs[0].value == b"xerial-payload"
    finally:
        c.close()


def test_lz4_block_linked_frame_decodes():
    """librdkafka / python-lz4 default to block-LINKED frames (FLG bit 5
    clear): matches in block N may reference bytes produced by block
    N-1. Hand-built two-block linked frame; block 2 is a single match
    reaching 8 bytes back into block 1's output (advisor r4 low)."""
    import struct as _struct

    from seaweedfs_tpu.mq.kafka import codecs as kc

    flg = 0x40  # version 01, LINKED blocks (0x20 clear), no checksums
    bd = 0x40  # 64 KiB max block size
    hc = (kc.xxh32(bytes([flg, bd])) >> 8) & 0xFF
    block1 = bytes([0x80]) + b"abcdefgh"  # literals-only sequence
    block2 = bytes([0x00, 0x08, 0x00])  # 0 literals, match off=8 len=4
    frame = (
        _struct.pack("<I", 0x184D2204)
        + bytes([flg, bd, hc])
        + _struct.pack("<I", len(block1)) + block1
        + _struct.pack("<I", len(block2)) + block2
        + _struct.pack("<I", 0)
    )
    assert kc.lz4_decompress(frame) == b"abcdefghabcd"
    # independent-block frames still decode (regression guard)
    assert kc.lz4_decompress(kc.lz4_compress(b"x" * 1000)) == b"x" * 1000
