"""Chunked dirty-page writer tests (reference weed/mount/page_writer.go
+ dirty_pages_chunked.go): interval merging, chunk spill with bounded
memory, commit over the filer gRPC service.

Runs WITHOUT a kernel mount: FilerMount methods are driven directly
with fake fuse_file_info objects, so these tests exercise the page
writer everywhere (test_mount.py covers the kernel-mount path where
/dev/fuse exists)."""

import ctypes
import time
import types

import pytest
import requests

from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.meta_log import MetaLog
from seaweedfs_tpu.mount.page_writer import PageBuffer
from seaweedfs_tpu.mount.weed_mount import FilerMount
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer

from conftest import allocate_port as free_port


# ------------------------------------------------------------ PageBuffer


def test_page_buffer_sequential_append():
    pb = PageBuffer()
    pb.write(0, b"aaaa")
    pb.write(4, b"bbbb")
    pb.write(8, b"cccc")
    assert pb.drain() == [(0, b"aaaabbbbcccc")]


def test_page_buffer_overlap_latest_wins():
    pb = PageBuffer()
    pb.write(0, b"xxxxxxxxxx")
    pb.write(3, b"YYY")
    assert pb.read(0, 10) == b"xxxYYYxxxx"
    pb.write(8, b"ZZZZ")  # extends past the end
    assert pb.total == 12
    assert pb.read(0, 12) == b"xxxYYYxxZZZZ"


def test_page_buffer_gap_and_merge():
    pb = PageBuffer()
    pb.write(0, b"aa")
    pb.write(10, b"bb")
    assert pb.total == 4
    assert pb.read(0, 2) == b"aa" and pb.read(10, 2) == b"bb"
    assert pb.read(0, 12) is None  # gap: not fully covered
    assert pb.covers_any(1, 10)
    pb.write(2, b"cccccccc")  # bridges the gap
    assert pb.drain() == [(0, b"aaccccccccbb")]


def test_page_buffer_truncate():
    pb = PageBuffer()
    pb.write(0, b"abcdef")
    pb.write(10, b"ghij")
    pb.truncate(12)
    assert pb.read(10, 2) == b"gh"
    pb.truncate(3)
    assert pb.drain() == [(0, b"abc")]


# ------------------------------------------------------ mount page writer


@pytest.fixture(scope="module")
def filer_stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("pwvol")
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    filer = Filer(MemoryStore(), master=f"localhost:{mport}", chunk_size=256 * 1024)
    fs = FilerServer(
        filer,
        ip="localhost",
        port=free_port(),
        meta_log=MetaLog(str(tmp / "metalog")),
        grpc_port=0,
    )
    fs.start()
    yield fs
    fs.stop()
    vs.stop()
    master.stop()


def _fi():
    return types.SimpleNamespace(contents=types.SimpleNamespace(fh=0))


def _mount(fs) -> FilerMount:
    return FilerMount(
        f"localhost:{fs.port}", filer_grpc=f"localhost:{fs.grpc_port}"
    )


def _write(m, fi, path, offset, data):
    buf = ctypes.create_string_buffer(bytes(data), len(data))
    assert m.write(path, buf, len(data), offset, fi) == len(data)


def _read(m, fi, path, offset, size):
    buf = ctypes.create_string_buffer(size)
    n = m.read(path, buf, size, offset, fi)
    assert n >= 0, f"read errno {-n}"
    return buf.raw[:n]


def test_mount_write_spills_with_flat_memory(filer_stack):
    """A 40MB sequential write with an 8MB flush bound keeps dirty
    bytes bounded and round-trips byte-exact (the VERDICT item)."""
    import seaweedfs_tpu.mount.weed_mount as wm

    m = _mount(filer_stack)
    fi = _fi()
    assert m.create("/bigfile.bin", 0o644, fi) == 0
    h = m._handles[fi.contents.fh]
    total = 40 * 1024 * 1024
    step = 1024 * 1024
    peak_dirty = 0
    chunkcount_before_close = None
    for off in range(0, total, step):
        block = bytes([(off // step) % 256]) * step
        _write(m, fi, "/bigfile.bin", off, block)
        peak_dirty = max(peak_dirty, h.pages.total)
    chunkcount_before_close = len(h.chunks)
    assert m.release("/bigfile.bin", fi) == 0
    # bounded memory: dirty pages never exceeded the flush bound + one
    # write, and most data had already spilled as chunks pre-close
    assert peak_dirty <= wm.FLUSH_BYTES + step
    assert chunkcount_before_close >= (total - wm.FLUSH_BYTES) // wm.CHUNK_SIZE
    # committed entry is byte-exact
    r = requests.get(f"http://localhost:{filer_stack.port}/bigfile.bin")
    assert r.status_code == 200 and len(r.content) == total
    for off in range(0, total, step):
        assert r.content[off] == (off // step) % 256


def test_mount_read_modify_write(filer_stack):
    m = _mount(filer_stack)
    fi = _fi()
    assert m.create("/rmw.txt", 0o644, fi) == 0
    _write(m, fi, "/rmw.txt", 0, b"hello world, page writer here")
    assert m.release("/rmw.txt", fi) == 0
    # reopen, patch the middle, read back through the dirty overlay
    fi2 = _fi()
    assert m.open("/rmw.txt", fi2) == 0
    _write(m, fi2, "/rmw.txt", 6, b"WORLD")
    assert _read(m, fi2, "/rmw.txt", 6, 5) == b"WORLD"
    # read across dirty + committed regions forces a commit-then-read
    assert _read(m, fi2, "/rmw.txt", 0, 29) == b"hello WORLD, page writer here"
    assert m.release("/rmw.txt", fi2) == 0
    r = requests.get(f"http://localhost:{filer_stack.port}/rmw.txt")
    assert r.content == b"hello WORLD, page writer here"


def test_mount_sparse_and_truncate(filer_stack):
    m = _mount(filer_stack)
    fi = _fi()
    assert m.create("/sparse.bin", 0o644, fi) == 0
    _write(m, fi, "/sparse.bin", 0, b"head")
    _write(m, fi, "/sparse.bin", 1000, b"tail")
    assert m.ftruncate("/sparse.bin", 1002, fi) == 0
    assert m.release("/sparse.bin", fi) == 0
    r = requests.get(f"http://localhost:{filer_stack.port}/sparse.bin")
    assert len(r.content) == 1002
    assert r.content[:4] == b"head"
    assert r.content[4:1000] == b"\x00" * 996  # gap reads as zeros
    assert r.content[1000:] == b"ta"


def test_mount_shared_handle_refcount(filer_stack):
    m = _mount(filer_stack)
    fi1, fi2 = _fi(), _fi()
    assert m.create("/shared.txt", 0o644, fi1) == 0
    assert m.open("/shared.txt", fi2) == 0  # same live handle
    _write(m, fi1, "/shared.txt", 0, b"via fd1")
    assert _read(m, fi2, "/shared.txt", 0, 7) == b"via fd1"
    assert m.release("/shared.txt", fi1) == 0
    # still open via fd2: path stays visible
    assert m._by_path.get("/shared.txt") is not None
    assert m.release("/shared.txt", fi2) == 0
    assert m._by_path.get("/shared.txt") is None
