"""TTL tests: encoding, volume expiry, ttl-bucketed assignment
(reference weed/storage/needle/volume_ttl.go + TTL volume reaping)."""

import os
import time

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.storage.volume import NotFoundError, Volume


def test_ttl_parse_and_encode():
    assert TTL.parse("") == TTL()
    assert not TTL.parse("0")
    for s, secs in [("5m", 300), ("2h", 7200), ("1d", 86400), ("1w", 7 * 86400)]:
        t = TTL.parse(s)
        assert t.seconds == secs and str(t) == s
        assert TTL.from_bytes(t.to_bytes()) == t
    assert TTL.parse("90").seconds == 90 * 60  # bare number = minutes
    with pytest.raises(ValueError):
        TTL.parse("5x")
    with pytest.raises(ValueError):
        TTL.parse("300m")  # count > 255


def test_ttl_volume_read_expiry(tmp_path):
    v = Volume(str(tmp_path), 2, ttl="1m")
    assert v.ttl.seconds == 60
    n = Needle(cookie=1, needle_id=1, data=b"short lived")
    v.write_needle(n)
    assert v.read_needle(1).data == b"short lived"
    # a needle written 2 minutes ago is expired
    old = Needle(cookie=2, needle_id=2, data=b"stale")
    old.set_last_modified(int(time.time()) - 120)
    v.write_needle(old)
    with pytest.raises(NotFoundError, match="expired"):
        v.read_needle(2)
    v.close()
    # ttl survives reopen via the superblock
    v2 = Volume(str(tmp_path), 2, create=False)
    assert str(v2.ttl) == "1m"
    v2.close()


def test_ttl_volume_reap(tmp_path):
    from seaweedfs_tpu.storage.store import Store

    st = Store([str(tmp_path)])
    v = st.allocate_volume(4, ttl="1m")
    v.write_needle(Needle(cookie=1, needle_id=1, data=b"x"))
    v.flush()
    assert st.reap_expired_volumes() == []  # fresh
    v._last_write_ts = time.time() - 3600  # idle past the TTL window
    assert st.reap_expired_volumes() == [4]
    assert st.find_volume(4) is None
    assert not os.path.exists(str(tmp_path / "4.dat"))
    st.close()


def test_ttl_bucketed_assignment(tmp_path):
    """Assigns with different TTLs must land on different volumes
    (reference VolumeLayout keyed by (collection, rp, ttl))."""
    from seaweedfs_tpu.client.operations import Operations
    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer
    from seaweedfs_tpu.storage.file_id import FileId

    from conftest import allocate_port as free_port

    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    while not master.topo.nodes:
        time.sleep(0.05)
    ops = Operations(f"localhost:{mport}")
    try:
        fid_plain = ops.upload(b"forever")
        fid_ttl = ops.upload(b"ephemeral", ttl="1h")
        vid_plain = FileId.parse(fid_plain).volume_id
        vid_ttl = FileId.parse(fid_ttl).volume_id
        assert vid_plain != vid_ttl, "TTL bucket must not share volumes"
        v = vs.store.find_volume(vid_ttl)
        assert str(v.ttl) == "1h"
        # same-ttl assigns reuse the bucket
        fid_ttl2 = ops.upload(b"ephemeral2", ttl="1h")
        assert FileId.parse(fid_ttl2).volume_id == vid_ttl
        assert ops.read(fid_ttl) == b"ephemeral"
    finally:
        ops.close()
        vs.stop()
        master.stop()
