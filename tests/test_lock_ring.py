"""Filer distributed lock ring (reference weed/cluster/lock_manager).

Done-criterion from the r3 verdict: kill a lock-holding filer — the
lock survives (renewal re-creates it on the ring successor, transfer
moves misplaced leases on membership change), and mutual exclusion
holds throughout.
"""

import time

import pytest

from conftest import allocate_port as free_port
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.lock_ring import DlmClient, _score
from seaweedfs_tpu.server.filer_server import FilerServer
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer


def wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


@pytest.fixture
def ring(tmp_path):
    """Master + 3 filers in one lock ring."""
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="vs registers")

    http_ports = [free_port() for _ in range(3)]
    grpc_ports = [free_port() for _ in range(3)]
    grpc_addrs = [f"localhost:{p}" for p in grpc_ports]
    filers = []
    for i in range(3):
        f = Filer(MemoryStore(), master=f"localhost:{mport}")
        fs = FilerServer(
            f,
            ip="localhost",
            port=http_ports[i],
            grpc_port=grpc_ports[i],
            peers=[a for j, a in enumerate(grpc_addrs) if j != i],
        )
        # fast liveness detection + short failover grace for the test
        fs.lock_ring.probe_interval = 0.3
        fs.lock_ring.FAILOVER_GRACE = 5.0
        fs.start()
        filers.append((f, fs))
    yield master, filers, grpc_addrs
    for f, fs in filers:
        try:
            fs.stop()
            f.close()
        except Exception:
            pass
    vs.stop()
    master.stop()


def test_lock_survives_filer_death(ring):
    master, filers, addrs = ring
    c = DlmClient(addrs)
    try:
        r = c.lock("jobs/compact", owner="worker-1", ttl=30.0)
        assert r.ok, r.error
        token = r.token

        # find which filer holds the lease and kill exactly that one
        holder_idx = None
        for i, (f, fs) in enumerate(filers):
            if fs.lock_ring.locks.status():
                holder_idx = i
        assert holder_idx is not None
        filers[holder_idx][1].stop()

        # mutual exclusion must hold across the failover: another owner
        # cannot steal the name while the holder keeps renewing
        deadline = time.time() + 5
        while time.time() < deadline:
            rr = c.renew("jobs/compact", "worker-1", token, ttl=30.0)
            assert rr.ok, rr.error
            r2 = c.lock("jobs/compact", owner="intruder", ttl=30.0)
            assert not r2.ok and r2.holder == "worker-1"
            time.sleep(0.2)

        # the lease now lives on a SURVIVING filer
        alive = [
            fs for i, (f, fs) in enumerate(filers) if i != holder_idx
        ]
        assert any(fs.lock_ring.locks.status() for fs in alive)

        # release: the name becomes free for the next owner
        assert c.unlock("jobs/compact", token).ok
        r3 = c.lock("jobs/compact", owner="intruder", ttl=5.0)
        assert r3.ok
    finally:
        c.close()


def test_transfer_on_membership_change(ring):
    """A lease created while its ring owner was down moves back to the
    rightful owner once liveness recovers (mover thread)."""
    master, filers, addrs = ring
    c = DlmClient(addrs)
    try:
        name = "jobs/rebalance"
        order = sorted(addrs, key=lambda m: _score(m, name), reverse=True)
        owner_idx = addrs.index(order[0])
        second_idx = addrs.index(order[1])

        # kill the rightful owner; after the failover grace expires the
        # lock lands on the runner-up
        filers[owner_idx][1].stop()
        deadline = time.time() + 20
        while True:
            r = c.lock(name, owner="mover", ttl=60.0)
            if r.ok:
                break
            assert "grace" in r.error and time.time() < deadline, r.error
            time.sleep(0.3)
        wait_for(
            lambda: filers[second_idx][1].lock_ring.locks.status(),
            msg="lease on the runner-up",
        )

        # restart the rightful owner on the SAME grpc port; the mover
        # must hand the lease back
        f = Filer(MemoryStore(), master=f"localhost:{master.port}")
        fs = FilerServer(
            f,
            ip="localhost",
            port=free_port(),
            grpc_port=int(addrs[owner_idx].split(":")[1]),
            peers=[a for a in addrs if a != addrs[owner_idx]],
        )
        fs.lock_ring.probe_interval = 0.3
        fs.start()
        filers.append((f, fs))
        wait_for(
            lambda: [x for x in fs.lock_ring.locks.status() if x[0] == name],
            msg="lease transferred back to the rightful owner",
        )
        assert not [
            x
            for x in filers[second_idx][1].lock_ring.locks.status()
            if x[0] == name
        ]
        # the ORIGINAL token still renews after the transfer
        assert c.renew(name, "mover", r.token, ttl=30.0).ok
    finally:
        c.close()


def test_master_lease_api_rides_the_ring(tmp_path):
    """The master's AdminLock RPC becomes a CLIENT of the filer ring
    when dlm_filers is configured — the shell's cluster_guard flows
    through filers transparently."""
    mport = free_port()
    grpc_ports = [free_port() for _ in range(2)]
    addrs = [f"localhost:{p}" for p in grpc_ports]
    master = MasterServer(ip="localhost", port=mport, dlm_filers=addrs)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v")],
        master=f"localhost:{mport}",
        ip="localhost",
        port=free_port(),
        ec_backend="cpu",
    )
    vs.start()
    wait_for(lambda: master.topo.nodes, msg="vs registers")
    filers = []
    for i in range(2):
        f = Filer(MemoryStore(), master=f"localhost:{mport}")
        fs = FilerServer(
            f,
            ip="localhost",
            port=free_port(),
            grpc_port=grpc_ports[i],
            peers=[addrs[1 - i]],
        )
        fs.start()
        filers.append((f, fs))
    try:
        from seaweedfs_tpu.shell.commands import ShellEnv, run_command

        env = ShellEnv(f"localhost:{mport}")
        try:
            # a mutating shell command acquires the admin lease through
            # the master -> ring path
            out = run_command(env, "lock")
            assert "error" not in out, out
            # the lease is visible ON a filer, not in the master table
            assert not master.service.locks.status()
            assert any(fs.lock_ring.locks.status() for _, fs in filers)
            assert "admin" in run_command(env, "lock.status")
            run_command(env, "unlock")
            assert not any(fs.lock_ring.locks.status() for _, fs in filers)
        finally:
            env.close()
    finally:
        for f, fs in filers:
            fs.stop()
            f.close()
        vs.stop()
        master.stop()


def test_failover_grace_blocks_immediate_steal(ring):
    """Immediately after the owning filer dies, a FRESH acquire by a
    different owner is held back (the dead filer's lease table died
    with it); after the grace expires with no renewal, it succeeds."""
    master, filers, addrs = ring
    c = DlmClient(addrs)
    try:
        name = "jobs/graced"
        order = sorted(addrs, key=lambda m: _score(m, name), reverse=True)
        owner_idx = addrs.index(order[0])
        r = c.lock(name, owner="original", ttl=30.0)
        assert r.ok
        filers[owner_idx][1].stop()
        # allow liveness detection to notice the death
        time.sleep(0.8)
        r2 = c.lock(name, owner="thief", ttl=5.0)
        assert not r2.ok and "grace" in r2.error, (r2.ok, r2.error)
        # original never renews; after the grace the name is takeable
        wait_for(lambda: c.lock(name, owner="thief", ttl=5.0).ok, timeout=20)
    finally:
        c.close()
