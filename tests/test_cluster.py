"""Cluster slice integration: in-process master + volume servers on
loopback, driven through real gRPC/HTTP.

Modeled on the reference's in-process harness technique
(test/plugin_workers/framework.go) rather than process spawning — same
protocols, no subprocess overhead. Process-spawned tests live in
test_cluster_spawn.py.
"""

import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.client.operations import Operations
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.shell.commands import ShellEnv, run_command
from seaweedfs_tpu.storage.file_id import FileId


from conftest import allocate_port as free_port


@pytest.fixture
def cluster(tmp_path):
    mport = free_port()
    master = MasterServer(ip="localhost", port=mport)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path / f"v{i}")],
            master=f"localhost:{mport}",
            ip="localhost",
            port=free_port(),
            ec_backend="cpu",
        )
        vs.start()
        vols.append(vs)
    deadline = time.time() + 10
    while len(master.topo.nodes) < 2:
        if time.time() > deadline:
            raise TimeoutError("volume servers did not register")
        time.sleep(0.05)
    yield master, vols
    for vs in vols:
        vs.stop()
    master.stop()


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


def test_assign_upload_read_delete(cluster):
    master, vols = cluster
    ops = Operations(f"localhost:{master.port}")
    try:
        data = b"hello tpu world" * 1000
        fid = ops.upload(data, name="hello.bin", mime="text/plain")
        assert ops.read(fid) == data
        # read again via raw HTTP with the fid URL form
        f = FileId.parse(fid)
        loc = ops.master.lookup(f.volume_id)[0]
        r = requests.get(f"http://{loc.url}/{fid}")
        assert r.status_code == 200 and r.content == data
        assert r.headers["Content-Type"] == "text/plain"
        # wrong cookie 404s
        bad = f"{f.volume_id},{f.needle_id:x}{(f.cookie ^ 1):08x}"
        assert requests.get(f"http://{loc.url}/{bad}").status_code == 404
        ops.delete(fid)
        with pytest.raises(LookupError):
            ops.read(fid)
    finally:
        ops.close()


def test_replicated_write(cluster):
    master, vols = cluster
    ops = Operations(f"localhost:{master.port}")
    try:
        data = b"replicated-blob" * 100
        fid = ops.upload(data, replication="001")
        f = FileId.parse(fid)
        locs = ops.master.lookup(f.volume_id)
        assert len(locs) == 2, "001 => 2 copies on 2 servers"
        for loc in locs:
            r = requests.get(f"http://{loc.url}/{fid}")
            assert r.status_code == 200 and r.content == data
        # delete propagates to both replicas
        ops.delete(fid)
        for loc in locs:
            assert requests.get(f"http://{loc.url}/{fid}").status_code == 404
    finally:
        ops.close()


def test_blob_range_reads(cluster):
    master, vols = cluster
    ops = Operations(f"localhost:{master.port}")
    try:
        data = bytes(range(256)) * 100
        fid = ops.upload(data)
        loc = ops.master.lookup(FileId.parse(fid).volume_id)[0]
        r = requests.get(
            f"http://{loc.url}/{fid}", headers={"Range": "bytes=100-299"}
        )
        assert r.status_code == 206 and r.content == data[100:300]
        assert r.headers["Content-Range"] == f"bytes 100-299/{len(data)}"
        r = requests.get(
            f"http://{loc.url}/{fid}", headers={"Range": "bytes=-50"}
        )
        assert r.status_code == 206 and r.content == data[-50:]
        r = requests.get(
            f"http://{loc.url}/{fid}",
            headers={"Range": f"bytes={len(data) + 1}-"},
        )
        assert r.status_code == 416
    finally:
        ops.close()


def test_ec_delete_tombstone_fanout(cluster):
    """Deleting a blob on one EC shard holder must tombstone it on every
    holder — a decode or read served elsewhere must not resurrect it."""
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    try:
        blobs = {}
        for i in range(12):
            blobs[ops.upload(b"fanout-%d" % i * 500)] = None
        vid = FileId.parse(next(iter(blobs))).volume_id
        run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        wait_for(
            lambda: any(vid in n.ec_shards for n in master.topo.nodes.values())
        )
        # split shards across both nodes so two holders journal deletes
        run_command(env, "ec.balance")
        wait_for(
            lambda: sum(
                1 for n in master.topo.nodes.values() if vid in n.ec_shards
            )
            == 2
        )
        victim = next(iter(blobs))
        ops.delete(victim)
        time.sleep(0.5)
        # every holder's EcVolume must consider the needle deleted
        nid = FileId.parse(victim).needle_id
        holders = [
            vs for vs in vols if vs.store.find_ec_volume(vid) is not None
        ]
        assert len(holders) == 2
        for vs in holders:
            assert not vs.store.find_ec_volume(vid).has_needle(nid), (
                f"tombstone missing on {vs.port}"
            )
    finally:
        env.close()
        ops.close()


def test_heartbeat_liveness(cluster):
    master, vols = cluster
    vols[1].stop()
    wait_for(
        lambda: len(master.topo.nodes) == 1,
        msg="stopped node should be unregistered when its stream drops",
    )
    vols.pop()


def test_ec_encode_read_rebuild_decode(cluster, tmp_path):
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    rng = np.random.default_rng(1)
    try:
        blobs = {}
        for i in range(40):
            data = rng.integers(0, 256, int(rng.integers(1, 80_000)), np.uint8).tobytes()
            blobs[ops.upload(data, collection="")] = data
        vid = FileId.parse(next(iter(blobs))).volume_id

        out = run_command(env, f"ec.encode -volumeId {vid} -backend cpu")
        assert "generation" in out
        wait_for(
            lambda: any(
                vid in n.ec_shards for n in master.topo.nodes.values()
            ),
            msg="ec shards should register via heartbeat",
        )
        # source volume deleted; reads must come from EC shards
        wait_for(
            lambda: not any(
                vid in n.volumes for n in master.topo.nodes.values()
            ),
            msg="source volume should be deleted after ec.encode",
        )
        for fid, data in blobs.items():
            assert ops.read(fid) == data, "EC read path"

        # EC delete via HTTP -> .ecj journal
        victim = next(iter(blobs))
        ops.delete(victim)
        r = requests.get(
            f"http://{ops.master.lookup(vid, refresh=True)[0].url}/{victim}"
        )
        assert r.status_code == 404

        # damage two shards on disk, rebuild, then decode to normal volume
        out = run_command(env, f"ec.rebuild -volumeId {vid}")
        assert "rebuilt shards []" in out  # nothing missing yet

        out = run_command(env, f"ec.decode -volumeId {vid}")
        assert "decoded" in out
        wait_for(
            lambda: any(
                vid in n.volumes for n in master.topo.nodes.values()
            ),
            msg="decoded volume should register",
        )
        for fid, data in blobs.items():
            if fid == victim:
                continue
            assert ops.read(fid) == data, "post-decode read"
        assert requests.get(
            f"http://{ops.master.lookup(vid, refresh=True)[0].url}/{victim}"
        ).status_code == 404, "EC tombstone survives decode"
    finally:
        env.close()
        ops.close()


def test_ec_remote_shard_read(cluster):
    """Move some shards to the second server; reads on the first must
    fetch them over VolumeEcShardRead (or recover via RS)."""
    master, vols = cluster
    addr = f"localhost:{master.port}"
    ops = Operations(addr)
    env = ShellEnv(addr)
    rng = np.random.default_rng(2)
    try:
        blobs = {}
        for i in range(20):
            data = rng.integers(0, 256, 50_000, np.uint8).tobytes()
            blobs[ops.upload(data)] = data
        vid = FileId.parse(next(iter(blobs))).volume_id
        run_command(env, f"ec.encode -volumeId {vid} -backend cpu")

        # find holder, move shards 0-6 to the other node
        import grpc as grpc_mod

        from seaweedfs_tpu.pb import cluster_pb2 as pb
        from seaweedfs_tpu.pb import rpc as rpcmod

        holder = next(
            vs for vs in vols if vs.store.find_ec_volume(vid) is not None
        )
        other = next(vs for vs in vols if vs is not holder)
        move = list(range(7))
        with grpc_mod.insecure_channel(
            f"localhost:{other.grpc_port}"
        ) as ch:
            stub = rpcmod.volume_stub(ch)
            stub.VolumeEcShardsCopy(
                pb.EcShardsCopyRequest(
                    volume_id=vid,
                    shard_ids=move,
                    source_url=f"localhost:{holder.grpc_port}",
                    copy_ecx=True,
                    copy_ecj=True,
                    copy_vif=True,
                    copy_ecsum=True,
                ),
                timeout=120,
            )
            stub.VolumeEcShardsMount(
                pb.EcShardsMountRequest(volume_id=vid), timeout=30
            )
        with grpc_mod.insecure_channel(
            f"localhost:{holder.grpc_port}"
        ) as ch:
            stub = rpcmod.volume_stub(ch)
            # partial unmount: shards 7-13 must keep serving
            stub.VolumeEcShardsUnmount(
                pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=move),
                timeout=30,
            )
            stub.VolumeEcShardsDelete(
                pb.EcShardsDeleteRequest(volume_id=vid, shard_ids=move),
                timeout=30,
            )
        assert holder.store.find_ec_volume(vid) is not None, "partial unmount"
        assert holder.store.find_ec_volume(vid).shard_ids == list(range(7, 14))
        wait_for(
            lambda: len(master.topo.lookup_ec(vid)) == 14
            and all(
                locs for locs in master.topo.lookup_ec(vid).values()
            ),
            msg="all 14 shards should be registered across both nodes",
        )
        for fid, data in blobs.items():
            assert ops.read(fid) == data, "split-shard EC read"

        # decode with shards spread across nodes: shell collects first
        out = run_command(env, f"ec.decode -volumeId {vid}")
        assert "decoded" in out, out
        wait_for(
            lambda: any(vid in n.volumes for n in master.topo.nodes.values()),
            msg="decoded volume should register",
        )
        for fid, data in blobs.items():
            assert ops.read(fid) == data, "post-split-decode read"
    finally:
        env.close()
        ops.close()
