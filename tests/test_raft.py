"""Master HA: raft election, failover, replicated volume-id allocation,
and KeepConnected streaming sessions.

Reference models: weed/server/raft_hashicorp.go,
test/multi_master/failover_test.go, wdclient masterclient.go:483.
All masters run in-process on ephemeral ports (the suite's usual
in-process harness tier); election timeouts are shortened for CI.
"""

import time

import pytest

from seaweedfs_tpu.client.master_client import MasterClient
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.server.master import MasterServer

from conftest import allocate_port

FAST_ELECTION = (0.15, 0.35)


def _start_group(tmp_path, n=3):
    ports = [allocate_port() for _ in range(n)]
    peers = [f"localhost:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        d = tmp_path / f"m{i}"
        d.mkdir()
        m = MasterServer(
            ip="localhost",
            port=p,
            peers=peers,
            meta_dir=str(d),
            election_timeout=FAST_ELECTION,
            vacuum_interval=3600,
        )
        m.start()
        masters.append(m)
    return masters, peers


# Leader waits back to single-digit seconds (round-4 verdict): the
# timing-sensitivity that needed 30s lives in the deterministic fault
# harness now (test_raft_faults.py); these spawned-process tests only
# need a normal election round plus CI scheduling slack.
def _wait_leader(masters, timeout=15.0, exclude=()):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader and m not in exclude]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no unique leader elected")


@pytest.fixture
def group(tmp_path):
    masters, peers = _start_group(tmp_path)
    yield masters, peers
    for m in masters:
        try:
            m.stop()
        except Exception:
            pass


def test_single_leader_elected(group):
    masters, _ = group
    leader = _wait_leader(masters)
    # followers agree on who leads
    time.sleep(0.5)
    for m in masters:
        assert m.raft.leader == leader.node_id


def test_follower_redirects_assign(group):
    masters, peers = group
    leader = _wait_leader(masters)
    followers = [m for m in masters if m is not leader]
    deadline = time.time() + 5
    while time.time() < deadline and followers[0].raft.leader != leader.node_id:
        time.sleep(0.05)  # follower learns the leader from the first append
    resp = followers[0].service.Assign(
        pb.AssignRequest(count=1), None
    )
    assert resp.error.startswith("not leader")
    assert leader.node_id in resp.error


def test_replicated_volume_id_allocation(group):
    masters, _ = group
    leader = _wait_leader(masters)
    ids = [leader._alloc_volume_id() for _ in range(5)]
    assert ids == sorted(set(ids)), "allocation must be strictly increasing"
    # replicated: followers' state machines converge
    time.sleep(0.8)
    for m in masters:
        assert m.topo.max_volume_id >= ids[-1]


def test_leader_failover_and_no_id_reuse(group):
    """Kill the leader mid-operation: a new leader takes over within
    seconds and never re-issues an allocated volume id."""
    masters, _ = group
    leader = _wait_leader(masters)
    before = [leader._alloc_volume_id() for _ in range(3)]
    leader.stop()
    survivors = [m for m in masters if m is not leader]
    new_leader = _wait_leader(survivors, timeout=10)
    after = [new_leader._alloc_volume_id() for _ in range(3)]
    assert min(after) > max(before), f"id reuse after failover: {before} {after}"


def test_restart_preserves_allocation_state(tmp_path):
    """A full-group restart must not reuse volume ids (durable log)."""
    masters, peers = _start_group(tmp_path)
    try:
        leader = _wait_leader(masters)
        issued = [leader._alloc_volume_id() for _ in range(4)]
    finally:
        for m in masters:
            m.stop()
    # restart the same group over the same meta dirs
    masters2 = []
    for i, p in enumerate(int(x.split(":")[1]) for x in peers):
        m = MasterServer(
            ip="localhost",
            port=p,
            peers=peers,
            meta_dir=str(tmp_path / f"m{i}"),
            election_timeout=FAST_ELECTION,
            vacuum_interval=3600,
        )
        m.start()
        masters2.append(m)
    try:
        leader2 = _wait_leader(masters2, timeout=10)
        fresh = leader2._alloc_volume_id()
        assert fresh > max(issued), f"volume id reused after restart: {fresh} <= {max(issued)}"
    finally:
        for m in masters2:
            m.stop()


def test_client_follows_leader(group):
    masters, peers = group
    _wait_leader(masters)
    mc = MasterClient(",".join(peers), keepconnected=False)
    try:
        st = mc.raft_status() if mc._resolve_leader() else None
        assert st is None or st.role in ("leader", "follower")
        # statistics round-trips regardless of which master we guessed
        stats = mc.statistics()
        assert stats.node_count == 0
    finally:
        mc.close()


def test_keepconnected_session_and_failover(group, tmp_path):
    """A KeepConnected client sees volume deltas from the leader and
    re-homes after failover; writes resume within seconds."""
    from seaweedfs_tpu.server.volume_server import VolumeServer

    masters, peers = group
    leader = _wait_leader(masters)
    vdir = tmp_path / "vol"
    vdir.mkdir()
    vs = VolumeServer(
        [str(vdir)], master=",".join(peers), ip="localhost",
        port=allocate_port(),
    )
    vs.start()
    mc = MasterClient(",".join(peers))
    try:
        # volume server finds the leader and registers (slack is for
        # full-suite CPU starvation of the spawned threads, not raft)
        deadline = time.time() + 20
        while time.time() < deadline and not leader.topo.nodes:
            time.sleep(0.05)
        assert leader.topo.nodes, "volume server never registered with leader"

        r = mc.assign()
        vid = int(r.fid.split(",")[0])
        # the streaming session learns the new volume's location
        # (generous: full-suite runs contend heavily for CPU)
        deadline = time.time() + 20
        locs = []
        while time.time() < deadline:
            if mc._synced.is_set():
                with mc._lock:
                    held = mc._vidmap.get(vid)
                if held:
                    locs = list(held.values())
                    break
            time.sleep(0.05)
        assert locs and locs[0].url == f"localhost:{vs.port}"

        # kill the leader: assigns keep working via the new leader
        leader.stop()
        survivors = [m for m in masters if m is not leader]
        _wait_leader(survivors, timeout=10)
        deadline = time.time() + 10
        last = None
        while time.time() < deadline:
            try:
                r2 = mc.assign()
                break
            except Exception as e:  # noqa: BLE001 — retry until failover settles
                last = e
                time.sleep(0.2)
        else:
            raise AssertionError(f"writes never resumed after failover: {last}")
        assert r2.fid
    finally:
        mc.close()
        vs.stop()


def test_membership_grow_1_to_3_and_failover(tmp_path):
    """VERDICT r3 #7 done-criterion: grow a single master to a 3-node
    group LIVE via AddServer, then kill the leader — the grown group
    fails over and allocation state survives."""
    ports = [allocate_port() for _ in range(3)]
    addrs = [f"localhost:{p}" for p in ports]
    m0 = MasterServer(
        ip="localhost", port=ports[0], peers=[addrs[0]],
        meta_dir=str(tmp_path / "m0"), election_timeout=FAST_ELECTION,
        vacuum_interval=3600,
    )
    (tmp_path / "m0").mkdir()
    m0.start()
    masters = [m0]
    try:
        leader = _wait_leader(masters)
        ids = [leader.raft.propose("alloc_volume_id", 0) for _ in range(5)]
        assert ids == sorted(set(ids))

        # grow one at a time; each joiner starts pointed at the group
        for i in (1, 2):
            d = tmp_path / f"m{i}"
            d.mkdir()
            m = MasterServer(
                ip="localhost", port=ports[i], peers=addrs[: i + 1],
                meta_dir=str(d), election_timeout=FAST_ELECTION,
                vacuum_interval=3600,
            )
            m.start()
            masters.append(m)
            members = _wait_leader(
                masters, timeout=10, exclude=masters[1:]
            ).raft.add_server(addrs[i])
            assert addrs[i] in members
            # the joiner converges (gets the log/snapshot)
            deadline = time.time() + 10
            while time.time() < deadline:
                if m.raft.last_applied >= masters[0].raft.last_applied:
                    break
                time.sleep(0.05)

        leader = _wait_leader(masters, timeout=10)
        assert sorted({leader.raft.node_id, *leader.raft.peers}) == sorted(addrs)

        # kill the leader: the grown group elects a new one, ids monotonic
        leader.stop()
        rest = [m for m in masters if m is not leader]
        new_leader = _wait_leader(rest, timeout=10)
        nid = new_leader.raft.propose("alloc_volume_id", 0)
        assert nid > max(ids)
    finally:
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass


def test_remove_server_shrinks_group(group):
    masters, peers = group
    leader = _wait_leader(masters)
    victim = next(m for m in masters if m is not leader)
    members = leader.raft.remove_server(victim.raft.node_id)
    assert victim.raft.node_id not in members

    # The victim cannot know it was removed (the leader stops
    # replicating to it), so it will keep campaigning — the vote
    # disruption guard (§4.2.3) must keep the remaining group STABLE:
    # same leader, working proposals, victim never elected.
    term_before = leader.raft.current_term
    deadline = time.time() + 3.0
    while time.time() < deadline:
        assert not victim.raft.is_leader
        time.sleep(0.1)
    ldr = _wait_leader([m for m in masters if m is not victim])
    assert ldr is leader and leader.raft.current_term == term_before
    assert ldr.raft.propose("alloc_volume_id", 0) > 0


def test_log_compaction_bounds_disk(tmp_path):
    """VERDICT r3 #7: the persisted log must stay bounded under load,
    and a restart from the compacted file must preserve allocation."""
    import os

    from seaweedfs_tpu.server.raft import RaftNode

    d = tmp_path / "r"
    d.mkdir()
    state = {"v": 0}

    def apply(kind, value):
        state["v"] = max(state["v"], value) + 1
        return state["v"]

    n = RaftNode(
        "localhost:19991", [], state_dir=str(d),
        apply_fn=apply, compact_threshold=64,
        snapshot_fn=lambda: dict(state),
        restore_fn=lambda s: state.update(s),
    )
    n.start()
    try:
        last = 0
        for _ in range(500):
            last = n.propose("alloc_volume_id", 0)
        assert last >= 500
        # in-memory log and on-disk file both bounded
        assert len(n.log) <= 64 + 2
        size = os.path.getsize(str(d / "raft.jsonl"))
        assert size < 64 * 200, size  # ~bounded by the kept tail
    finally:
        n.stop()

    # restart from the compacted file: allocation continues, no reuse
    state2 = {"v": 0}

    def apply2(kind, value):
        state2["v"] = max(state2["v"], value) + 1
        return state2["v"]

    n2 = RaftNode(
        "localhost:19991", [], state_dir=str(d),
        apply_fn=apply2, compact_threshold=64,
        snapshot_fn=lambda: dict(state2),
        restore_fn=lambda s: state2.update(s),
    )
    n2.start()
    try:
        nxt = n2.propose("alloc_volume_id", 0)
        assert nxt > last
    finally:
        n2.stop()


def test_snapshot_install_catches_up_fresh_follower(tmp_path):
    """A follower joining AFTER compaction must be caught up via
    InstallSnapshot (its entries no longer exist in the leader log)."""
    ports = [allocate_port() for _ in range(2)]
    addrs = [f"localhost:{p}" for p in ports]
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    a = MasterServer(
        ip="localhost", port=ports[0], peers=[addrs[0]],
        meta_dir=str(tmp_path / "a"), election_timeout=FAST_ELECTION,
        vacuum_interval=3600,
    )
    a.start()
    b = None
    try:
        leader = _wait_leader([a])
        a.raft.compact_threshold = 32
        last = 0
        for _ in range(200):
            last = a.raft.propose("alloc_volume_id", 0)
        assert a.raft.snap_index > 0  # compaction actually happened

        b = MasterServer(
            ip="localhost", port=ports[1], peers=addrs,
            meta_dir=str(tmp_path / "b"), election_timeout=FAST_ELECTION,
            vacuum_interval=3600,
        )
        b.start()
        a.raft.add_server(addrs[1])
        deadline = time.time() + 15
        while time.time() < deadline:
            # full catch-up: the snapshot AND the remaining log tail
            if b.raft.last_applied >= a.raft.last_applied:
                break
            time.sleep(0.05)
        assert b.raft.last_applied >= a.raft.snap_index > 0
        assert b.topo.max_volume_id >= last
    finally:
        if b:
            b.stop()
        a.stop()


def test_remove_dead_member_from_two_node_group(tmp_path):
    """Config-at-append semantics: a 2-node group whose follower died
    must still be able to remove it (quorum counts the NEW set)."""
    ports = [allocate_port() for _ in range(2)]
    addrs = [f"localhost:{p}" for p in ports]
    masters = []
    for i in (0, 1):
        d = tmp_path / f"m{i}"
        d.mkdir()
        m = MasterServer(
            ip="localhost", port=ports[i], peers=addrs,
            meta_dir=str(d), election_timeout=FAST_ELECTION,
            vacuum_interval=3600,
        )
        m.start()
        masters.append(m)
    try:
        leader = _wait_leader(masters)
        dead = next(m for m in masters if m is not leader)
        dead.stop()

        members = leader.raft.remove_server(dead.raft.node_id)
        assert members == [leader.raft.node_id]
        # now a single-node group: proposals commit alone
        assert leader.raft.propose("alloc_volume_id", 0) > 0
    finally:
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass
