"""Master HA: raft election, failover, replicated volume-id allocation,
and KeepConnected streaming sessions.

Reference models: weed/server/raft_hashicorp.go,
test/multi_master/failover_test.go, wdclient masterclient.go:483.
All masters run in-process on ephemeral ports (the suite's usual
in-process harness tier); election timeouts are shortened for CI.
"""

import time

import pytest

from seaweedfs_tpu.client.master_client import MasterClient
from seaweedfs_tpu.pb import cluster_pb2 as pb
from seaweedfs_tpu.server.master import MasterServer

from conftest import allocate_port

FAST_ELECTION = (0.15, 0.35)


def _start_group(tmp_path, n=3):
    ports = [allocate_port() for _ in range(n)]
    peers = [f"localhost:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        d = tmp_path / f"m{i}"
        d.mkdir()
        m = MasterServer(
            ip="localhost",
            port=p,
            peers=peers,
            meta_dir=str(d),
            election_timeout=FAST_ELECTION,
            vacuum_interval=3600,
        )
        m.start()
        masters.append(m)
    return masters, peers


def _wait_leader(masters, timeout=10.0, exclude=()):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader and m not in exclude]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no unique leader elected")


@pytest.fixture
def group(tmp_path):
    masters, peers = _start_group(tmp_path)
    yield masters, peers
    for m in masters:
        try:
            m.stop()
        except Exception:
            pass


def test_single_leader_elected(group):
    masters, _ = group
    leader = _wait_leader(masters)
    # followers agree on who leads
    time.sleep(0.5)
    for m in masters:
        assert m.raft.leader == leader.node_id


def test_follower_redirects_assign(group):
    masters, peers = group
    leader = _wait_leader(masters)
    followers = [m for m in masters if m is not leader]
    deadline = time.time() + 5
    while time.time() < deadline and followers[0].raft.leader != leader.node_id:
        time.sleep(0.05)  # follower learns the leader from the first append
    resp = followers[0].service.Assign(
        pb.AssignRequest(count=1), None
    )
    assert resp.error.startswith("not leader")
    assert leader.node_id in resp.error


def test_replicated_volume_id_allocation(group):
    masters, _ = group
    leader = _wait_leader(masters)
    ids = [leader._alloc_volume_id() for _ in range(5)]
    assert ids == sorted(set(ids)), "allocation must be strictly increasing"
    # replicated: followers' state machines converge
    time.sleep(0.8)
    for m in masters:
        assert m.topo.max_volume_id >= ids[-1]


def test_leader_failover_and_no_id_reuse(group):
    """Kill the leader mid-operation: a new leader takes over within
    seconds and never re-issues an allocated volume id."""
    masters, _ = group
    leader = _wait_leader(masters)
    before = [leader._alloc_volume_id() for _ in range(3)]
    leader.stop()
    survivors = [m for m in masters if m is not leader]
    new_leader = _wait_leader(survivors, timeout=15)
    after = [new_leader._alloc_volume_id() for _ in range(3)]
    assert min(after) > max(before), f"id reuse after failover: {before} {after}"


def test_restart_preserves_allocation_state(tmp_path):
    """A full-group restart must not reuse volume ids (durable log)."""
    masters, peers = _start_group(tmp_path)
    try:
        leader = _wait_leader(masters)
        issued = [leader._alloc_volume_id() for _ in range(4)]
    finally:
        for m in masters:
            m.stop()
    # restart the same group over the same meta dirs
    masters2 = []
    for i, p in enumerate(int(x.split(":")[1]) for x in peers):
        m = MasterServer(
            ip="localhost",
            port=p,
            peers=peers,
            meta_dir=str(tmp_path / f"m{i}"),
            election_timeout=FAST_ELECTION,
            vacuum_interval=3600,
        )
        m.start()
        masters2.append(m)
    try:
        leader2 = _wait_leader(masters2, timeout=15)
        fresh = leader2._alloc_volume_id()
        assert fresh > max(issued), f"volume id reused after restart: {fresh} <= {max(issued)}"
    finally:
        for m in masters2:
            m.stop()


def test_client_follows_leader(group):
    masters, peers = group
    _wait_leader(masters)
    mc = MasterClient(",".join(peers), keepconnected=False)
    try:
        st = mc.raft_status() if mc._resolve_leader() else None
        assert st is None or st.role in ("leader", "follower")
        # statistics round-trips regardless of which master we guessed
        stats = mc.statistics()
        assert stats.node_count == 0
    finally:
        mc.close()


def test_keepconnected_session_and_failover(group, tmp_path):
    """A KeepConnected client sees volume deltas from the leader and
    re-homes after failover; writes resume within seconds."""
    from seaweedfs_tpu.server.volume_server import VolumeServer

    masters, peers = group
    leader = _wait_leader(masters)
    vdir = tmp_path / "vol"
    vdir.mkdir()
    vs = VolumeServer(
        [str(vdir)], master=",".join(peers), ip="localhost",
        port=allocate_port(),
    )
    vs.start()
    mc = MasterClient(",".join(peers))
    try:
        # volume server finds the leader and registers
        deadline = time.time() + 10
        while time.time() < deadline and not leader.topo.nodes:
            time.sleep(0.05)
        assert leader.topo.nodes, "volume server never registered with leader"

        r = mc.assign()
        vid = int(r.fid.split(",")[0])
        # the streaming session learns the new volume's location
        deadline = time.time() + 10
        locs = []
        while time.time() < deadline:
            if mc._synced.is_set():
                with mc._lock:
                    held = mc._vidmap.get(vid)
                if held:
                    locs = list(held.values())
                    break
            time.sleep(0.05)
        assert locs and locs[0].url == f"localhost:{vs.port}"

        # kill the leader: assigns keep working via the new leader
        leader.stop()
        survivors = [m for m in masters if m is not leader]
        _wait_leader(survivors, timeout=15)
        deadline = time.time() + 20
        last = None
        while time.time() < deadline:
            try:
                r2 = mc.assign()
                break
            except Exception as e:  # noqa: BLE001 — retry until failover settles
                last = e
                time.sleep(0.2)
        else:
            raise AssertionError(f"writes never resumed after failover: {last}")
        assert r2.fid
    finally:
        mc.close()
        vs.stop()
